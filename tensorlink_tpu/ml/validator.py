"""DistributedValidator — the ML-process planner on a validator node.

Reference: ml/validator.py:122 (``DistributedValidator.check_node`` polling
``get_jobs`` every tick, inspect_model → ModelParser → send_job_request).
Here job requests arrive as work events; planning = resolve the model config
(preset registry or HF checkpoint config) + ``plan_sharding`` over the live
worker capacities, then hand the job back to the network process to recruit
(roles.py `cmd_create_job`).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import uuid
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

from tensorlink_tpu.core.logging import get_logger


@dataclass
class HostedJob:
    """A model the validator serves through the HTTP API (reference hosted
    jobs, ml/validator.py:901-1041)."""

    name: str
    status: str = "loading"  # loading | ready | failed
    model: Any = None  # DistributedModel
    tokenizer: Any = None  # TokenizerAdapter
    cfg: Any = None
    seq_len: int = 2048
    error: str = ""
    t0: float = field(default_factory=time.time)
    lock: threading.Lock = field(default_factory=threading.Lock)
    # dynamic request batching (ml/batching.py): concurrent API requests
    # coalesce into one batched decode instead of queueing on the lock
    batcher: Any = None
    # -- fleet serving (tensorlink_tpu/fleet, docs/SERVING.md "Fleet
    # serving"): N replicas of this model behind a cache-/SLO-aware
    # router. ``replicas`` holds [{rid, model, batcher, job_id}];
    # ``model``/``batcher`` above stay replica 0 (the single-replica
    # path is byte-identical when the fleet knobs are off).
    replicas: list = field(default_factory=list)
    router: Any = None  # FleetRouter when > 1 replica hosted
    autopilot: Any = None  # FleetAutopilot when enabled


class DistributedValidator:
    def __init__(self, node):
        self.node = node
        self.bridge = node.bridge
        self.log = get_logger(f"ml.validator{node.config.duplicate}")
        # model demand tracking, persisted across restarts (reference
        # logs/models.json, ml/utils.py:663-674 + ml/validator.py:169-365)
        self._demand_path = Path(node.config.log_dir) / "models.json"
        self._demand_lock = threading.Lock()
        self._demand_written = 0.0
        self._demand_flush_s = 5.0  # debounce between disk writes
        self.demand: dict[str, int] = self._load_demand()
        self.hosted: dict[str, HostedJob] = {}
        self._host_lock = threading.Lock()
        # surfaced by /healthz for load balancers / the cluster router
        # (ROADMAP item 3): a draining validator keeps serving in-flight
        # work but should stop receiving new placements
        self.draining = False
        # control-plane crash safety (core/journal.py, docs/FAILURE_MODEL
        # "Control plane"): the write-ahead journal this validator records
        # hosting / admissions / tickets / autopilot intents into, and the
        # recovery-window flag /healthz + the API surface while recover()
        # replays it (api/server.py answers 503 + Retry-After meanwhile)
        self.recovering = False
        self._journal_errors = 0
        self.journal = None
        ml_cfg = node.config.ml
        if getattr(ml_cfg, "journal", True):
            try:
                from tensorlink_tpu.core.journal import ControlJournal

                self.journal = ControlJournal(
                    Path(node.config.log_dir) / "control_journal.jsonl",
                    flush_every=int(
                        getattr(ml_cfg, "journal_flush_every", 16)
                    ),
                    flush_s=float(getattr(ml_cfg, "journal_flush_s", 0.05)),
                )
            except OSError as e:
                # no journal ≠ no serving: run exactly as before PR 16,
                # just without crash recovery — and say so loudly
                self.log.warning("control journal unavailable: %s", e)
        if node.config.ml.autoload_default_models:
            threading.Thread(
                target=self._autoload_defaults,
                name="ml-autoload",
                daemon=True,
            ).start()

    # -- demand persistence / default-model auto-load -------------------
    def _load_demand(self) -> dict[str, int]:
        try:
            data = json.loads(self._demand_path.read_text())
            if not isinstance(data, dict):
                return {}
            return {str(k): int(v) for k, v in data.items()}
        except Exception:  # stats must never block startup
            return {}

    def _bump_demand(self, name: str) -> None:
        with self._demand_lock:
            self.demand[name] = self.demand.get(name, 0) + 1
            now = time.monotonic()
            if now - self._demand_written < self._demand_flush_s:
                return  # debounce: no disk write per inference request
            self._demand_written = now
            snapshot = dict(self.demand)
        try:
            self._demand_path.parent.mkdir(parents=True, exist_ok=True)
            tmp = self._demand_path.with_suffix(".tmp")
            tmp.write_text(json.dumps(snapshot))
            tmp.replace(self._demand_path)
        except OSError as e:
            # stats persistence must never break planning — but say so
            self.log.debug("demand persistence failed: %s", e)

    def _autoload_defaults(self) -> None:
        """Host each configured default model so the API serves it without a
        first-request cold start (reference DEFAULT_MODELS auto-load)."""
        from tensorlink_tpu.core.config import DEFAULT_CONFIG

        for name in DEFAULT_CONFIG.get("default_models", []):
            try:
                job = self.host_model(name)
                self.log.info(
                    "default model %s: %s", name, job.status
                )
            except Exception:
                self.log.exception("default model %s failed to host", name)

    # -- control-plane journal (crash safety) ----------------------------
    # every helper swallows journal failures: the journal is a durability
    # layer, and a full disk / injected journal.write fault must degrade
    # to "no crash recovery", never to a failed request
    def _journal_rec(self, kind: str, data: dict | None = None, *,
                     flush: bool = False):
        j = self.journal
        if j is None:
            return None
        try:
            return j.append(kind, data, flush=flush)
        except Exception as e:
            self._journal_errors += 1
            self.log.debug("journal write failed (%s): %s", kind, e)
            return None

    def _jintent(self, kind: str, data: dict | None = None):
        j = self.journal
        if j is None:
            return None
        try:
            return j.intent(kind, data)
        except Exception as e:
            self._journal_errors += 1
            self.log.debug("journal intent failed (%s): %s", kind, e)
            return None

    def _jcommit(self, iid, data: dict | None = None) -> None:
        if self.journal is None or iid is None:
            return
        try:
            self.journal.commit(iid, data)
        except Exception as e:
            self._journal_errors += 1
            self.log.debug("journal commit failed: %s", e)

    def _jabort(self, iid, data: dict | None = None) -> None:
        if self.journal is None or iid is None:
            return
        try:
            self.journal.abort(iid, data)
        except Exception as e:
            self._journal_errors += 1
            self.log.debug("journal abort failed: %s", e)

    def _journal_replica(self, job: HostedJob, rep: dict) -> None:
        self._journal_rec("replica_up", {
            "name": job.name, "rid": rep["rid"], "job_id": rep["job_id"],
            "attach": rep.get("attach") or {},
            "spec": rep.get("spec") or {},
            "batch": rep.get("batch", 1), "seed": rep.get("seed", 0),
            "seq_len": job.seq_len,
        }, flush=True)

    def _note_admit_seed(self, jrid: str, seed: int) -> None:
        """ContinuousBatcher.on_admit hook: pair the admission record with
        the decode seed the batcher assigned (write-ahead — called before
        dispatch), completing the journal's replayable admission tuple."""
        self._journal_rec("seed", {"jrid": jrid, "seed": int(seed)})

    def run(self) -> None:
        # a RESTARTED validator replays its journal before serving (a
        # fresh one no-ops in microseconds: empty journal, nothing live).
        # Failures degrade to a cold start — recovery must never wedge
        # the work loop.
        try:
            self.recover()
        except Exception:
            self.log.exception("startup recovery failed — cold start")
        while True:
            try:
                item = self.bridge.get_work(timeout=1.0)
            except EOFError:
                # the bridge ring closed under us — a crashed/stopped node;
                # exit the loop instead of dying with an unhandled thread
                # exception (the chaos suite kills validators mid-decode)
                self.log.info("work bridge closed — validator loop exiting")
                return
            if item is None:
                continue
            kind, payload = item
            if kind == "_stop":
                return
            try:
                if kind == "job_req":
                    self._plan_job(payload)
                elif kind == "token":
                    pass  # API streaming relay lands here in the serving layer
                else:
                    self.log.warning("unhandled work kind %s", kind)
            except Exception:
                self.log.exception("work %s failed", kind)
                if kind == "job_req":
                    self.bridge.request(
                        "decline_job",
                        {"req_id": payload.get("req_id"), "error": "planning failed"},
                    )

    # -- planning -------------------------------------------------------
    def _resolve_config(self, model_spec: dict):
        """Model identity → ModelConfig. Accepts an explicit config dict, a
        preset name (registry), or a checkpoint dir with an HF config.json
        (reference resolves HF names via AutoConfig, ml/validator.py:367)."""
        from tensorlink_tpu.models.base import ModelConfig
        from tensorlink_tpu.models.registry import config_presets

        if model_spec.get("config"):
            return ModelConfig.from_json(model_spec["config"])
        name = model_spec.get("name", "")
        presets = config_presets()
        if name in presets:
            return presets[name]
        if model_spec.get("ckpt"):
            import json

            from tensorlink_tpu.engine.loader import resolve_checkpoint
            from tensorlink_tpu.models.registry import config_from_hf

            ckpt = resolve_checkpoint(model_spec["ckpt"], config_only=True)
            return config_from_hf(
                json.loads((ckpt / "config.json").read_text())
            )
        raise ValueError(f"cannot resolve model {name!r}")

    def _plan_and_create(
        self,
        model_spec: dict,
        cfg,
        *,
        batch: int = 1,
        seq_len: int = 2048,
        training: bool = False,
        n_micro=None,
        mesh_hints: dict | None = None,
        req_id: str | None = None,
        user_id: str | None = None,
    ) -> dict:
        """Shared plan→recruit path for user jobs and hosted models: live
        worker capacities → plan_sharding → create_job on the net process.
        Returns the create_job result. Raises AssignmentError on no fit."""
        from tensorlink_tpu.parallel.planner import (
            AssignmentError,
            WorkerCapacity,
            plan_sharding,
        )

        name = model_spec.get("name", "")
        stats = self.bridge.request("stats_workers", timeout=15.0)
        # -- disaggregated prefill/decode placement (docs/SERVING.md) ----
        # Workers advertise a serving_role with every stats sweep. When a
        # SERVING job is planned against a pool that contains decode-role
        # workers, those are reserved as handoff destinations: the job's
        # stages (= the admission point new requests hit) land on
        # prefill/mixed workers, and each prefill-role worker the plan
        # touches gets the decode-pool membership pushed at recruit time
        # (roles.py cmd_create_job → HANDOFF frames) so it can ship every
        # completed prefill there. Training jobs, pools with no decode
        # workers, and models that can never hand off (the paged slot
        # engine refuses them, or continuous batching is off — either way
        # they serve through the windowed batcher, which has no
        # prefill→decode boundary) place exactly as before: reserving
        # decode workers for them would only shrink the plannable pool.
        from tensorlink_tpu.engine.continuous import paged_unsupported

        roles = {
            s.get("id"): str(s.get("serving_role") or "mixed")
            for s in stats
        }
        # explicit tensor parallelism (docs/SHARDING.md): each worker's
        # advertised serving shard degree. A tp=N worker is ONE placement
        # unit over N chips (its continuous engine runs a single sharded
        # program), so the plan carries the degree through to consumers
        # (router placement, /healthz) rather than splitting the mesh. A
        # worker advertising more tp than devices would refuse TP at
        # hosting time and serve static — surface that misfit here, at
        # plan time, where the operator is looking.
        tp_degrees = {
            s.get("id"): int(s.get("tensor_parallel", 1) or 1)
            for s in stats
        }
        for s in stats:
            tp_adv = tp_degrees.get(s.get("id"), 1)
            if tp_adv > 1 and tp_adv > int(s.get("n_devices", 1)):
                self.log.warning(
                    "worker %s advertises tensor_parallel=%d but only %d "
                    "device(s) — its engines will fall back to static "
                    "batching", s.get("id"), tp_adv,
                    int(s.get("n_devices", 1)),
                )
        decode_pool = [
            {"id": s["id"], "addr": list(s["addr"])}
            for s in stats
            if roles.get(s.get("id")) == "decode" and s.get("addr")
        ]
        if decode_pool and not (
            self.node.config.ml.continuous_batching
            and paged_unsupported(cfg) is None
        ):
            decode_pool = []
        placement = stats
        if not training and decode_pool:
            non_decode = [
                s for s in stats if roles.get(s.get("id")) != "decode"
            ]
            if non_decode:
                placement = non_decode
            else:
                # every worker is decode-role: nothing to disaggregate
                # against — serve single-pool rather than fail planning
                decode_pool = []
        def _plan(pool):
            workers = [
                WorkerCapacity(
                    node_id=s["id"],
                    hbm_bytes=float(
                        s.get("free_bytes", s.get("hbm_bytes", 0.0))
                    ),
                    n_devices=int(s.get("n_devices", 1)),
                    slice_id=str(s.get("slice_id", "") or ""),
                )
                for s in pool
            ]
            return plan_sharding(
                cfg, workers, model_name=name, batch=batch,
                seq_len=seq_len, training=training, n_micro=n_micro,
                mesh_hints=mesh_hints,
                merge_co_slice=self.node.config.ml.co_slice_planning,
            )

        try:
            plan = _plan(placement)
        except AssignmentError:
            if placement is stats:
                raise
            # the prefill/mixed subset alone can't fit the model (the
            # reserved decode workers hold the missing capacity): a
            # single-pool placement over the FULL pool beats a failed
            # host — disaggregation is a latency optimization, not worth
            # declining a job the cluster can serve
            self.log.warning(
                "disaggregated placement for %s does not fit the "
                "prefill/mixed subset; falling back to single-pool "
                "placement over all %d workers", name, len(stats),
            )
            decode_pool = []
            plan = _plan(stats)
        total_layers = max(cfg.n_layers, 1)
        job = {
            "job_id": uuid.uuid4().hex,
            "model": model_spec,
            "plan": plan.to_json(),
            "stage_bytes": {
                s.worker_id: plan.estimate.total
                * (s.layer_hi - s.layer_lo) / total_layers
                for s in plan.stages
            },
        }
        if not training and decode_pool:
            handoff_push = {
                s.worker_id: decode_pool
                for s in plan.stages
                if roles.get(s.worker_id) == "prefill"
            }
            if handoff_push:
                job["handoff_push"] = handoff_push
                self.log.info(
                    "disaggregated placement for %s: %d prefill worker(s) "
                    "→ %d decode worker(s)",
                    name, len(handoff_push), len(decode_pool),
                )
        result = self.bridge.request(
            "create_job",
            {"req_id": req_id, "user_id": user_id, "job": job},
            timeout=30.0,
        )
        # the planned workers' advertised pool roles, for consumers that
        # must know the shape BEFORE any traffic produces a serving
        # snapshot (/healthz serving_modes on a fresh replica)
        result["serving_roles"] = {
            s.worker_id: roles.get(s.worker_id, "mixed")
            for s in plan.stages
        }
        # ...and their serving shard degrees, same reasoning: a router
        # scoring this replica needs to know a tp=N worker is one engine
        # over N chips before the first serving snapshot exists
        result["tensor_parallel"] = {
            s.worker_id: tp_degrees.get(s.worker_id, 1)
            for s in plan.stages
        }
        self.log.info(
            "job %s (%s): accepted=%s stages=%d",
            job["job_id"][:8], name, result.get("accepted"), plan.n_stages,
        )
        return result

    def _plan_job(self, p: dict) -> None:
        from tensorlink_tpu.parallel.planner import AssignmentError

        spec = p["spec"]
        model_spec = dict(spec.get("model", {}))
        name = model_spec.get("name", "")
        self._bump_demand(name)
        try:
            cfg = self._resolve_config(model_spec)
        except Exception as e:
            self.bridge.request(
                "decline_job", {"req_id": p["req_id"], "error": str(e)}
            )
            return
        model_spec["config"] = cfg.to_json()
        try:
            self._plan_and_create(
                model_spec, cfg,
                batch=int(spec.get("batch", 1)),
                seq_len=int(spec.get("seq_len", 2048)),
                training=bool(spec.get("training", False)),
                n_micro=spec.get("n_micro"),
                mesh_hints=spec.get("parallelism"),
                req_id=p["req_id"],
                user_id=p.get("user_id"),
            )
        except AssignmentError as e:
            self.log.info("declining job %s: %s", name, e)
            self.bridge.request(
                "decline_job", {"req_id": p["req_id"], "error": str(e)}
            )

    # ------------------------------------------------------------------
    # hosted models (reference _initialize_hosted_job → DistributedModel,
    # ml/validator.py:901-1041) — the validator is its own "user"
    # ------------------------------------------------------------------
    def host_model(
        self,
        name: str,
        *,
        batch: int = 1,
        seq_len: int | None = None,
        config: dict | None = None,
        seed: int = 0,
        quant: str | None = None,
    ) -> HostedJob:
        """Plan, recruit, and attach a model for API serving. Synchronous and
        thread-safe; callable from API handler threads. ``quant`` ("int8" /
        "int8+kv") serves the model weight-only-quantized on the paged
        engine — weights and KV shrink together (docs/SERVING.md
        "Quantized KV")."""
        with self._host_lock:
            job = self.hosted.get(name)
            if job is not None and job.status in ("loading", "ready"):
                return job
            job = HostedJob(name=name)
            self.hosted[name] = job
        try:
            self._do_host(
                job, batch=batch, seq_len=seq_len, config=config, seed=seed,
                quant=quant,
            )
        except Exception as e:
            job.status = "failed"
            job.error = f"{type(e).__name__}: {e}"
            self.log.exception("hosting %s failed", name)
        return job

    def _build_replica(
        self, job: HostedJob, model_spec: dict, cfg, *, batch, seed,
    ) -> tuple:
        """Plan, recruit, attach, and wrap ONE serving replica of
        ``job``'s model: (model, batcher, job_id, attach) — ``attach`` is
        the JSON-safe job result a recovered validator replays to
        re-attach this replica without rebuilding it
        (DistributedModel.from_job(..., attach_only=True)). Raises on
        failure after releasing whatever recruiting reserved."""
        from tensorlink_tpu.ml.module import DistributedModel

        result = self._plan_and_create(
            model_spec, cfg, batch=batch, seq_len=job.seq_len, training=False,
        )
        if not result.get("accepted"):
            raise RuntimeError(f"recruiting failed: {result.get('declined')}")
        try:
            model = DistributedModel.from_job(
                self.node, result, seq_len=job.seq_len, seed=seed,
            )
        except Exception:
            # release what recruiting reserved — workers that accepted would
            # otherwise keep the reservation forever (same leak the recruit
            # decline path guards against, roles.py cmd_create_job)
            try:
                self.bridge.request(
                    "shutdown_job", {"job_id": result["job_id"]}, timeout=15.0
                )
            except Exception:
                self.log.warning("rollback of job %s failed", result["job_id"][:8])
            raise
        batcher = self._make_batcher(
            job, model, cfg, result.get("serving_roles") or {},
        )
        self.log.info(
            "replica of %s ready (%d stages, job %s)",
            job.name, len(result["plan"]["stages"]), result["job_id"][:8],
        )
        attach = {
            k: result[k]
            for k in ("job_id", "plan", "model", "workers", "serving_roles")
            if k in result
        }
        return model, batcher, result["job_id"], attach

    def _make_batcher(self, job: HostedJob, model, cfg, serving_roles: dict):
        """ONE construction site for a replica's batcher — first host and
        crash-recovery re-attach must pick the same kind with the same
        knobs or replayed replicas would silently change behavior."""
        from tensorlink_tpu.ml.batching import ContinuousBatcher, GenBatcher

        ml_cfg = self.node.config.ml
        merged = any(s.coworkers for s in model.plan.stages)
        # models the paged slot engine refuses must get the WINDOWED
        # batcher here — routing them continuous would degrade each
        # request to a serialized solo generate on the worker's fallback.
        # The predicate lives with the engine (paged_unsupported) so this
        # routing can never drift from what the engine actually accepts:
        # int8-KV models ("int8+kv") serve CONTINUOUS now — the paged
        # cache stores int8 pages natively (kv_quant, docs/SERVING.md)
        from tensorlink_tpu.engine.continuous import paged_unsupported

        unpageable = paged_unsupported(cfg) is not None
        # the ENTRY worker's advertised pool role (disaggregated serving):
        # what /healthz serving_modes reports until live snapshots arrive
        entry_role = "mixed"
        if getattr(model, "plan", None) is not None:
            entry_role = str(
                (serving_roles or {}).get(
                    model.plan.stages[0].worker_id
                ) or "mixed"
            )
        if ml_cfg.continuous_batching and not merged and not unpageable:
            # continuous batching (docs/SERVING.md): no arrival window, no
            # drain barrier — requests join the model's running slot batch
            # at decode-chunk boundaries.
            batcher = ContinuousBatcher(
                model, job.tokenizer.eos_ids,
                worker_role=entry_role,
                max_slots=min(ml_cfg.cont_max_slots, ml_cfg.max_serve_batch),
                chunk_steps=ml_cfg.cont_chunk_steps,
                kv_quant=ml_cfg.kv_quant,
                host_tier_pages=int(
                    getattr(ml_cfg, "cont_host_tier_pages", 0)
                ),
                spec_decode=bool(getattr(ml_cfg, "spec_decode", False)),
                spec_draft=int(getattr(ml_cfg, "spec_draft", 8)),
                spec_budget=int(getattr(ml_cfg, "spec_budget", 0)),
                default_priority=ml_cfg.default_priority,
                sched_queue_cap=ml_cfg.sched_queue_cap,
                sched_aging_ticks=ml_cfg.sched_aging_ticks,
                sched_preemption=ml_cfg.sched_preemption,
                sched_policy=ml_cfg.sched_policy,
                sched_max_wait_s=ml_cfg.sched_max_wait_s,
            )
        else:
            batcher = GenBatcher(
                model, job.tokenizer.eos_ids,
                # a batch never exceeds what the engine's buckets compile for
                max_batch=min(ml_cfg.max_serve_batch, ml_cfg.batch_buckets[-1]),
            )
        if hasattr(batcher, "on_admit"):
            # write-ahead seed journaling: the batcher tells the journal
            # each jrid-tagged admission's decode seed before dispatch
            batcher.on_admit = self._note_admit_seed
        return batcher

    def _do_host(
        self, job: HostedJob, *, batch, seq_len, config, seed, quant=None
    ) -> None:
        from tensorlink_tpu.api.tokenizer import load_tokenizer

        name = job.name
        model_spec: dict = {"name": name, "seed": seed}
        if config:
            model_spec["config"] = config
        if quant:
            # weight-only-quantized serving rides the job spec to the
            # worker (ml/worker.py::load_stage quantizes the stage params;
            # the paged engine dequantizes through quant.matmul on the fly)
            if quant not in ("int8", "int8+kv"):
                raise ValueError(f"unknown quant mode {quant!r}")
            model_spec["quant"] = quant
        if "/" in name or name.startswith("."):
            model_spec.setdefault("ckpt", name)
        cfg = self._resolve_config(model_spec)
        model_spec["config"] = cfg.to_json()
        job.cfg = cfg
        job.seq_len = min(seq_len or cfg.max_seq_len, cfg.max_seq_len)
        job.tokenizer = load_tokenizer(model_spec)

        # write-ahead: the host intent (with everything needed to rebuild
        # the job shell at recovery) is durable before recruiting starts
        iid = self._jintent("host", {
            "name": name, "spec": dict(model_spec), "batch": batch,
            "seed": seed, "seq_len": job.seq_len,
        })
        try:
            job.model, job.batcher, jid, attach = self._build_replica(
                job, model_spec, cfg, batch=batch, seed=seed,
            )
            job.replicas = [{
                "rid": "r0", "model": job.model, "batcher": job.batcher,
                "job_id": jid, "spec": dict(model_spec), "batch": batch,
                "seed": seed, "attach": attach,
            }]
            self._journal_replica(job, job.replicas[0])
            ml_cfg = self.node.config.ml
            n_replicas = max(int(getattr(ml_cfg, "fleet_replicas", 1)), 1)
            if n_replicas > 1:
                self._grow_fleet(job, model_spec, cfg, n_replicas,
                                 batch=batch, seed=seed)
        except Exception as e:
            self._jabort(iid, {"error": f"{type(e).__name__}: {e}"[:200]})
            raise
        job.status = "ready"
        self._jcommit(iid, {"replicas": len(job.replicas)})
        self.log.info(
            "hosting %s ready (%d replica(s))", name, len(job.replicas)
        )

    def _grow_fleet(
        self, job: HostedJob, model_spec: dict, cfg, n_replicas: int,
        *, batch, seed,
    ) -> None:
        """Host replicas 1..N-1 behind a FleetRouter (docs/SERVING.md
        "Fleet serving"). A replica that fails to plan/recruit degrades
        the fleet instead of failing the host — a model served by fewer
        replicas beats a model not served at all."""
        from tensorlink_tpu.fleet.router import FleetRouter

        ml_cfg = self.node.config.ml
        router = FleetRouter(
            refresh_s=float(getattr(ml_cfg, "fleet_refresh_s", 0.5)),
        )
        router.register("r0", job.batcher)
        for i in range(1, n_replicas):
            try:
                model, batcher, jid, attach = self._build_replica(
                    job, model_spec, cfg, batch=batch, seed=seed,
                )
            except Exception as e:
                self.log.warning(
                    "fleet replica %d of %s failed to host (%s: %s) — "
                    "serving with %d replica(s)",
                    i, job.name, type(e).__name__, e, len(job.replicas),
                )
                break
            job.replicas.append({
                "rid": f"r{i}", "model": model, "batcher": batcher,
                "job_id": jid, "spec": dict(model_spec), "batch": batch,
                "seed": seed, "attach": attach,
            })
            self._journal_replica(job, job.replicas[-1])
            router.register(f"r{i}", batcher)
        if len(job.replicas) < 2:
            return  # no fleet materialized: the single-replica path stands
        job.router = router
        self._push_replica_sets(job)
        if bool(getattr(ml_cfg, "fleet_autopilot", False)):
            self._start_autopilot(job)

    def _start_autopilot(self, job: HostedJob) -> None:
        """ONE construction site for a fleet's control loop — host-time
        (fleet_autopilot=True) and the on-demand /fleet/deploy path must
        build it identically or silently drift."""
        from tensorlink_tpu.fleet.autopilot import FleetAutopilot

        ml_cfg = self.node.config.ml
        job.autopilot = FleetAutopilot(
            job.router,
            ValidatorFleetActions(self, job),
            interval_s=float(
                getattr(ml_cfg, "fleet_autopilot_interval_s", 2.0)
            ),
            on_action=self._journal_action(job.name),
        ).start()

    def _journal_action(self, name: str):
        """The autopilot's on_action hook bound to one hosted model:
        intent/commit/abort pairs land in the control journal so a crash
        mid-deploy is resumed (open "action" intents at replay → re-queued
        via request_deploy) or rolled back — never forgotten."""

        def hook(phase: str, kind: str, rid: str, token=None):
            if phase == "intent":
                return self._jintent(
                    "action", {"verb": kind, "rid": rid, "name": name},
                )
            if token is None:
                return None
            if phase == "commit":
                self._jcommit(token)
            else:
                self._jabort(token)
            return token

        return hook

    # ------------------------------------------------------------------
    # crash recovery (PR 16 tentpole, docs/FAILURE_MODEL.md "Control
    # plane"): a restarted validator replays its journal, re-handshakes
    # the workers that kept serving through the crash, and reconciles the
    # journal's view of in-flight streams against theirs
    # ------------------------------------------------------------------
    def recover(self) -> dict:
        """Replay the control journal and re-attach to whatever the fleet
        kept alive across this validator's crash/restart.

        - hosted jobs with journaled replicas re-attach WITHOUT rebuilding
          (``DistributedModel.from_job(..., attach_only=True)`` — a
          rebuild would kill the live slots the workers preserved);
        - open migration tickets (drains the crash interrupted) are
          expired deterministically at both endpoints — staged pages drop,
          page conservation re-checked;
        - open autopilot action intents resolve: deploys re-queue,
          everything else aborts (the control loop re-decides from live
          state);
        - in-flight admissions reconcile against the worker-reported
          live/orphan streams — journal wins for PLACEMENT, worker wins
          for TOKENS.

        ``self.recovering`` is True for the duration; /healthz surfaces it
        and the API answers 503 + Retry-After meanwhile. Safe to call on a
        fresh validator (empty journal → fast no-op)."""
        from tensorlink_tpu.core.journal import ControlJournal

        if self.journal is None:
            return {"recovered": False, "reason": "journal disabled"}
        self.journal.flush()
        st = ControlJournal.replay(self.journal.path)
        live = {
            name: jrec for name, jrec in st.live_jobs().items()
            if name not in self.hosted
        }
        open_migs = st.open_intents("mig")
        open_actions = st.open_intents("action")
        if not live and not open_migs and not open_actions:
            return {
                "recovered": False, "reason": "nothing to recover",
                "torn": st.torn,
            }
        self.recovering = True
        info: dict = {
            "recovered": True, "torn": st.torn, "jobs": {},
            "streams": [], "expired_migrations": 0, "requeued_deploys": 0,
        }
        t0 = time.monotonic()
        try:
            for name, jrec in live.items():
                try:
                    job = self._recover_job(name, jrec, st, info)
                    info["jobs"][name] = {
                        "status": job.status, "replicas": len(job.replicas),
                    }
                except Exception as e:
                    self.log.exception("recovery of %s failed", name)
                    info["jobs"][name] = {
                        "status": "failed",
                        "error": f"{type(e).__name__}: {e}"[:200],
                    }
            self._expire_open_migrations(open_migs, info)
            self._resume_open_actions(open_actions, info)
            self._journal_rec("recovered", {
                "jobs": {
                    n: str(j.get("status", "")) for n, j in info["jobs"].items()
                },
                "streams": len(info["streams"]),
                "expired_migrations": info["expired_migrations"],
                "t_s": round(time.monotonic() - t0, 3),
            }, flush=True)
        finally:
            self.recovering = False
        self.log.info(
            "control-plane recovery: %d job(s), %d in-flight stream(s) "
            "reconciled, %d staged ticket(s) expired, %d deploy(s) "
            "re-queued, %d torn record(s) skipped (%.2fs)",
            len(info["jobs"]), len(info["streams"]),
            info["expired_migrations"], info["requeued_deploys"], st.torn,
            time.monotonic() - t0,
        )
        return info

    def _recover_job(self, name: str, jrec: dict, st, info: dict) -> HostedJob:
        """Rebuild one hosted job's shell from its journal record and
        re-attach every journaled replica. A replica that fails to
        re-attach (its worker died too) degrades the job instead of
        failing the recovery — same posture as ``_grow_fleet``."""
        from tensorlink_tpu.api.tokenizer import load_tokenizer
        from tensorlink_tpu.fleet.router import FleetRouter

        reps = jrec["replicas"]  # rid -> replica_up record
        any_rep = next(iter(reps.values()))
        spec = dict(
            (jrec["data"] or {}).get("spec") or any_rep.get("spec") or {}
        )
        if not spec:
            raise RuntimeError("journal carries no model spec to rebuild from")
        cfg = self._resolve_config(spec)
        seq_len = int(
            (jrec["data"] or {}).get("seq_len")
            or any_rep.get("seq_len") or cfg.max_seq_len
        )
        job = HostedJob(name=name)
        job.cfg = cfg
        job.seq_len = min(seq_len, cfg.max_seq_len)
        job.tokenizer = load_tokenizer(spec)
        with self._host_lock:
            cur = self.hosted.get(name)
            if cur is not None and cur.status in ("loading", "ready"):
                return cur  # hosted since the replay snapshot — keep it
            self.hosted[name] = job
        recovered: list[dict] = []
        for rid in sorted(reps, key=lambda r: (r != "r0", r)):
            try:
                recovered.append(
                    self._reattach_replica(job, rid, reps[rid])
                )
            except Exception as e:
                self.log.warning(
                    "replica %s of %s did not re-attach (%s: %s) — "
                    "recovering without it", rid, name, type(e).__name__, e,
                )
                self._journal_rec(
                    "replica_down", {"name": name, "rid": rid}, flush=True,
                )
        if not recovered:
            job.status = "failed"
            job.error = "no replica re-attached"
            raise RuntimeError(job.error)
        job.replicas = recovered
        job.model = recovered[0]["model"]
        job.batcher = recovered[0]["batcher"]
        self._reconcile_streams(job, recovered, st, info)
        if len(recovered) > 1:
            ml_cfg = self.node.config.ml
            router = FleetRouter(
                refresh_s=float(getattr(ml_cfg, "fleet_refresh_s", 0.5)),
            )
            for rep in recovered:
                router.register(rep["rid"], rep["batcher"])
            # journaled admission placements seed the routed counters so
            # routing telemetry survives the restart (fleet/router.py)
            router.seed_state({"routed": st.routed_counts()})
            job.router = router
            self._push_replica_sets(job)
            if bool(getattr(ml_cfg, "fleet_autopilot", False)):
                self._start_autopilot(job)
        job.status = "ready"
        return job

    def _reattach_replica(self, job: HostedJob, rid: str, rdata: dict) -> dict:
        """attach_only re-handshake of one journaled replica: the workers
        ACK their already-live stage (no rebuild — a rebuild would kill
        the slots that survived us) and re-announce live/orphan streams
        into ``model.attach_report``."""
        from tensorlink_tpu.ml.module import DistributedModel

        attach = dict(rdata.get("attach") or {})
        if not attach.get("plan"):
            raise RuntimeError("replica_up record carries no attach payload")
        model = DistributedModel.from_job(
            self.node, attach, seq_len=job.seq_len,
            seed=int(rdata.get("seed", 0) or 0), attach_only=True,
        )
        batcher = self._make_batcher(
            job, model, job.cfg, attach.get("serving_roles") or {},
        )
        return {
            "rid": rid, "model": model, "batcher": batcher,
            "job_id": attach.get("job_id") or rdata.get("job_id", ""),
            "spec": dict(rdata.get("spec") or {}),
            "batch": int(rdata.get("batch", 1) or 1),
            "seed": int(rdata.get("seed", 0) or 0),
            "attach": attach,
        }

    def _reconcile_streams(
        self, job: HostedJob, recovered: list, st, info: dict,
    ) -> None:
        """Merge the journal's in-flight admissions with the
        worker-reported live/orphaned streams from the attach_only acks.
        Contract (core/journal.py): the journal is authoritative for
        PLACEMENT, the worker for TOKENS — its count can only be >= the
        journaled high-water mark, so the mark is raised, never cut."""
        worker_view: dict[str, dict] = {}
        for rep in recovered:
            report = getattr(rep["model"], "attach_report", None) or {}
            for wid, ack in report.items():
                for o in ack.get("orphans", []) or []:
                    jrid = str(o.get("jrid", ""))
                    if jrid:
                        worker_view[jrid] = {
                            "rid": rep["rid"], "worker": wid,
                            "n": int(o.get("n", 0) or 0),
                            "finished": bool(o.get("finished")),
                        }
        for jrid, adm in st.orphan_admissions():
            if str(adm["data"].get("model", "")) != job.name:
                continue
            wv = worker_view.get(jrid)
            if wv is not None and wv["n"] > int(adm["hwm"]):
                # worker wins for tokens: raise the journaled mark to what
                # actually decoded while the control plane was down
                self._journal_rec("hwm", {"jrid": jrid, "n": int(wv["n"])})
            info["streams"].append({
                "jrid": jrid,
                "journal_hwm": int(adm["hwm"]),
                "worker_n": int(wv["n"]) if wv else None,
                "live": bool(wv and not wv["finished"]),
                # a stream the worker no longer holds is NOT resumable
                # from the buffer — the client's re-attach falls through
                # to a plain re-prefill resume (exactly-once regardless)
                "resumable": wv is not None,
            })

    def _expire_open_migrations(self, open_migs: list, info: dict) -> None:
        """Satellite fix: a drain in flight when the validator died may
        have left page-carrying migration tickets STAGED (exported, never
        committed). Expire them deterministically at replay — both
        endpoints drop staged pages and re-check page conservation — then
        abort the journal intent so the next replay sees it closed."""
        for iid, ent in open_migs:
            data = ent.get("data") or {}
            wids = {
                str(data.get("src") or ""), str(data.get("dest") or ""),
            } - {""}
            # dial the ticket's journaled endpoint addresses first: the
            # drain DESTINATION is usually outside the re-attached plan,
            # so this restarted validator holds no connection to it and
            # the per-wid expiry below would fail as "unknown worker"
            for addr_key in ("src_addr", "dest_addr"):
                addr = data.get(addr_key) or []
                if len(addr) == 2:
                    try:
                        self.bridge.request(
                            "connect",
                            {"host": str(addr[0]), "port": int(addr[1])},
                            timeout=10.0,
                        )
                    except Exception as e:
                        self.log.debug(
                            "dial of %s for ticket expiry failed: %s",
                            addr, e,
                        )
            if not data.get("dest"):
                # dest-less drain: the net layer chose the destination and
                # the choice died with it — sweep every worker (expire is
                # a no-op where nothing is staged)
                try:
                    stats = self.bridge.request("stats_workers", timeout=15.0)
                    wids |= {
                        str(s.get("id")) for s in stats if s.get("id")
                    }
                except Exception as e:
                    self.log.warning("worker sweep for expiry failed: %s", e)
            expired = 0
            for wid in sorted(wids):
                try:
                    r = self.bridge.request(
                        "expire_migrations",
                        {"worker": wid, "job_id": data.get("job_id", "")},
                        timeout=30.0,
                    )
                    if isinstance(r, dict):
                        expired += int(r.get("expired", 0) or 0)
                except Exception as e:
                    self.log.warning(
                        "migration-ticket expiry on %s failed: %s",
                        wid[:8], e,
                    )
            info["expired_migrations"] += expired
            self._jabort(iid, {"recovery": "expired", "expired": expired})

    def _resume_open_actions(self, open_actions: list, info: dict) -> None:
        """Open autopilot intents — the crash interrupted a control
        action. Deploys re-queue (rehost converges; repeating one is
        idempotent), everything else aborts and the control loop
        re-decides from live state."""
        for iid, ent in open_actions:
            data = ent.get("data") or {}
            verb = str(data.get("verb", ""))
            job = self.hosted.get(str(data.get("name", "")))
            requeued = False
            if verb == "deploy" and job is not None and job.autopilot is not None:
                rid = str(data.get("rid", ""))
                try:
                    job.autopilot.request_deploy([rid] if rid else None)
                    info["requeued_deploys"] += 1
                    requeued = True
                except Exception:
                    self.log.exception(
                        "deploy re-queue for %s failed", data.get("name"),
                    )
            self._jabort(
                iid, {"recovery": "requeued" if requeued else "dropped"},
            )

    def _replica_entry_worker(self, rep: dict) -> str:
        model = rep.get("model")
        plan = getattr(model, "plan", None)
        if plan is None or not plan.stages:
            return ""
        return str(plan.stages[0].worker_id)

    def _push_replica_sets(self, job: HostedJob) -> None:
        """Mirror of the PR 13 HANDOFF push at fleet granularity: tell
        each replica's entry worker who its sibling replicas are
        (REPLICA_SET frames), so a destination-less DRAIN — the
        autopilot's rolling deploy — lands on a sibling that already
        serves the same model. Best-effort: an unreached worker just
        keeps the validator-chosen drain destination."""
        entries = [
            (rep, self._replica_entry_worker(rep)) for rep in job.replicas
        ]
        for rep, wid in entries:
            if not wid:
                continue
            peers = [
                {"id": w2, "job_id": r2["job_id"]}
                for r2, w2 in entries
                if r2 is not rep and w2
            ]
            if not peers:
                continue
            try:
                self.bridge.request(
                    "set_replica_set",
                    {"worker": wid, "job_id": rep["job_id"], "peers": peers},
                    timeout=10.0,
                )
            except Exception as e:
                self.log.warning(
                    "replica-set push to %s failed: %s", wid[:8], e
                )

    def unhost_model(self, name: str) -> bool:
        """Drop a hosted model and release its workers (reference
        _remove_hosted_job, ml/validator.py:1043)."""
        with self._host_lock:
            job = self.hosted.pop(name, None)
        if job is None:
            return False
        self._journal_rec("unhost", {"name": name}, flush=True)
        if job.autopilot is not None:
            job.autopilot.stop()  # no control actions during teardown
        # fleet replicas beyond r0 (r0 IS job.model/job.batcher below)
        for rep in job.replicas[1:]:
            if job.router is not None:
                job.router.deregister(rep["rid"])
            try:
                rep["batcher"].close()
            except Exception:
                self.log.exception(
                    "replica %s of %s batcher close failed", rep["rid"],
                    name,
                )
            try:
                # shutdown ALWAYS runs — a wedged batcher close must not
                # leave this replica's recruited workers reserved forever
                rep["model"].shutdown()
            except Exception:
                self.log.exception(
                    "replica %s of %s failed to unhost", rep["rid"], name
                )
        if job.batcher is not None:
            job.batcher.close()  # drain the dispatcher first
        if job.model is not None:
            with job.lock:  # let an in-flight generation finish first
                job.model.shutdown()
        return True

    def health_snapshot(self) -> dict:
        """The ``GET /healthz`` body: status, hosted model names, drain
        flag. Deliberately CHEAP — dict reads under the host lock, no
        batcher stats, no ML-process round trip — so load balancers and
        the cluster router (ROADMAP item 3) can probe at high frequency
        without touching the serving path."""
        with self._host_lock:
            jobs = {
                name: (j.batcher, list(j.replicas))
                for name, j in self.hosted.items()
            }
        modes = {}
        headroom: dict = {}
        for name, (batcher, replicas) in jobs.items():
            get_modes = getattr(batcher, "serving_modes", None)
            if callable(get_modes):
                modes[name] = get_modes()
            else:
                # windowed batcher (or no batcher yet): vanilla decode
                modes[name] = {
                    "kv_quant": "none", "weight_quant": "none",
                    "spec_decode": False, "host_tier": False,
                    "worker_role": "mixed", "weights_version": 1,
                }
            # per-replica headroom (kv_pages_free, slots_free, per-class
            # queue depth): enough for an EXTERNAL load balancer to
            # route without scraping /metrics — same cheap contract
            reps = replicas or (
                [{"rid": "r0", "batcher": batcher}] if batcher is not None
                else []
            )
            hr = {}
            for rep in reps:
                get_hr = getattr(rep.get("batcher"), "headroom", None)
                if not callable(get_hr):
                    continue
                try:
                    hr[rep["rid"]] = get_hr()
                except Exception:
                    # one dead replica must not 500 the whole node's
                    # probe — report it unroutable, keep the siblings
                    hr[rep["rid"]] = {
                        "slots_free": 0, "kv_pages_free": 0,
                        "queue_depth": {}, "draining": True,
                        "dead": True,
                    }
            if hr:
                headroom[name] = hr
        return {
            "status": "ok",
            "hosted_models": list(jobs),
            # per-model throughput modes (kv_quant, spec_decode): which
            # decode shape a replica actually runs — a router must see
            # this before placing traffic (cheap attribute reads, the
            # same no-ML-round-trip contract as the rest of the body)
            "serving_modes": modes,
            # per-model, per-replica headroom (docs/SERVING.md "Fleet
            # serving" — the external-LB routing fields)
            "headroom": headroom,
            "draining": bool(self.draining),
            # recovery window (control-plane crash safety): True while
            # recover() is replaying the journal — the API answers new
            # generations 503 + Retry-After until it drops
            "recovering": bool(self.recovering),
        }

    def metrics_groups(self) -> list[tuple[dict, Any]]:
        """(labels, registry) pairs for the /metrics exposition: each
        hosted model's engine registry when it lives in-process (local
        continuous batching), or its last remote serving snapshot
        flattened into gauges (the dict riding every GENERATE_RESP)."""
        from tensorlink_tpu.core.metrics import (
            MetricsRegistry,
            snapshot_gauges,
        )

        groups: list[tuple[dict, Any]] = []
        with self._host_lock:
            jobs = list(self.hosted.values())
        for j in jobs:
            # one label group per replica (single-replica models keep
            # the unlabeled-model shape — byte-compatible with pre-fleet
            # scrapes); the router/autopilot registries render under the
            # model label alone
            fleet = j.router is not None
            replicas = j.replicas or [
                {"rid": "r0", "model": j.model, "batcher": j.batcher}
            ]
            for rep in replicas:
                labels = {"model": j.name}
                if fleet:
                    labels["replica"] = rep["rid"]
                batcher = rep.get("batcher")
                reg = None
                if batcher is not None:
                    get_reg = getattr(batcher, "metrics_registry", None)
                    reg = get_reg() if callable(get_reg) else None
                    if reg is None:
                        reg = getattr(batcher, "metrics", None)
                if reg is not None:
                    groups.append((labels, reg))
                snap = getattr(
                    rep.get("model"), "cont_serving_stats", None
                )
                if isinstance(snap, dict) and snap:
                    sreg = MetricsRegistry()
                    snapshot_gauges(sreg, snap, prefix="tlink_engine_")
                    groups.append((labels, sreg))
            if fleet:
                groups.append(({"model": j.name}, j.router.metrics))
            if j.autopilot is not None:
                groups.append(({"model": j.name}, j.autopilot.metrics))
        return groups

    def hosted_snapshot(self) -> list[dict]:
        """Consistent view for API threads (the hosted dict is mutated by
        pool threads under _host_lock; readers must take it too)."""
        with self._host_lock:
            out = []
            for j in self.hosted.values():
                entry = {"name": j.name, "status": j.status}
                stats = j.batcher.stats() if j.batcher is not None else None
                if stats:
                    entry["serving"] = stats
                model = j.model
                if model is not None and getattr(model, "plan", None):
                    entry["stages"] = model.plan.n_stages
                    cf = getattr(model, "chain_forwards", 0)
                    if cf:  # worker-to-worker chained calls completed
                        entry["chain_forwards"] = cf
                if j.router is not None:
                    # fleet view: per-replica routed counts + health,
                    # and each replica's own serving stats under its rid
                    entry["replicas"] = len(j.replicas)
                    entry["fleet"] = j.router.snapshot()
                    entry["replica_serving"] = {
                        rep["rid"]: rep["batcher"].stats()
                        for rep in j.replicas[1:]
                        if rep.get("batcher") is not None
                    }
                out.append(entry)
            return out

    def model_status(self, name: str) -> dict:
        job = self.hosted.get(name)
        if job is None:
            return {"model": name, "status": "absent"}
        out = {"model": name, "status": job.status}
        if job.error:
            out["error"] = job.error
        # serving telemetry (scheduler + slot-engine/prefix-cache counters
        # when the continuous path is active) — same dict /stats carries
        # per hosted model via hosted_snapshot()
        stats = job.batcher.stats() if job.batcher is not None else None
        if stats:
            out["serving"] = stats
        return out

    # ------------------------------------------------------------------
    # generation service for the API (reference _prepare_generation /
    # _generate / _generate_streaming, ml/validator.py:579-850)
    # ------------------------------------------------------------------
    def generate_api(
        self,
        req,  # schemas.GenerationRequest
        on_delta: Callable[[str], None] | None = None,
        trace_id: str | None = None,
        meta_cb: Callable[[dict], None] | None = None,
    ) -> dict:
        """Run one generation on a hosted model. Returns
        ``{text, reasoning, prompt_tokens, completion_tokens, finish_reason,
        jrid}``.
        ``on_delta`` receives visible-answer text pieces as they decode.
        ``meta_cb`` (streaming only) fires once at admission with
        ``{"jrid": ...}`` so SSE clients hold their re-attach handle
        BEFORE any crash can interrupt the stream.
        ``trace_id`` (minted by the API server) threads through the
        batcher to the engine so every hop's spans land under it, and is
        installed as the ACTIVE trace on this worker thread so json-mode
        log lines join the trace too (core/logging.py)."""
        from tensorlink_tpu.core.trace import current_trace

        tid = str(trace_id or "")
        token = current_trace.set(tid)
        try:
            return self._generate_api(req, on_delta, tid, meta_cb)
        finally:
            # the pool thread serves many requests — never leak the id
            current_trace.reset(token)

    def _generate_api(self, req, on_delta, trace_id: str,
                      meta_cb=None) -> dict:
        from tensorlink_tpu.api.formatter import (
            StopStream,
            ThinkStripStream,
            extract_reasoning_and_answer,
            format_chat_prompt,
            normalize_generate_args,
        )

        job = self.hosted.get(req.hf_name)
        if job is None or job.status != "ready":
            raise ModelNotReady(req.hf_name, job.status if job else "absent")
        self._bump_demand(req.hf_name)
        tok = job.tokenizer

        prompt = format_chat_prompt(
            req.message,
            req.history,
            tokenizer=tok if tok.chat_template else None,
            model_name=req.hf_name,
            enable_thinking=req.enable_thinking,
        )
        ids = tok.encode(prompt)
        max_ctx = min(job.seq_len, tok.model_max_length)
        # clamp the prompt against the context window while reserving room
        # for the requested completion (reference formatter.py:47-71
        # truncates against model_max_length)
        reserve = min(int(req.max_new_tokens), max(max_ctx // 2, 1))
        if len(ids) > max_ctx - reserve:
            ids = ids[-(max_ctx - reserve):]
        args = normalize_generate_args(req, prompt_len=len(ids), max_context=max_ctx)

        # control-plane journal: write-ahead admission record. jrid is the
        # durable re-attach handle — the worker keys its live-stream and
        # orphan ledgers on it, so a restarted validator (or a client that
        # outlived one) can resume this exact stream. The prompt travels
        # as a digest only (the journal is an ops artifact, not a prompt
        # store); the seed record pairs up via the batcher's on_admit hook.
        # A re-attach request REUSES the pre-crash jrid: its admission is
        # already journaled (and open — no finish record), so a second
        # admit would reset the replayed high-water mark.
        rjid = str(getattr(req, "reattach", "") or "")
        jrid = rjid or uuid.uuid4().hex
        if not rjid:
            self._journal_rec(
                "admit",
                {
                    "jrid": jrid,
                    "model": req.hf_name,
                    "prompt_sha": hashlib.sha256(
                        ",".join(map(str, ids)).encode()
                    ).hexdigest()[:16],
                    "n_prompt": len(ids),
                    "priority": str(getattr(req, "priority", None) or ""),
                    "max_new_tokens": int(args["max_new_tokens"]),
                    "placement": "router" if job.router is not None else "r0",
                },
                flush=True,
            )
        if meta_cb is not None:
            meta_cb({"jrid": jrid})

        stripper = ThinkStripStream() if not req.enable_thinking else None
        # Incremental detokenization via the offset algorithm (HF
        # TextStreamer): both decodes share the same start token, so
        # SentencePiece leading-space handling stays consistent, and each
        # step decodes only a bounded window — not the whole sequence
        # (O(n²) otherwise on the SSE hot path).
        emitted_ids: list[int] = []
        prefix_offset = 0
        read_offset = 0

        # OpenAI-style stop sequences (applied HERE, not just declared like
        # the reference's schema field). Stream-side filtering runs only
        # when the deltas are ANSWER text (think blocks stripped) — with
        # enable_thinking=true the raw reasoning streams through unfiltered
        # and only the final answer field is truncated, since a stop match
        # inside the think block must not silence the whole stream.
        stop_list = list(getattr(req, "stop", []) or [])
        multi_stage = (
            job.model is not None
            and getattr(job.model, "plan", None) is not None
            and job.model.plan.n_stages > 1
        )
        # stop DETECTION also runs for NON-streamed requests on pipelined
        # models: their decode is host-driven anyway, so a confirmed match
        # cancels the loop and saves the remaining per-token stage hops.
        # (Non-streamed single-stage requests stay on the fully-compiled
        # loop — trading it for a host loop to enable cancel would cost
        # far more than the cancel saves.)
        stream_stops = (
            StopStream(stop_list, on_delta or (lambda _s: None))
            if stop_list and stripper is not None
            and (on_delta is not None or multi_stage)
            else None
        )

        def _deliver(delta: str) -> None:
            if stream_stops is not None:
                stream_stops.feed(delta)
            elif on_delta is not None:
                on_delta(delta)

        def _emit(delta: str) -> None:
            if stripper is not None:
                delta = stripper.feed(delta)
            if delta:
                _deliver(delta)

        use_cb = on_delta is not None or stream_stops is not None
        # delivered-token high-water marks, journaled every N tokens at
        # chunk granularity (streamed requests only — a non-streamed
        # request has delivered nothing until it returns, so its whole
        # outcome is the single finish record)
        hwm_every = max(int(getattr(self.node.config.ml, "journal_hwm_every", 16)), 1)
        hwm_next = [hwm_every]

        def stream_cb(new_tokens: list[int | None]):
            nonlocal prefix_offset, read_offset
            if not use_cb:
                return None
            emitted_ids.extend(t for t in new_tokens if t is not None)
            if len(emitted_ids) >= hwm_next[0]:
                self._journal_rec("hwm", {"jrid": jrid, "n": len(emitted_ids)})
                hwm_next[0] = len(emitted_ids) + hwm_every
            prefix_text = tok.decode(emitted_ids[prefix_offset:read_offset])
            new_text = tok.decode(emitted_ids[prefix_offset:])
            if len(new_text) > len(prefix_text) and not new_text.endswith("�"):
                delta = new_text[len(prefix_text):]
                prefix_offset = read_offset
                read_offset = len(emitted_ids)
                _emit(delta)
            if stream_stops is not None and stream_stops.stopped:
                # confirmed stop match: truthy return cancels this row —
                # host-driven decode loops stop generating it, compiled
                # loops stop forwarding its stream
                return [0]
            return None

        n_beams = int(getattr(req, "num_beams", 1) or 1)
        # beam search works on BOTH distributions: the engine session on
        # whole-model jobs, the session-cached stage chain on pipelined
        # jobs (ml/module.py::_generate_beam_pipelined) — the r4 400s for
        # multi-stage beams and penalties are both gone.
        # presence/frequency penalties work on BOTH distributions: the
        # engine path carries counts in its compiled loop, the pipelined
        # path keeps them session-resident on the head-holding worker
        # (ml/worker.py::_sample_from_logits) — the r4 400 is gone.
        # legacy lookahead is greedy-only; the emitted tokens are identical
        # to vanilla greedy, so the flag is a pure speed hint. Continuous
        # speculation ({"speculative": true}) rides the slot batch instead
        # and works under any sampling — also a pure hint.
        spec = bool(getattr(req, "lookahead", False)) and args["temperature"] == 0.0
        spec_cont = bool(getattr(req, "speculative", False))
        beams_used = None
        if (
            rjid
            and n_beams == 1
            and job.batcher is not None
            and job.model is not None
            and getattr(job.model, "plan", None) is not None
            and job.model.plan.n_stages == 1
        ):
            out_ids = self._reattach_api(
                job, rjid, ids, args, req,
                stream_cb=stream_cb if use_cb else None,
                trace_id=trace_id,
            )
        elif n_beams > 1:
            # deterministic beam decode: bypass the batcher (beams cannot
            # co-batch with other requests — they ARE the batch rows) and
            # serialize on the model lock like the non-batcher path; the
            # shared post-processing tail below handles eos/stop/finish
            # the worker may clamp the width to its largest compiled batch
            # bucket — info_out is per-call, so a concurrent batcher
            # dispatch on this model cannot clobber it
            info: dict = {}
            with job.lock:
                seqs = job.model.generate(
                    [ids],
                    max_new_tokens=args["max_new_tokens"],
                    eos_ids=tok.eos_ids,
                    num_beams=n_beams,
                    info_out=info,
                    trace_id=trace_id,
                )
            beams_used = info.get("num_beams_used")
            out_ids = seqs[0]
        elif job.batcher is not None:
            # concurrent requests coalesce into one batched decode
            # (ml/batching.py); the batcher demuxes this request's tokens.
            # A fleet-hosted model routes through the FleetRouter first:
            # same generate contract, placement scored per request
            # (prefix-cache affinity + per-class load), replica failure
            # failing over before the first token (docs/SERVING.md
            # "Fleet serving")
            gen = (
                job.router.dispatch if job.router is not None
                else job.batcher.generate
            )
            kw: dict = {}
            if job.router is not None:
                # journal the replica actually chosen (the admit record
                # could only say "router") so replayed routed-counts seed
                # the recovered router's real per-replica counters
                kw["on_route"] = lambda rid: self._journal_rec(
                    "place", {"jrid": jrid, "rid": rid}
                )
            out_ids = gen(
                ids,
                jrid=jrid,
                max_new_tokens=args["max_new_tokens"],
                temperature=args["temperature"],
                top_k=args["top_k"],
                top_p=args["top_p"],
                presence_penalty=args["presence_penalty"],
                frequency_penalty=args["frequency_penalty"],
                stream_cb=stream_cb if use_cb else None,
                lookahead=spec,
                speculative=spec_cont,
                priority=getattr(req, "priority", None) or None,
                trace_id=trace_id,
                # per-request opt-out of the disaggregated prefill→decode
                # handoff ({"handoff": false}; default opted in)
                handoff=bool(getattr(req, "handoff", True)),
                **kw,
            )
        else:
            with job.lock:  # serialize per-model generation
                seqs = job.model.generate(
                    [ids],
                    max_new_tokens=args["max_new_tokens"],
                    temperature=args["temperature"],
                    top_k=args["top_k"],
                    top_p=args["top_p"],
                    presence_penalty=args["presence_penalty"],
                    frequency_penalty=args["frequency_penalty"],
                    eos_ids=tok.eos_ids,
                    stream_cb=stream_cb if use_cb else None,
                    lookahead=spec,
                    trace_id=trace_id,
                )
            out_ids = seqs[0]
        if on_delta is not None:
            # flush whatever the offset algorithm still holds (including a
            # trailing partial-UTF8 replacement char — the stream must match
            # the non-stream text for the same request)
            prefix_text = tok.decode(emitted_ids[prefix_offset:read_offset])
            new_text = tok.decode(emitted_ids[prefix_offset:])
            if len(new_text) > len(prefix_text):
                _emit(new_text[len(prefix_text):])
            if stripper is not None:
                tail = stripper.flush()
                if tail:
                    _deliver(tail)
            if stream_stops is not None:
                stream_stops.flush()  # resolve pending prefixes / holdback
        eos = set(tok.eos_ids)
        full_text = tok.decode([i for i in out_ids if i not in eos])
        reasoning, answer = extract_reasoning_and_answer(full_text)
        hit_eos = bool(out_ids) and out_ids[-1] in eos
        finish = "stop" if hit_eos else "length"
        completion = len(out_ids)
        hits = [i for i in (answer.find(s) for s in stop_list) if i != -1]
        if hits:
            answer = answer[: min(hits)]
            finish = "stop"
            # bill tokens generated THROUGH the stop match, not the whole
            # decode (OpenAI semantics): the smallest prefix of out_ids
            # whose decoded answer contains a stop. Monotone in k, so
            # binary search; host-driven decode paths also CANCEL at the
            # match, while the fully-compiled loop runs out its budget —
            # either way the count is the truncated output's.
            def _stopped_at(k: int) -> bool:
                r_, a_ = extract_reasoning_and_answer(
                    tok.decode([t for t in out_ids[:k] if t not in eos])
                )
                return any(a_.find(s) != -1 for s in stop_list)

            lo_k, hi_k = 1, len(out_ids)
            while lo_k < hi_k:
                mid = (lo_k + hi_k) // 2
                if _stopped_at(mid):
                    hi_k = mid
                else:
                    lo_k = mid + 1
            completion = lo_k
        # finish closes the admission in the journal: replay no longer
        # treats this jrid as an orphaned stream needing reconciliation
        self._journal_rec("finish", {"jrid": jrid, "n": completion, "reason": finish})
        out = {
            "text": answer,
            "reasoning": reasoning,
            "prompt_tokens": len(ids),
            "completion_tokens": completion,
            "finish_reason": finish,
            # the durable re-attach handle: a client that outlives this
            # validator repeats its request with {"reattach": jrid} against
            # the recovered one (docs/FAILURE_MODEL.md "Control plane")
            "jrid": jrid,
        }
        if beams_used is not None and beams_used != n_beams:
            out["num_beams_used"] = int(beams_used)  # worker clamped
        return out

    def _reattach_api(self, job, rjid: str, ids, args, req, *,
                      stream_cb, trace_id: str):
        """Serve a ``{"reattach": jrid}`` request: rung 1 of the client
        re-attach ladder over REST. The journaled admission supplies the
        decode seed and (fleet) the replica placement; the worker rebinds
        its still-live slot or replays its finished-orphan buffer, and a
        miss falls through to a plain re-prefill generate — every rung
        returns the COMPLETE stream from token 0, so the client replaces
        its partial pre-crash text (exactly-once by replacement)."""
        from tensorlink_tpu.core.journal import ControlJournal

        seed = 0
        placement = ""
        if self.journal is not None:
            try:
                adm = ControlJournal.replay(
                    self.journal.path
                ).admissions.get(rjid)
                if adm is not None:
                    if adm.get("seed") is not None:
                        seed = int(adm["seed"])
                    placement = str(adm["data"].get("placement", "") or "")
            except Exception as e:
                self.log.debug("journal lookup for re-attach failed: %s", e)
        model = job.model
        for rep in job.replicas or []:
            if placement and rep.get("rid") == placement:
                model = rep["model"]
                break
        return model.reattach_continuous(
            rjid,
            prompt=ids,
            delivered=[],
            max_new_tokens=args["max_new_tokens"],
            temperature=args["temperature"],
            top_k=args["top_k"],
            top_p=args["top_p"],
            presence_penalty=args["presence_penalty"],
            frequency_penalty=args["frequency_penalty"],
            eos_ids=job.tokenizer.eos_ids,
            seed=seed,
            stream_cb=stream_cb,
            priority=getattr(req, "priority", None) or None,
            trace_id=trace_id,
        )

    # ------------------------------------------------------------------
    # fleet serving (tensorlink_tpu/fleet, docs/SERVING.md "Fleet
    # serving") — the /fleet route's view + the rolling-deploy verb
    # ------------------------------------------------------------------
    def fleet_snapshot(self) -> dict:
        """Per-model fleet state for ``GET /fleet``: router telemetry
        (per-replica routed counts, health, headroom) and the autopilot's
        status/history when one runs."""
        with self._host_lock:
            jobs = list(self.hosted.values())
        out = {}
        for j in jobs:
            if j.router is None:
                continue
            out[j.name] = {
                "replicas": len(j.replicas),
                "router": j.router.snapshot(),
                "autopilot": (
                    j.autopilot.status() if j.autopilot is not None else None
                ),
            }
        return out

    def fleet_deploy(self, model: str, replicas: list | None = None) -> dict:
        """Operator trigger for a zero-dropped-token rolling deploy
        (``POST /fleet/deploy``): each named replica (default all) in
        turn drains onto a sibling, rebuilds, rejoins. Requires a fleet;
        an autopilot is started on demand when none is running."""
        # under the host lock: a deploy racing unhost_model must either
        # see the job gone, or install the autopilot BEFORE unhost pops
        # the job — so unhost's stop() always finds and kills it (no
        # orphan control thread issuing verbs against released workers)
        with self._host_lock:
            job = self.hosted.get(model)
            if job is None or job.router is None:
                return {
                    "ok": False, "error": f"no fleet hosted for {model!r}"
                }
            if job.autopilot is None:
                self._start_autopilot(job)
            autopilot = job.autopilot
        queued = autopilot.request_deploy(replicas)
        return {"ok": True, "queued": queued}


def _attach_addr(rep: dict | None, wid: str) -> list:
    """``[host, port]`` of ``wid`` from a replica's journaled attach
    payload (the create_job worker map), ``[]`` when unknown — used to
    make migration tickets self-contained for crash recovery."""
    if not rep or not wid:
        return []
    addr = ((rep.get("attach") or {}).get("workers") or {}).get(wid)
    return list(addr) if addr else []


class ValidatorFleetActions:
    """FleetAutopilot actions over REMOTE replicas — every verb rides
    the existing wire machinery, so moved streams stay bit-identical by
    the PR 8 contract:

    - ``drain``/``drain_step``: the validator's DRAIN verb sheds the
      replica's entry worker (page-ship, re-prefill fallback, zero
      dropped streams); in-flight client requests follow the migration
      redirects transparently (ml/module.py).
    - ``rehost``: the rolling deploy's upgrade — shut the replica's job
      down, re-plan/recruit a fresh one (the drained worker sits fenced
      until its operator restarts it, which IS the binary-upgrade
      window), return the new batcher for the router to re-register.
    - ``rebalance``: declined (returns 0). The wire moves streams at
      WORKER granularity only — a per-stream rebalance would drain the
      whole replica, which is the deploy verb's job, not a load tweak.
      (The in-process :class:`~tensorlink_tpu.fleet.autopilot.
      EngineFleetActions` does per-stream moves.)
    - ``scale_decode``: re-push the handoff pool (PR 13) to every
      replica's entry worker with one more / one fewer decode-role
      worker.
    - ``publish_weights``: declined (returns False). A live weight
      hot-swap needs the engine in-process (docs/TRAINING.md); a remote
      replica picks a new model version up through the rolling-deploy
      path (rehost reloads the checkpoint), which the autopilot records
      per replica so the operator sees exactly who is on what.
    """

    def __init__(self, validator: DistributedValidator, job: HostedJob):
        self.validator = validator
        self.job = job
        self.log = validator.log
        self._decode_pool_n: int | None = None
        # replicas whose wire DRAIN completed: the serving snapshot only
        # refreshes on GENERATE_RESP traffic, and a drained (fenced)
        # replica receives none — judging "drained" from the stale
        # snapshot would loop the deploy forever
        self._drained: set[str] = set()

    def _job_live(self) -> bool:
        """The job is still THE hosted job for its model. unhost_model's
        autopilot.stop() only joins 10s while wire verbs run minutes —
        an in-flight tick that outlives the unhost must not keep acting
        (a post-unhost rehost would recruit workers nothing ever
        releases)."""
        return self.validator.hosted.get(self.job.name) is self.job

    def _rep(self, rid: str) -> dict | None:
        for rep in self.job.replicas:
            if rep["rid"] == rid:
                return rep
        return None

    def live_work(self, rid: str) -> int:
        rep = self._rep(rid)
        if rep is None:
            return 0
        snap = rep["batcher"].router_snapshot()
        live = max(
            int(snap.get("max_slots") or 0) - int(snap.get("slots_free") or 0),
            0,
        )
        return live + sum(
            int(v) for v in (snap.get("queue_depth") or {}).values()
        )

    def movable_streams(self, rid: str) -> int:
        return self.live_work(rid)

    def rebalance(self, src: str, dst: str, max_streams: int = 1) -> int:
        self.log.debug(
            "fleet rebalance %s→%s declined: remote replicas move at "
            "worker granularity (use the deploy/drain verb)", src, dst,
        )
        return 0

    def drain(self, rid: str) -> None:
        rep = self._rep(rid)
        if rep is None or not self._job_live():
            return
        wid = self.validator._replica_entry_worker(rep)
        if not wid:
            return
        # primary path: drain onto a SIBLING replica's entry worker (it
        # already hosts the model — no stage ship, prefix probes hit).
        # When no sibling runs on a different worker the verb goes out
        # dest-less: the net layer picks most-free, and the worker's own
        # REPLICA_SET fallback backstops a validator with no candidates.
        dest, dest_rep = next(
            (
                (w, r2) for r2 in self.job.replicas
                if r2 is not rep
                and (w := self.validator._replica_entry_worker(r2))
                and w != wid
            ),
            (None, None),
        )
        req = {"worker": wid}
        if dest:
            req["dest"] = dest
        # write-ahead migration ticket: a validator that dies while this
        # drain is in flight leaves an OPEN "mig" intent in the journal;
        # recovery expires the staged pages at both endpoints
        # deterministically (no half-staged tickets leak), then aborts it.
        # The endpoint ADDRESSES ride the ticket: the recovered validator
        # re-dials only the plan workers, and the drain destination is
        # outside the source plan by construction — without its address
        # the expiry could never reach the staged pages.
        iid = self.validator._jintent("mig", {
            "name": self.job.name, "rid": rid, "src": wid,
            "dest": dest or "", "job_id": rep["job_id"],
            "src_addr": _attach_addr(rep, wid),
            "dest_addr": _attach_addr(dest_rep, dest or ""),
        })
        try:
            summary = self.validator.bridge.request(
                "drain_worker", req, timeout=600.0,
            )
        except Exception as e:
            self.validator._jabort(iid, {"error": str(e)[:200]})
            raise
        if isinstance(summary, dict) and summary.get("ok"):
            self._drained.add(rid)
            self.validator._jcommit(iid, {"ok": True})
        else:
            self.validator._jabort(iid, {"summary": str(summary)[:200]})
        self.log.info(
            "autopilot drain of replica %s (worker %s → %s): %s",
            rid, wid[:8], (dest or "auto")[:8], summary,
        )

    def undrain(self, rid: str) -> None:
        # the DRAIN verb is synchronous and terminal for the worker (it
        # stays capacity-fenced for its upgrade); nothing to lower here
        return

    def drain_step(self, src: str, dst: str, max_streams: int = 4) -> int:
        # a COMPLETED wire drain moved everything synchronously —
        # in-flight client requests finish through their migration
        # redirects regardless, and the stale snapshot must not gate the
        # deploy (it stops refreshing the moment the replica is fenced)
        if src in self._drained:
            return 0
        return self.live_work(src)

    def rehost(self, rid: str):
        """Rebuild the replica on current capacity; returns the new
        batcher (the autopilot re-registers it under the same rid)."""
        if not self._job_live():
            raise RuntimeError(
                f"{self.job.name} was unhosted mid-deploy — refusing to "
                "recruit workers for a released job"
            )
        rep = self._rep(rid)
        if rep is None:
            return None
        old_batcher, old_model = rep["batcher"], rep["model"]
        model, batcher, jid, attach = self.validator._build_replica(
            self.job, dict(rep["spec"]), self.job.cfg,
            batch=rep.get("batch", 1), seed=rep.get("seed", 0),
        )
        self.validator._journal_rec(
            "replica_down", {"name": self.job.name, "rid": rid}, flush=True,
        )
        rep.update({
            "model": model, "batcher": batcher, "job_id": jid,
            "attach": attach,
        })
        self.validator._journal_replica(self.job, rep)
        self._drained.discard(rid)  # the rebuilt replica serves again
        if rep is self.job.replicas[0]:
            self.job.model, self.job.batcher = model, batcher
        try:
            old_batcher.close()
        except Exception:
            self.log.exception("old replica %s batcher close failed", rid)
        try:
            # shutdown ALWAYS runs — a wedged batcher close must not
            # leave the old replica's recruited workers reserved forever
            # (the same invariant unhost_model keeps)
            old_model.shutdown()
        except Exception:
            self.log.exception("old replica %s teardown failed", rid)
        self.validator._push_replica_sets(self.job)
        return batcher

    def publish_weights(self, rid: str, params, version: int) -> bool:
        """Declined — see the class docstring: remote replicas take the
        rolling-deploy path for model updates."""
        self.log.info(
            "fleet weight publish v%s declined for remote replica %s "
            "(rolling-deploy path)", version, rid,
        )
        return False

    def scale_decode(self, up: bool) -> bool:
        if not self._job_live():
            return False
        stats = self.validator.bridge.request(
            "stats_workers", timeout=15.0
        )
        decode = [
            s for s in stats
            if str(s.get("serving_role") or "mixed") == "decode"
            and s.get("addr")
        ]
        if not decode:
            return False
        cur = (
            self._decode_pool_n
            if self._decode_pool_n is not None else len(decode)
        )
        target = max(1, min(len(decode), cur + (1 if up else -1)))
        if target == cur and self._decode_pool_n is not None:
            return False
        pool = [
            {"id": s["id"], "addr": list(s["addr"])}
            for s in decode[:target]
        ]
        pushed = False
        for rep in self.job.replicas:
            wid = self.validator._replica_entry_worker(rep)
            if not wid:
                continue
            try:
                self.validator.bridge.request(
                    "set_handoff_pool", {"worker": wid, "pool": pool},
                    timeout=10.0,
                )
                pushed = True
            except Exception as e:
                self.log.warning(
                    "handoff-pool push to %s failed: %s", wid[:8], e
                )
        if pushed:
            self._decode_pool_n = target
        return pushed


class ModelNotReady(RuntimeError):
    def __init__(self, name: str, status: str):
        super().__init__(f"model {name!r} is {status}")
        self.model = name
        self.status = status
