"""DistributedValidator — the ML-process planner on a validator node.

Reference: ml/validator.py:122 (``DistributedValidator.check_node`` polling
``get_jobs`` every tick, inspect_model → ModelParser → send_job_request).
Here job requests arrive as work events; planning = resolve the model config
(preset registry or HF checkpoint config) + ``plan_sharding`` over the live
worker capacities, then hand the job back to the network process to recruit
(roles.py `cmd_create_job`).
"""

from __future__ import annotations

import uuid

from tensorlink_tpu.core.logging import get_logger


class DistributedValidator:
    def __init__(self, node):
        self.node = node
        self.bridge = node.bridge
        self.log = get_logger(f"ml.validator{node.config.duplicate}")
        # model demand tracking (reference logs/models.json, ml/utils.py:663)
        self.demand: dict[str, int] = {}

    def run(self) -> None:
        while True:
            item = self.bridge.get_work(timeout=1.0)
            if item is None:
                continue
            kind, payload = item
            if kind == "_stop":
                return
            try:
                if kind == "job_req":
                    self._plan_job(payload)
                elif kind == "token":
                    pass  # API streaming relay lands here in the serving layer
                else:
                    self.log.warning("unhandled work kind %s", kind)
            except Exception:
                self.log.exception("work %s failed", kind)
                if kind == "job_req":
                    self.bridge.request(
                        "decline_job",
                        {"req_id": payload.get("req_id"), "error": "planning failed"},
                    )

    # -- planning -------------------------------------------------------
    def _resolve_config(self, model_spec: dict):
        """Model identity → ModelConfig. Accepts an explicit config dict, a
        preset name (registry), or a checkpoint dir with an HF config.json
        (reference resolves HF names via AutoConfig, ml/validator.py:367)."""
        from tensorlink_tpu.models.base import ModelConfig
        from tensorlink_tpu.models.registry import config_presets

        if model_spec.get("config"):
            return ModelConfig.from_json(model_spec["config"])
        name = model_spec.get("name", "")
        presets = config_presets()
        if name in presets:
            return presets[name]
        if model_spec.get("ckpt"):
            from tensorlink_tpu.engine.loader import CheckpointReader
            from tensorlink_tpu.models.registry import config_from_hf

            return config_from_hf(CheckpointReader(model_spec["ckpt"]).config())
        raise ValueError(f"cannot resolve model {name!r}")

    def _plan_job(self, p: dict) -> None:
        from tensorlink_tpu.parallel.planner import (
            AssignmentError,
            WorkerCapacity,
            plan_sharding,
        )

        spec = p["spec"]
        model_spec = dict(spec.get("model", {}))
        name = model_spec.get("name", "")
        self.demand[name] = self.demand.get(name, 0) + 1
        try:
            cfg = self._resolve_config(model_spec)
        except Exception as e:
            self.bridge.request(
                "decline_job", {"req_id": p["req_id"], "error": str(e)}
            )
            return
        model_spec["config"] = cfg.to_json()

        stats = self.bridge.request("stats_workers", timeout=15.0)
        workers = [
            WorkerCapacity(
                node_id=s["id"],
                hbm_bytes=float(s.get("free_bytes", s.get("hbm_bytes", 0.0))),
                n_devices=int(s.get("n_devices", 1)),
            )
            for s in stats
        ]
        try:
            plan = plan_sharding(
                cfg,
                workers,
                model_name=name,
                batch=int(spec.get("batch", 1)),
                seq_len=int(spec.get("seq_len", 2048)),
                training=bool(spec.get("training", False)),
                n_micro=spec.get("n_micro"),
            )
        except AssignmentError as e:
            self.log.info("declining job %s: %s", name, e)
            self.bridge.request(
                "decline_job", {"req_id": p["req_id"], "error": str(e)}
            )
            return

        # per-worker byte estimate for the recruit capacity check
        total_layers = max(cfg.n_layers, 1)
        stage_bytes = {
            s.worker_id: plan.estimate.total * (s.layer_hi - s.layer_lo) / total_layers
            for s in plan.stages
        }
        job = {
            "job_id": uuid.uuid4().hex,
            "model": model_spec,
            "plan": plan.to_json(),
            "stage_bytes": stage_bytes,
        }
        result = self.bridge.request(
            "create_job",
            {"req_id": p["req_id"], "user_id": p.get("user_id"), "job": job},
            timeout=30.0,
        )
        self.log.info(
            "job %s (%s): accepted=%s stages=%d",
            job["job_id"][:8], name, result.get("accepted"), plan.n_stages,
        )
