"""ML-process executors and the user-facing DistributedModel.

Reference package: tensorlink/ml (module.py, worker.py, validator.py,
optim.py, graphing.py). The graphing/planner capability lives in
``tensorlink_tpu.parallel``; models are native JAX programs
(``tensorlink_tpu.models``), so there is no injector and no module shipping —
jobs ship a plan + checkpoint reference, workers run compiled programs.
"""

from tensorlink_tpu.ml.module import DistributedModel

__all__ = ["DistributedModel"]
