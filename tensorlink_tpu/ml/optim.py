"""Distributed optimizer handle (reference ml/optim.py:81-205).

The reference dynamically subclasses a torch optimizer whose ``step`` /
``zero_grad`` fan OPTIMIZER RPCs to every worker and poll for completion.
Here each stage runs optax on its own (sharded) parameters — the fan-out
carries only the op + a gradient scale, and completion is the tensor-request
reply (no polling)."""

from __future__ import annotations

from typing import Any


class DistributedOptimizer:
    """Thin handle over the per-stage optax optimizers of one job."""

    def __init__(self, model, name: str = "adamw", **spec: Any):
        self.model = model
        self.name = name
        self.spec = spec
        model.init_optimizer(name, **spec)

    def step(self, scale: float = 1.0) -> dict:
        """Apply accumulated gradients on every stage. ``scale`` multiplies
        the accumulated cotangent sums first (DistributedModel.train_step
        passes 1/total_tokens; manual training loops usually pass 1.0)."""
        return self.model.optimizer_step(scale=scale)

    def zero_grad(self) -> None:
        self.model.zero_grad()


def create_distributed_optimizer(model, name: str = "adamw", **spec: Any):
    """Factory matching the reference's surface
    (``create_distributed_optimizer(model, torch.optim.AdamW, **kwargs)``,
    ml/optim.py:81) — optimizer identity is a name + kwargs resolved by
    engine/training.py::make_optimizer on each worker."""
    return DistributedOptimizer(model, name, **spec)
