"""DistributedModel — the user-facing handle on a distributed job.

Reference: ml/module.py:237 — an ``nn.Module`` wrapper whose offloaded
submodules RPC forward/backward/generate to workers. Here the model is a
functional program split into pipeline stages; this class is the driver:

- ``__init__`` requests a job (validator plans over live worker capacity),
  connects to the assigned workers, and ships each its stage assignment —
  a plan slice + model config + checkpoint reference, never code.
- ``forward`` chains FORWARD tensor-requests across the stages (the
  reference's OffloadedModule chain, module.py:1536), including the
  tied-embedding head hop.
- ``generate`` uses the worker-side compiled engine for single-stage jobs
  (streaming over the TOKEN relay) and drives a session-cached stage chain
  per token for pipelined jobs.

All waits are bounded (reference MAX_WAIT_TIME=150 s, module.py:58).
"""

from __future__ import annotations

import random
import secrets
import time
from typing import Any, Callable, Sequence

import numpy as np

from tensorlink_tpu.core.logging import get_logger
from tensorlink_tpu.p2p import protocol as proto

MAX_WAIT_TIME = 150.0  # reference ml/module.py:58

# retry envelope for worker RPCs (exponential backoff with jitter — the
# single bare retry this replaces would hammer a recovering worker and give
# up exactly when a second replacement was one more attempt away)
RETRY_ATTEMPTS = 4
BACKOFF_BASE_S = 0.1
BACKOFF_CAP_S = 5.0
# transport-failure signatures: errors cross the IPC bridge as RemoteError
# (stringified "TimeoutError: ..." / "ConnectionError: ...", nodes/ipc.py),
# so match on text as well as type
_TRANSPORT_SIGNS = (
    "TimeoutError", "ConnectionError", "no connection", "IncompleteReadError",
    "timed out",
)


def _transportish(e: BaseException) -> bool:
    return isinstance(e, (TimeoutError, ConnectionError)) or any(
        s in str(e) for s in _TRANSPORT_SIGNS
    )


class WorkerLost(RuntimeError):
    """A stage worker's connection died mid-training-step: the step's
    distributed state (micro-batch residuals, accumulated gradients) is
    gone with it, so the step must be re-driven from the last checkpoint —
    a transparent RPC retry would silently apply a partial gradient."""

    def __init__(self, worker_id: str | None, cause: BaseException):
        super().__init__(f"worker {str(worker_id)[:12]} lost: {cause}")
        self.worker_id = worker_id
        self.cause = cause


class SessionLost(WorkerLost):
    """A worker holding decode-session KV died mid-generate. A retry on a
    replacement would decode against an EMPTY cache; the session must be
    re-established by re-prefilling prompt + tokens-emitted-so-far
    (_generate_pipelined recovery)."""


def _any_nonzero(v) -> bool:
    """True when a scalar-or-per-row sampling knob has any nonzero entry
    (None coerces to 0)."""
    vals = v if isinstance(v, (list, tuple, np.ndarray)) else [v]
    return any(float(x or 0.0) != 0.0 for x in vals)


def _head_result(resp: dict):
    """Decode a head-worker FORWARD response into its terminal result:
    sampled token ids, speculative per-position argmax ids, or beam
    candidate (vals, idx) — or None when the response carries a plain
    activation/logits array (``resp["out"]``)."""
    spans = resp.get("trace_spans")
    if isinstance(spans, dict):
        # session-op spans shipped home by the responding stage worker
        # (ml/worker.py::_finish_fwd) — merge so /trace sees them
        from tensorlink_tpu.core.trace import get_tracer

        tracer = get_tracer()
        for tid, ss in spans.items():
            tracer.ingest(str(tid), ss or [])
    if "token" in resp:
        return np.asarray(resp["token"], np.int32)
    if "verify_ids" in resp:
        return np.asarray(resp["verify_ids"], np.int32)
    if "beam_vals" in resp:
        return np.asarray(resp["beam_vals"]), np.asarray(resp["beam_idx"])
    return None


class JobDeclinedError(RuntimeError):
    pass


class DistributedModel:
    def __init__(
        self,
        model: Any,  # preset name | ModelConfig | checkpoint dir
        node=None,
        *,
        training: bool = False,
        batch: int = 1,
        seq_len: int | None = None,
        n_micro: int | None = None,
        parallelism: dict[str, int] | None = None,
        seed: int = 0,
        ckpt: str | None = None,
        quant: str | None = None,  # "int8" | "int8+kv" quantized serving
        flash_attention: bool = False,  # Pallas flash prefill on workers
        start_session: bool = True,
        ckpt_every_steps: int = 0,  # auto-checkpoint cadence (0 = off)
        ckpt_dir: str | None = None,  # auto-checkpoint target directory
        request_timeout: float = MAX_WAIT_TIME,
        retry_attempts: int = RETRY_ATTEMPTS,
        **node_kw,
    ):
        from tensorlink_tpu.models.base import ModelConfig

        self.log = get_logger("ml.model")
        self._owns_node = node is None
        if node is None:
            from tensorlink_tpu.nodes.runners import UserNode

            node = UserNode(**node_kw).start()
        self.node = node
        self.training = training

        # model identity → job spec (resolution happens on the validator)
        if isinstance(model, ModelConfig):
            self.model_spec = {"name": "custom", "config": model.to_json()}
        elif isinstance(model, str) and ("/" in model or model.startswith(".")):
            self.model_spec = {"name": model, "ckpt": model}
        else:
            self.model_spec = {"name": str(model)}
        if ckpt:
            self.model_spec["ckpt"] = ckpt
        if quant:
            self.model_spec["quant"] = quant
        if flash_attention:
            self.model_spec["flash"] = True
        self.model_spec["seed"] = seed

        self.spec = {
            "model": self.model_spec,
            "batch": batch,
            "seq_len": seq_len or 2048,
            "training": training,
            "n_micro": n_micro,
            # explicit per-worker mesh axes (tensor/seq/stage/expert/...);
            # validated by the planner (parallel/planner._apply_mesh_hints)
            "parallelism": parallelism,
        }
        self.job_id: str | None = None
        self.plan = None
        self.cfg = None
        self.workers: dict[str, str] = {}  # worker plan id -> connected node id
        self.worker_addrs: dict[str, list] = {}  # worker id -> [host, port]
        self.chain_forwards = 0  # completed worker-to-worker chained calls
        import threading

        self._repair_lock = threading.Lock()
        self._repaired: dict[str, str] = {}  # dead worker id -> replacement
        self._request_timeout = float(request_timeout)
        self._retry_attempts = max(int(retry_attempts), 1)
        # jitter source for retry backoff — seeded so chaos runs replay
        self._retry_rng = random.Random(seed)
        self._ckpt_every_steps = int(ckpt_every_steps)
        self._ckpt_dir = ckpt_dir
        if start_session:
            self._initialize_distribution()

    # ------------------------------------------------------------------
    # job setup (reference _initialize_distribution → distribute_model,
    # module.py:987-1021,699)
    # ------------------------------------------------------------------
    @classmethod
    def from_job(cls, node, job_result: dict, *, attach_only: bool = False,
                 **kw) -> "DistributedModel":
        """Attach to an already-created job (validator-hosted models: the
        validator plans + recruits itself — reference _initialize_hosted_job,
        ml/validator.py:901 — then drives the job through its own node).

        ``attach_only=True`` is the control-plane recovery handshake: the
        MODULE frames tell each worker to ACK an already-live stage
        instead of rebuilding it (a rebuild would kill every live slot),
        and the acks re-announce live/orphaned streams into
        ``self.attach_report`` for journal reconciliation."""
        model = cls(
            job_result["model"].get("name", "hosted"),
            node=node,
            start_session=False,
            **kw,
        )
        model._attach(job_result, attach_only=attach_only)
        return model

    def _initialize_distribution(self) -> None:
        reply = self.node.send_request(
            "request_job", {"spec": self.spec}, timeout=MAX_WAIT_TIME
        )
        if not reply.get("accepted"):
            raise JobDeclinedError(str(reply.get("error", reply)))
        self._attach(reply)

    def _attach(self, reply: dict, attach_only: bool = False) -> None:
        from tensorlink_tpu.models.base import ModelConfig
        from tensorlink_tpu.parallel.planner import ShardingPlan

        #: wid -> {"attached", "live_slots", "orphans"} from attach_only
        #: re-handshakes (empty on a normal attach)
        self.attach_report: dict[str, dict] = {}
        self.job_id = reply["job_id"]
        self.plan = ShardingPlan.from_json(reply["plan"])
        self.model_spec = reply.get("model", self.model_spec)
        self.cfg = ModelConfig.from_json(self.model_spec["config"])

        # connect to each assigned worker (co-slice coworkers included —
        # they execute every mirrored work item) and ship its stage
        for stage in self.plan.stages:
            for wid in [stage.worker_id] + list(stage.coworkers or []):
                if wid in self.workers:
                    continue
                if wid not in reply["workers"]:
                    # a merged stage missing ANY member's address cannot
                    # run — its SPMD programs would block forever at the
                    # first cross-process collective. Fail at setup.
                    raise RuntimeError(
                        f"job reply has no address for stage member "
                        f"{wid[:8]} — cannot drive the merged mesh"
                    )
                host, port = reply["workers"][wid]
                conn_id = self.node.connect_to(host, int(port))
                self.workers[wid] = conn_id
                # kept for chained forwards: each hop dials the NEXT
                # stage's worker by address (worker-to-worker, no user
                # transit)
                self.worker_addrs[wid] = [host, int(port)]
        for stage in self.plan.stages:
            body = {
                "job_id": self.job_id,
                "model": self.model_spec,
                "stage": _stage_dict(stage),
                "training": self.training,
            }
            if attach_only:
                body["attach_only"] = True
            resp = self._request_mirrored(
                stage, proto.MODULE, body, timeout=MAX_WAIT_TIME,
            )
            if not resp.get("ok"):
                raise RuntimeError(f"stage load failed: {resp}")
            if attach_only:
                self.attach_report[stage.worker_id] = {
                    "attached": bool(resp.get("attached", False)),
                    "live_slots": int(resp.get("live_slots", 0) or 0),
                    "orphans": list(resp.get("orphans", []) or []),
                }
        self.log.info(
            "job %s distributed over %d stage(s)",
            self.job_id[:8], self.plan.n_stages,
        )

    def _stage_members(self, stage) -> list[str]:
        """Primary first, then connected co-slice coworkers (merged-mesh
        stages, parallel/planner.py::_merge_co_slice)."""
        return [stage.worker_id] + [
            c for c in (stage.coworkers or []) if c in self.workers
        ]

    def _request_mirrored(
        self, stage, tag: str, body: dict, timeout=None,
    ):
        """One work item to a stage — and, when the stage is a co-slice
        MERGED mesh, the same item to every coworker process concurrently.
        The members joined one jax.distributed runtime, so each compiled
        call is one SPMD program that every process must launch; the
        mirrored items ARE those launches, and XLA's collectives keep them
        lockstep (a member that launches first simply blocks at its first
        collective until the others arrive). Coworkers answer a slim ack
        (``mirror`` flag, ml/worker.py); the primary's full response is
        returned. No repair on merged stages — replacing one member of a
        live jax.distributed job is not supported."""
        timeout = self._request_timeout if timeout is None else timeout
        members = self._stage_members(stage)
        if len(members) == 1:
            return self._request(stage.worker_id, tag, body, timeout)
        import threading

        results: dict[str, Any] = {}

        def issue(m: str) -> None:
            try:
                results[m] = self._request(
                    m, tag, dict(body, mirror=True), timeout, no_repair=True
                )
            except Exception as e:  # surfaced after the primary returns
                results[m] = e

        threads = [
            threading.Thread(target=issue, args=(m,), daemon=True)
            for m in members[1:]
        ]
        for t in threads:
            t.start()
        try:
            out = self._request(
                stage.worker_id, tag, body, timeout, no_repair=True
            )
        finally:
            for t in threads:
                t.join(timeout=timeout)
        for m, t in zip(members[1:], threads):
            if t.is_alive() or m not in results:
                # an unfinished mirror is a desynced SPMD member — report
                # it HERE, not as an unattributed hang on a later item
                raise RuntimeError(
                    f"co-slice member {m[:8]} did not complete the "
                    f"mirrored {tag} within {timeout}s"
                )
        for m, r in results.items():
            if isinstance(r, Exception):
                raise RuntimeError(
                    f"co-slice member {m[:8]} failed the mirrored {tag}: {r}"
                )
        return out

    def _backoff_delay(self, attempt: int) -> float:
        """Exponential backoff with jitter: base·2^(k-1), capped, scaled by
        a seeded uniform in [0.5, 1.5) so synchronized retry storms from
        concurrent driver threads decorrelate."""
        base = min(BACKOFF_BASE_S * 2 ** (attempt - 1), BACKOFF_CAP_S)
        return base * self._retry_rng.uniform(0.5, 1.5)

    def _request(
        self, worker_plan_id: str, tag: str, body: dict, timeout=None,
        _repaired: bool = False, no_repair: bool = False,
    ):
        """One worker RPC with a bounded retry envelope.

        - Transport timeouts retry the SAME worker with exponential backoff
          — but only when the op is idempotent (it carries a session ``seq``,
          which the worker dedups, ml/worker.py::_session_dup); anything
          else could double-apply.
        - A dead connection on a stateless op pulls a replacement from the
          validator (the reference's "request another worker" TODO,
          module.py:510-511, made real) and retries there.
        - A dead connection on a SESSION op raises :class:`SessionLost`:
          the replacement has no KV, so the generate loop must re-establish
          the session (re-prefill), not retry the RPC.
        - A dead connection mid-training-step (optimizer initialized)
          raises :class:`WorkerLost`: the step's residuals/gradients died
          with the worker, so train_step re-drives the whole step from the
          last checkpoint instead of applying a partial gradient.
        - ``no_repair``: mirrored SPMD work items are never retried at all —
          a lone re-launch would desync the merged mesh.
        """
        timeout = self._request_timeout if timeout is None else timeout
        session_op = tag == proto.FORWARD and body.get("session") is not None
        idempotent = body.get("seq") is not None
        attempts = 1 if (no_repair or _repaired) else self._retry_attempts
        worker = worker_plan_id
        resp = None
        for attempt in range(attempts):
            if attempt:
                time.sleep(self._backoff_delay(attempt))
            try:
                resp = self.node.send_request(
                    "tensor_request",
                    {
                        "peer": self.workers[worker],
                        "tag": tag,
                        "body": body,
                        "timeout": timeout,
                    },
                    timeout=timeout + 10.0,
                )
            except Exception as e:
                if no_repair or _repaired:
                    raise
                conn_lost = "no connection" in str(e)
                if conn_lost and session_op:
                    raise SessionLost(worker, e) from e
                if conn_lost and getattr(self, "_opt_ready", False) \
                        and getattr(self, "_step_active", False):
                    raise WorkerLost(worker, e) from e
                if conn_lost:
                    if attempt == attempts - 1:
                        raise
                    worker = self._repair(worker)
                    continue
                if idempotent and _transportish(e) and attempt < attempts - 1:
                    self.log.warning(
                        "%s to %s timed out (attempt %d); retrying "
                        "(seq-idempotent)", tag, worker[:8], attempt + 1,
                    )
                    continue
                raise
            break
        if isinstance(resp, dict) and resp.get("error"):
            # chained hops attribute the failing worker (ml/worker.py run
            # loop ships "worker" alongside the error)
            who = str(resp.get("worker", ""))[:12]
            raise RuntimeError(
                f"{tag} failed on worker{' ' + who if who else ''}: "
                f"{resp['error']}"
            )
        return resp

    # ------------------------------------------------------------------
    # worker replacement (user-pulled; validator may also push JOB_UPDATE —
    # the monitor path, platform/job_monitor.py)
    # ------------------------------------------------------------------
    def _repair(self, dead_plan_wid: str) -> str:
        """Ask the validator for a replacement, connect, re-ship the stage.
        Returns the new plan worker id. Raises if none is available.

        Concurrent micro-batch threads (train_step overlap) can all hit the
        same dead worker: the repair lock serializes them and the repair map
        makes followers reuse the first thread's replacement instead of
        recruiting again."""
        with self._repair_lock:
            fixed = self._chase_repaired(dead_plan_wid)
            if fixed:
                return fixed
            return self._repair_locked(dead_plan_wid)

    # tlint: holds-lock(self._repair_lock)
    def _chase_repaired(self, dead_plan_wid: str) -> str | None:
        """Resolve chained repairs (A→B then B→C): a straggler holding the
        oldest id must land on the live replacement. None when this id was
        never repaired. Caller holds _repair_lock."""
        fixed = self._repaired.get(dead_plan_wid)
        if not fixed:
            return None
        seen = {dead_plan_wid}
        while fixed in self._repaired and fixed not in seen:
            seen.add(fixed)
            fixed = self._repaired[fixed]
        return fixed

    # tlint: holds-lock(self._repair_lock)
    def _repair_locked(self, dead_plan_wid: str) -> str:
        validators = self.node.send_request("validators", timeout=10.0)
        if not validators:
            raise RuntimeError("no validator available for job repair")
        update = self.node.send_request(
            "control_request",
            {"peer": validators[0], "tag": proto.JOB_REPAIR,
             "body": {"job_id": self.job_id, "worker_id": dead_plan_wid},
             "timeout": 15.0},
            timeout=25.0,
        )
        if not isinstance(update, dict) or "worker" not in update:
            # the validator's MONITOR may have beaten this pull to the same
            # dead worker (its replace already rewrote the plan, so the
            # pull finds no stage to fix) — apply any pushed JOB_UPDATEs
            # sitting in our buffer and reuse that replacement. (Inline
            # rather than poll_job_updates(): we already hold _repair_lock.)
            try:
                for u in self.node.send_request("job_updates", timeout=10.0):
                    if u.get("job_id") == self.job_id and "worker" in u:
                        old = u.get("old_worker", "")
                        if old in self.workers and old not in self._repaired:
                            self._apply_update(u, old)
            except Exception as e:
                self.log.debug("job_updates scan during repair failed: %s", e)
            fixed = self._chase_repaired(dead_plan_wid)
            if fixed:
                return fixed
            raise RuntimeError(
                f"job repair failed: {update.get('error') if isinstance(update, dict) else update}"
            )
        return self._apply_update(update, dead_plan_wid)

    def _apply_update(self, update: dict, dead_plan_wid: str) -> str:
        new_id = update["worker"]["id"]
        host, port = update["worker"]["addr"]
        conn_id = self.node.connect_to(host, int(port))
        self.worker_addrs[new_id] = [host, int(port)]
        # order matters for concurrent readers: the new mapping must exist
        # before any stage names it; the old mapping stays (its connection
        # is dead, so a straggler request on it re-enters repair and gets
        # the recorded replacement)
        self.workers[new_id] = conn_id
        affected = [
            s for s in self.plan.stages if s.worker_id == dead_plan_wid
        ]
        for s in affected:
            s.worker_id = new_id
        self._repaired[dead_plan_wid] = new_id
        for s in affected:
            resp = self._request(
                new_id, proto.MODULE,
                {
                    "job_id": self.job_id,
                    "model": self.model_spec,
                    "stage": _stage_dict(s),
                    "training": self.training,
                },
                timeout=MAX_WAIT_TIME, _repaired=True,
            )
            if not resp.get("ok"):
                raise RuntimeError(f"replacement stage load failed: {resp}")
        # Restore training state consistently: a replacement stage loads
        # fresh checkpoint-reference weights, so if training has progressed
        # EVERY stage must roll back to the same snapshot — restoring only
        # the new worker would silently mix parameter versions across stages.
        if getattr(self, "_opt_ready", False):
            self._request(
                new_id, proto.OPTIMIZER,
                {"job_id": self.job_id, "op": "init",
                 "spec": {"name": getattr(self, "_opt_name", "adamw"),
                          "grad_clip": None,
                          **getattr(self, "_opt_spec", {})}},
                _repaired=True,
            )
            if getattr(self, "_last_ckpt", None):
                for s in self.plan.stages:
                    self._request(
                        s.worker_id, proto.CHECKPOINT,
                        {"job_id": self.job_id, "op": "restore",
                         "dir": self._last_ckpt},
                        _repaired=True,
                    )
                # roll the driver's step counter back to the snapshot so
                # the "lost at most ckpt_every_steps steps" contract holds
                # for the step accounting (and tags) too
                try:
                    import json
                    from pathlib import Path

                    manifest = json.loads(
                        (Path(self._last_ckpt) / "manifest.json").read_text()
                    )
                    self._step = int(manifest.get("step", getattr(self, "_step", 0)))
                except Exception as e:
                    self.log.warning(
                        "checkpoint manifest %s unreadable: %s",
                        self._last_ckpt, e,
                    )
            elif getattr(self, "_step", 0) > 0:
                raise RuntimeError(
                    "worker replaced mid-training with no checkpoint to roll "
                    "back to: trained state on surviving stages is "
                    "inconsistent with the fresh replacement stage — set "
                    "ckpt_every_steps (auto-checkpoint) or call "
                    "save_checkpoint() periodically to make repair lossless"
                )
        self.log.info(
            "repaired job %s: %s -> %s", self.job_id[:8],
            dead_plan_wid[:8], new_id[:8],
        )
        return new_id

    def poll_job_updates(self) -> int:
        """Apply validator-pushed replacements (monitor path); returns how
        many updates were applied."""
        updates = self.node.send_request("job_updates", timeout=10.0)
        n = 0
        for u in updates:
            if u.get("job_id") == self.job_id and "worker" in u:
                old = u.get("old_worker", "")
                with self._repair_lock:
                    if old in self.workers and old not in self._repaired:
                        self._apply_update(u, old)
                        n += 1
        return n

    # ------------------------------------------------------------------
    # forward (reference module.py:348-411 + OffloadedModule.forward:1536)
    # ------------------------------------------------------------------
    def forward(
        self,
        tokens: np.ndarray,  # int [B, T]
        attn_mask: np.ndarray | None = None,
        *,
        session: str | None = None,
        cache_len: int | None = None,
        sample: dict | None = None,
        last_idx: np.ndarray | None = None,
        reorder_idx: np.ndarray | None = None,
        reset_len: int | None = None,
        reset_rows: Sequence[int] | None = None,
        seq: int | None = None,
        trace: Sequence[str] | None = None,
    ) -> np.ndarray:
        """Chain the pipeline stages; returns logits ``[B, T, V]``.

        ``session`` keeps per-stage KV caches alive on the workers between
        calls (decode); omit it for stateless forward.

        ``sample`` ({temperature, top_k, top_p, seed, step}): the stage
        holding the head samples ON-WORKER and this returns token ids
        ``[B]`` instead of logits — the pipelined-decode path, which
        otherwise ships full-vocab logits host-side every token
        (``last_idx`` names each row's final real position at prefill).
        """
        assert self.plan is not None
        x = np.asarray(tokens, np.int32)
        body_common: dict[str, Any] = {"job_id": self.job_id}
        if session is not None:
            body_common["session"] = session
            body_common["cache_len"] = cache_len or self.spec["seq_len"]
            if seq is not None:
                # per-session op counter: workers dedup on it, which makes
                # RPC retries and duplicated frames idempotent
                body_common["seq"] = int(seq)
        if reorder_idx is not None:
            # beam search: each stage permutes its session cache rows to
            # follow their source beam BEFORE this step's attention — the
            # permutation rides the forward (and the worker chain), so no
            # extra per-stage round-trips
            body_common["reorder_idx"] = np.asarray(reorder_idx, np.int32)
        if reset_len is not None:
            # speculative decode: roll back the previous verify pass's
            # rejected cache positions before this step (same piggyback)
            body_common["reset_len"] = int(reset_len)
        if reset_rows:
            # slot admission (continuous batching on pipelined jobs):
            # recycle finished rows by zeroing their session-cache write
            # offsets on EVERY stage before this op's KV writes land
            body_common["reset_rows"] = [int(r) for r in reset_rows]
        if trace:
            # distributed-trace ids of the requests this session op admits
            # (core/trace.py): each stage worker records its hop under them
            body_common["trace"] = [str(t) for t in trace if t]
        if attn_mask is not None:
            body_common["attn_mask"] = np.asarray(attn_mask, bool)

        def samp_body(base: dict) -> dict:
            if sample is not None:
                base["sample"] = sample
                if last_idx is not None:
                    base["last_idx"] = np.asarray(last_idx, np.int32)
            return base

        if len(self.plan.stages) > 1 and all(
            s.worker_id in self.worker_addrs for s in self.plan.stages
        ) and not any(s.coworkers for s in self.plan.stages):
            # (merged stages take the per-hop path below — chain entries
            # address primaries only and would skip the coworker mirrors)
            # worker-to-worker chain: ONE request; activations hop straight
            # between stage workers and only the final result (token ids or
            # logits) returns here. Stateless calls fall back to the per-hop
            # path (which repairs workers) on transport failure; session
            # calls surface the error — a partially-prefilled session must
            # not be silently re-driven (double KV writes).
            try:
                return self._forward_chain(x, body_common, samp_body)
            except SessionLost:
                raise  # classified by _request — generate loops recover
            except Exception as e:
                # transport failures cross the IPC bridge as RemoteError
                # (stringified "TimeoutError: ..."/"ConnectionError: ...",
                # nodes/ipc.py) — match on text as well as type. Compute
                # errors re-raise. A session chain whose transport died
                # raises SessionLost: the per-hop fallback cannot help (a
                # mid-chain stage may already have absorbed this call's KV
                # writes) — the generate loop re-establishes the session.
                if not _transportish(e):
                    raise
                if session is not None:
                    raise SessionLost(None, e) from e
                self.log.warning(
                    "chained forward failed (%s); per-hop fallback", e
                )

        last = self.plan.stages[-1]
        head_on_last = last.last and last.holds_head
        out: np.ndarray | None = None
        for stage in self.plan.stages:
            body = dict(body_common, op="stage")
            if stage.first:
                body["tokens"] = x
            else:
                body["hidden"] = out
            if head_on_last and stage is last:
                body = samp_body(body)
            resp = self._request_mirrored(stage, proto.FORWARD, body)
            res = _head_result(resp)
            if res is not None:
                return res
            out = np.asarray(resp["out"])

        if not head_on_last:
            head_stage = next(s for s in self.plan.stages if s.holds_head)
            resp = self._request_mirrored(
                head_stage,
                proto.FORWARD,
                samp_body({"job_id": self.job_id, "op": "head", "hidden": out}),
            )
            res = _head_result(resp)
            if res is not None:
                return res
            out = np.asarray(resp["out"])
        return out

    def _forward_chain(self, x, body_common: dict, samp_body) -> np.ndarray:
        """One request drives the whole pipeline: each stage worker computes
        its slice and ships the hidden state DIRECTLY to the next stage's
        worker (nodes/roles.py::cmd_chain_send); the final hop (the head
        holder — looping back to stage 0 for tied embeddings) responds to
        this user. Per token that is stages+1 one-way transfers instead of
        2·stages, and the [B, T, d_model] activations never transit the
        user's link at all."""
        stages = self.plan.stages
        entries = [
            {"addr": list(self.worker_addrs[s.worker_id]), "head": False}
            for s in stages[1:]
        ]
        last = stages[-1]
        if not (last.last and last.holds_head):
            head_stage = next(s for s in stages if s.holds_head)
            entries.append(
                {"addr": list(self.worker_addrs[head_stage.worker_id]),
                 "head": True}
            )
        body = samp_body(dict(
            body_common, op="chain", chain=entries,
            reply_to=self.node.node_id, tokens=x,
        ))
        # session chains are safe to retry through _request: every hop
        # dedups on the op's seq and re-drives its cached output downstream,
        # so a retry after a lost reply reaches the final hop without any
        # stage re-absorbing KV writes. A dead worker raises SessionLost
        # (classified in _request) for the generate loop to recover.
        resp = self._request(stages[0].worker_id, proto.FORWARD, body)
        self.chain_forwards += 1
        res = _head_result(resp)
        if res is not None:
            return res
        return np.asarray(resp["out"])

    __call__ = forward

    # ------------------------------------------------------------------
    # generate (reference module.py:763-769, OffloadedModule.generate:1496)
    # ------------------------------------------------------------------
    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        *,
        max_new_tokens: int = 64,
        temperature: float | Sequence[float] = 0.0,
        top_k: int | Sequence[int] = 0,
        top_p: float | Sequence[float] = 1.0,
        eos_ids: Sequence[int] = (),
        seed: int = 0,
        stream_cb: Callable[[list[int | None]], None] | None = None,
        budgets: Sequence[int] | None = None,
        reuse_prefix: bool = False,
        lookahead: bool = False,
        presence_penalty: float | Sequence[float] = 0.0,
        frequency_penalty: float | Sequence[float] = 0.0,
        num_beams: int = 1,
        info_out: dict | None = None,
        continuous: bool = False,
        priority: str | None = None,
        trace_id: str | None = None,
        speculative: bool = False,
        handoff: bool = True,
        jrid: str = "",
    ) -> list[list[int]]:
        """``reuse_prefix`` (B=1, single-stage): the worker's engine seeds
        the cache from the longest stored prompt prefix and prefills only
        the suffix — conversation turns re-pay just the delta.

        ``stream_cb`` receives, per decode step, one new token per row
        (None for rows already finished) — the engine's contract. Sampling
        knobs may be per-row sequences and ``budgets`` caps rows
        individually (both used by the serving batcher, ml/batching.py, to
        mix concurrent requests in one decode) — on single-stage jobs via
        the engine's bucketed batch, on pipelined jobs via the head
        worker's per-row sampler."""
        assert self.plan is not None
        if any(s.coworkers for s in self.plan.stages):
            # the engine's host-driven loops launch from ONE controller;
            # on a merged (multi-process) mesh every member must launch
            # every program — the training path mirrors work items, the
            # serving loops do not (yet). Refuse instead of deadlocking at
            # the first collective.
            raise RuntimeError(
                "generation on a co-slice merged mesh is not supported — "
                "host the model without co_slice_planning for serving"
            )
        if self.plan.n_stages == 1:
            prompts = [list(p) for p in prompts]
            if (
                continuous
                and len(prompts) == 1
                and int(num_beams) <= 1
                and not lookahead
                and not any(
                    isinstance(v, (list, tuple))
                    for v in (temperature, top_k, top_p,
                              presence_penalty, frequency_penalty)
                )
            ):
                # continuous batching: this request joins the worker's
                # RUNNING slot batch instead of dispatching a static batch
                return self._generate_continuous_remote(
                    prompts[0], max_new_tokens=int(max_new_tokens),
                    temperature=float(temperature), top_k=int(top_k),
                    top_p=float(top_p), eos_ids=eos_ids, seed=int(seed),
                    stream_cb=stream_cb,
                    presence_penalty=float(presence_penalty or 0.0),
                    frequency_penalty=float(frequency_penalty or 0.0),
                    priority=priority,
                    trace_id=str(trace_id or ""),
                    speculative=bool(speculative),
                    handoff=bool(handoff),
                    jrid=str(jrid or ""),
                )
            return self._generate_remote(
                prompts, max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, eos_ids=eos_ids, seed=seed,
                stream_cb=stream_cb, budgets=budgets,
                reuse_prefix=reuse_prefix, lookahead=lookahead,
                presence_penalty=presence_penalty,
                frequency_penalty=frequency_penalty,
                num_beams=num_beams, info_out=info_out,
            )
        if int(num_beams) > 1:
            return self._generate_beam_pipelined(
                prompts, num_beams=int(num_beams),
                max_new_tokens=max_new_tokens, eos_ids=eos_ids,
            )

        if (
            lookahead and len(list(prompts)) == 1
            and not isinstance(temperature, (list, tuple))
            and float(temperature) <= 0.0
            and not _any_nonzero(presence_penalty)
            and not _any_nonzero(frequency_penalty)
        ):
            # prompt-lookup speculation on the PIPELINED path: per-token
            # cost here is dominated by the cross-stage hops, so accepted
            # drafts divide the number of round trips. Greedy B=1 only —
            # the emitted tokens are exactly the vanilla sequence.
            return self._generate_lookahead_pipelined(
                prompts, max_new_tokens=max_new_tokens, eos_ids=eos_ids,
                stream_cb=stream_cb,
            )
        return self._generate_pipelined(
            prompts, max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, eos_ids=eos_ids, seed=seed,
            stream_cb=stream_cb, budgets=budgets,
            presence_penalty=presence_penalty,
            frequency_penalty=frequency_penalty,
        )

    def _generate_remote(
        self, prompts, *, max_new_tokens, temperature, top_k, top_p,
        eos_ids, seed, stream_cb, budgets=None, reuse_prefix=False,
        lookahead=False, presence_penalty=0.0, frequency_penalty=0.0,
        num_beams=1, info_out=None,
    ) -> list[list[int]]:
        """Whole model on one worker → its compiled engine does the loop."""
        stage = self.plan.stages[0]
        def _wire(v):
            return list(v) if isinstance(v, (list, tuple)) else v
        body = {
            "job_id": self.job_id,
            "prompts": [list(map(int, p)) for p in prompts],
            "max_new_tokens": max_new_tokens,
            "num_beams": int(num_beams),
            "presence_penalty": _wire(presence_penalty),
            "frequency_penalty": _wire(frequency_penalty),
            "temperature": _wire(temperature),
            "top_k": _wire(top_k),
            "top_p": _wire(top_p),
            "eos_ids": list(eos_ids),
            "seed": seed,
        }
        if budgets:
            body["budgets"] = [int(b) for b in budgets]
        if reuse_prefix:
            body["reuse_prefix"] = True
        if lookahead:
            body["lookahead"] = True
        stream_id = None
        if stream_cb is not None:
            stream_id = secrets.token_hex(8)
            body["stream"] = stream_id

        if stream_id is None:
            resp = self._request(stage.worker_id, proto.GENERATE, body)
            # response metadata (e.g. the worker's num_beams clamp) fills
            # the CALLER's dict — an attribute on self would race the
            # batcher thread, which drives concurrent generates on this
            # same model without job.lock
            if info_out is not None:
                info_out.update(
                    {k: resp[k] for k in ("num_beams_used",) if k in resp}
                )
            return [list(map(int, s)) for s in resp["sequences"]]

        # streaming: issue the request in a thread so we can drain tokens
        import threading

        result: dict = {}

        def issue():
            try:
                result["resp"] = self._request(stage.worker_id, proto.GENERATE, body)
            except Exception as e:  # surfaced after the stream drains
                result["err"] = e

        t = threading.Thread(target=issue, daemon=True)
        t.start()
        B = len(prompts)
        cancelled: set[int] = set()
        notified: set[int] = set()
        drained: list[list[int]] = [[] for _ in range(B)]

        def feed(row_map: dict[int, int]) -> None:
            for i, tk_ in row_map.items():
                if 0 <= i < B:
                    drained[i].append(int(tk_))
            cancel = stream_cb([row_map.get(i) for i in range(B)])
            cancelled.update(int(i) for i in cancel or ())

        def push_cancels() -> None:
            # confirmed stop-sequence matches ride back to the worker as a
            # STREAM_CANCEL control frame; its compiled chunked decode polls
            # them at chunk boundaries and stops those rows early — overrun
            # past a stop is ≤ one chunk instead of the full token budget
            new = cancelled - notified
            if not new:
                return
            notified.update(new)
            try:
                self.node.send_request(
                    "send_control",
                    {"peer": self.workers[stage.worker_id],
                     "tag": proto.STREAM_CANCEL,
                     "body": {"stream": stream_id,
                              "rows": sorted(cancelled)}},
                    timeout=10.0,
                )
            # tlint: disable=TL005(best-effort cancel push — the chunk budget bound still applies)
            except Exception:
                pass  # best-effort: the budget bound still applies

        while True:
            tk = self.node.send_request(
                "next_tokens",
                {"stream": stream_id, "timeout": 30.0},
                timeout=35.0,
            )
            if tk.get("tokens"):
                # the worker streams (row, token) pairs; the relay buffer
                # may merge several decode steps into one drain, so start a
                # fresh emission whenever a row repeats
                cur: dict[int, int] = {}
                for r, tok in tk["tokens"]:
                    if r in cur:
                        feed(cur)
                        cur = {}
                    cur[int(r)] = int(tok)
                if cur:
                    feed(cur)
                push_cancels()
            if tk.get("done"):
                break
            if len(cancelled) >= B:
                # every row's downstream (stop filters) confirmed a cancel:
                # stop forwarding so the client stream closes NOW. The
                # STREAM_CANCEL backchannel (push_cancels above) stops the
                # worker's compiled loop at its next chunk boundary, so the
                # response arrives within ~one chunk of decode.
                break
            if tk.get("timeout") and not t.is_alive():
                break
        t.join(timeout=MAX_WAIT_TIME)
        if len(cancelled) >= B:
            # early break never observed the done marker, so the relay's
            # drop-on-done cleanup didn't run — release the buffer (the
            # worker has responded by now, so its trailing pushes landed)
            try:
                self.node.send_request(
                    "drop_stream", {"stream": stream_id}, timeout=10.0
                )
            # tlint: disable=TL005(best-effort buffer release — the relay's stale-stream bound reclaims it)
            except Exception:
                pass
        if "err" in result:
            raise result["err"]
        if "resp" not in result:
            if len(cancelled) >= B and any(drained):
                # cancelled early and the worker's compiled loop is still
                # burning its residual budget past MAX_WAIT_TIME: the
                # drained tokens already contain everything through the
                # stop match, which is all the caller will keep anyway
                return [list(map(int, s)) for s in drained]
            raise TimeoutError(
                "streamed generate: worker response did not arrive within "
                f"{MAX_WAIT_TIME}s"
            )
        return [list(map(int, s)) for s in result["resp"]["sequences"]]

    def _note_serving(self, resp: dict) -> None:
        """Keep the worker's latest slot-engine snapshot (occupancy +
        prefix-cache counters, riding each continuous GENERATE_RESP) so
        the validator's /stats endpoint can surface it through
        ContinuousBatcher.stats() without a polling RPC."""
        snap = resp.get("serving")
        if isinstance(snap, dict):
            self.cont_serving_stats = snap
        self._note_trace(resp)

    @staticmethod
    def _note_trace(resp: dict) -> None:
        """Merge the worker's span payload (riding GENERATE_RESP next to
        the serving snapshot) into this process's tracer — the stitch
        that makes ``GET /trace/<rid>`` show a request's spans from every
        worker it touched, including both sides of a live migration."""
        tr = resp.get("trace")
        if isinstance(tr, dict) and tr.get("id"):
            from tensorlink_tpu.core.trace import get_tracer

            get_tracer().ingest(str(tr["id"]), tr.get("spans") or [])

    def _merge_migrated_tokens(
        self, mig: dict, delivered_prior: list[int],
        seen_total: list[int], stream_cb,
    ) -> list[int]:
        """Reconcile a migrated stream's token state: the redirect's
        ``tokens_so_far`` is the authoritative list of everything the
        draining worker emitted THIS submission (fire-and-forget relay
        frames may have dropped some). Tokens the caller hasn't seen yet
        are fed to ``stream_cb`` here — exactly once, in order — BEFORE
        any re-pointing that could fail, so a later repair can never
        re-emit or lose them."""
        auth = [int(t) for t in mig.get("tokens_so_far") or []]
        merged = list(delivered_prior) + auth
        for tok in merged[len(seen_total):]:
            if stream_cb is not None:
                stream_cb([tok])
        return merged

    @staticmethod
    def _count_redirect(redirects: int, cap: int) -> int:
        """Bound migration-redirect hops for one request: tokens already
        merged are preserved (the caller raises AFTER merging), but a
        redirect cycle must fail loudly instead of bouncing forever."""
        if redirects + 1 > cap:
            raise RuntimeError(
                f"migration redirect loop: request bounced {cap} times "
                "(draining workers pointing at each other?)"
            )
        return redirects + 1

    def _attach_migrated(
        self, old_wid: str, mig: dict, *, rewrite_plan: bool = True
    ) -> str | None:
        """Re-point this job at a migration redirect's destination worker
        (connect, rewrite the plan stage, record the repair mapping so
        concurrent requests chase to it too). Returns the staged-adoption
        ticket id (None = plain re-prefill resume). An unreachable
        destination raises :class:`WorkerLost` — the caller's recovery
        path then pulls a validator replacement, the ladder's last rung.

        ``rewrite_plan=False`` is the steady-state prefill→decode handoff
        shape (the redirect carries ``handoff: true``): only THIS request
        follows to the destination — the plan keeps naming the prefill
        worker, which stays the admission point for every later request."""
        dest_id = str(mig.get("worker") or "")
        addr = list(mig.get("addr") or [])
        if not dest_id or len(addr) != 2:
            raise WorkerLost(
                old_wid, RuntimeError("malformed migration redirect")
            )
        # ALWAYS (re)dial: the net layer dedupes live connections by
        # address, and a stale cached peer id (the destination restarted,
        # a dropped link) would otherwise make every future redirect to
        # it fail with "no connection" forever — the steady-state handoff
        # path hits the same destination on every request, so a dead
        # cache entry must heal here. The dial happens OUTSIDE the
        # repair lock (dedupe makes concurrent dials safe): holding the
        # model-wide lock across a cross-process round trip would
        # serialize every concurrent request's redirect on a path that
        # is now per-request, not per-drain.
        try:
            conn_id = self.node.connect_to(addr[0], int(addr[1]))
        except Exception as e:
            raise WorkerLost(old_wid, e) from e
        with self._repair_lock:
            self.workers[dest_id] = conn_id
            self.worker_addrs[dest_id] = [addr[0], int(addr[1])]
            if rewrite_plan:
                for s in self.plan.stages:
                    if s.worker_id == old_wid:
                        s.worker_id = dest_id
                if old_wid != dest_id:
                    self._repaired[old_wid] = dest_id
        self.log.info(
            "stream %s %s -> %s (%s)",
            "handed off" if not rewrite_plan else "migrated",
            old_wid[:8], dest_id[:8],
            "page-shipped" if mig.get("mig") else "re-prefill resume",
        )
        return mig.get("mig") or None

    def _follow_redirect(
        self, wid: str, mig: dict, *, off_plan: bool = False
    ) -> tuple[str | None, str | None, bool]:
        """Follow a migration/handoff redirect. Returns ``(adopt,
        wid_override, retry_at_source)``: ``wid_override`` names the
        destination for a HANDOFF redirect (this request only — the plan
        keeps naming the prefill worker, the admission point), and
        ``retry_at_source=True`` means a handoff destination was
        unreachable — the prefill source is alive, so the caller simply
        resubmits there (fresh prefill; the worker retries or serves the
        stream locally) instead of escalating to validator repair.

        ``off_plan=True`` marks a redirect received while already
        decoding OFF the plan (at an earlier handoff's destination) —
        e.g. the decode worker itself draining. The plan rewrite finds
        no stage naming it, so the ticket's new home must ride the
        override: re-issuing at the plan's prefill worker would carry a
        ticket staged somewhere else entirely (it could never adopt)."""
        is_handoff = bool(mig.get("handoff"))
        try:
            adopt = self._attach_migrated(
                wid, mig, rewrite_plan=not is_handoff
            )
        except WorkerLost:
            if not is_handoff:
                raise  # drain ladder: recovery pulls a validator repair
            self.log.warning(
                "handoff destination %s unreachable; resubmitting at the "
                "prefill worker", str(mig.get("worker") or "")[:8],
            )
            return None, None, True
        follow = is_handoff or off_plan
        return adopt, (str(mig["worker"]) if follow else None), False

    def reattach_continuous(
        self, jrid: str, *, prompt, delivered=(), max_new_tokens: int,
        temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
        eos_ids=(), seed: int = 0, stream_cb=None,
        presence_penalty: float = 0.0, frequency_penalty: float = 0.0,
        priority: str | None = None, trace_id: str = "",
    ) -> list[int]:
        """Client half of the re-attach ladder (validator loss mid-decode,
        docs/FAILURE_MODEL.md "Control plane"). ``jrid`` is the journal
        rid the original request carried; ``delivered`` is every token the
        pre-crash client consumed (its high-water mark); the sampling
        knobs and ``max_new_tokens`` must repeat the ORIGINAL request's
        values. Rung 1 rebinds the worker's still-decoding slot (or
        replays its finished-orphan ledger) and tops up past the
        high-water mark exactly-once; a miss falls through on the worker
        to rung 2, the PR 8 re-prefill resume — both rungs bit-identical
        to the uninterrupted stream by the fold_in sampling contract."""
        out = self._generate_continuous_remote(
            [int(t) for t in prompt],
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p), eos_ids=eos_ids, seed=int(seed),
            stream_cb=stream_cb,
            presence_penalty=float(presence_penalty or 0.0),
            frequency_penalty=float(frequency_penalty or 0.0),
            priority=priority, trace_id=str(trace_id or ""),
            jrid=str(jrid), reattach=str(jrid),
            _delivered=[int(t) for t in delivered],
        )
        return out[0]

    def _generate_continuous_remote(
        self, prompt: list[int], *, max_new_tokens: int, temperature: float,
        top_k: int, top_p: float, eos_ids, seed: int, stream_cb,
        presence_penalty: float, frequency_penalty: float,
        priority: str | None = None, trace_id: str = "",
        speculative: bool = False, handoff: bool = True,
        jrid: str = "", reattach: str = "",
        _delivered: list[int] | None = None,
    ) -> list[list[int]]:
        """One request through the worker's continuous slot engine
        (B=1 per RPC; the worker co-batches concurrent requests into its
        slot batch at chunk boundaries).

        Recovery keeps PR 1's re-prefill semantics on paged slots: a lost
        worker triggers repair, then the request re-submits with prompt =
        original prompt + every token already DELIVERED and start_step =
        len(delivered). The slot engine's per-token keys are
        ``fold_in(PRNGKey(seed), n)`` — stateless in n — so the resumed
        stream continues bit-identically: no duplicated, no missing
        tokens, and the replacement worker's fresh page allocator can't
        hand this session another session's KV blocks."""
        # a re-attach (validator recovery) pre-seeds delivered with what
        # the pre-crash client already consumed — its high-water mark
        delivered: list[int] = [int(t) for t in (_delivered or [])]
        recoveries = 0
        MAX_RECOVERIES = 3
        adopt: str | None = None  # staged-migration ticket on the dest
        redirects = 0
        # redirect hops are bounded separately from crash recoveries: a
        # drain cycle (A drained onto B, B later drained onto A before A
        # was stopped) must surface as an error, not an infinite bounce
        MAX_REDIRECTS = 8
        # a prefill→decode HANDOFF redirect moves only THIS request: the
        # override names the decode worker to re-issue at while the plan
        # keeps naming the prefill worker (the admission point)
        wid_override: str | None = None
        while True:
            # capture the id this attempt ISSUES to: a concurrent request's
            # repair may rewrite the plan mid-flight, and recovery must
            # repair the worker that actually failed us — _repair's chase
            # map then reuses the concurrent thread's replacement instead
            # of trying to "replace" the live one
            wid = wid_override or self.plan.stages[0].worker_id
            budget = int(max_new_tokens) - len(delivered)
            if budget <= 0:
                return [delivered]
            body = {
                "job_id": self.job_id,
                "prompts": [[int(t) for t in prompt] + delivered],
                "max_new_tokens": budget,
                "start_step": len(delivered),
                "continuous": True,
                "temperature": temperature, "top_k": top_k, "top_p": top_p,
                "presence_penalty": presence_penalty,
                "frequency_penalty": frequency_penalty,
                "eos_ids": list(eos_ids), "seed": int(seed),
            }
            if jrid:
                # the journal rid rides every attempt: the worker keys its
                # live-stream / orphan ledgers on it, which is what makes
                # the re-attach ladder (and validator-recovery
                # reconciliation) possible at all
                body["jrid"] = jrid
            if reattach:
                # re-attach ladder rung 1: ask the worker to rebind the
                # still-decoding (or finished-orphaned) stream and top up
                # past our high-water mark. A MISS falls through to plain
                # admission of THIS body — which already carries
                # prompt+delivered / start_step, i.e. rung 2 (re-prefill
                # resume) — on the worker, with no extra round trip.
                body["reattach"] = reattach
                body["hwm"] = len(delivered)
            if priority:
                # the worker's scheduler reads the class off the wire; an
                # old worker simply ignores the extra key (FCFS for it)
                body["priority"] = str(priority)
            if speculative:
                # draft/verify opt-in: the worker's engine packs draft
                # rows when its spec_decode is on; streams bit-identical
                # either way, so an ignoring worker changes nothing
                body["speculative"] = True
            if not handoff:
                # per-request opt-out of the prefill→decode handoff on a
                # disaggregated pool (the default is to follow the
                # worker's role); absence of the key means opted in
                body["handoff"] = False
            if trace_id:
                # the trace id rides the GENERATE frame: the worker's
                # engine records its spans under it and ships them back on
                # the response (docs/SERVING.md "Telemetry")
                body["trace"] = trace_id
            if adopt:
                # resume-after-migration: the destination staged our KV
                # pages under this ticket — admission binds them instead
                # of re-prefilling (and quietly falls back if it can't)
                body["adopt"] = adopt
            try:
                if stream_cb is None:
                    resp = self._request(
                        wid, proto.GENERATE, body, _repaired=True
                    )
                    self._note_serving(resp)
                    mig = resp.get("migrated")
                    if mig is not None:
                        # the worker is draining (or handing our freshly
                        # prefilled slot to the decode pool): top up
                        # delivered from the authoritative list, re-point
                        # at the destination, and re-issue there
                        delivered = self._merge_migrated_tokens(
                            mig, delivered, delivered, None
                        )
                        redirects = self._count_redirect(redirects,
                                                         MAX_REDIRECTS)
                        adopt, wid_override, retry = \
                            self._follow_redirect(
                                wid, mig,
                                off_plan=wid_override is not None,
                            )
                        if retry:
                            # the destination is unreachable FROM US
                            # (asymmetric routing) even though the
                            # prefill worker can ship to it — opt the
                            # resubmission out of handoff, or the worker
                            # would bounce us to the same dead end until
                            # the redirect cap drops the stream
                            recoveries += 1
                            handoff = False
                        continue
                    seq = [int(t) for t in resp["sequences"][0]]
                    if resp.get("reattached"):
                        # a re-attach HIT: sequences is the ORIGINAL
                        # submission's full token list (everything since
                        # its start_step = resume_base) — merge it onto
                        # the prefix delivered BEFORE that submission, or
                        # the overlap would be double-counted
                        base = int(resp.get("resume_base", 0))
                        return [delivered[:base] + seq]
                    return [delivered + seq]
                out, finished, mig = self._drain_continuous_stream(
                    wid, body, delivered, stream_cb
                )
                if mig is not None:
                    delivered = self._merge_migrated_tokens(
                        mig, delivered, out, stream_cb
                    )
                    redirects = self._count_redirect(redirects,
                                                     MAX_REDIRECTS)
                    adopt, wid_override, retry = \
                        self._follow_redirect(
                            wid, mig, off_plan=wid_override is not None,
                        )
                    if retry:
                        # see above: client-unreachable destination —
                        # pin the resubmission to the prefill worker
                        recoveries += 1
                        handoff = False
                    continue
                if finished:
                    return [out]
                delivered = out  # resume from what the relay delivered
                raise WorkerLost(wid, RuntimeError("stream interrupted"))
            except Exception as e:
                # ONLY a dead connection means the worker (and its slots)
                # are gone — a plain RPC timeout may just be a long decode
                # queued behind a busy slot batch, and "repairing" the live
                # worker for it would re-ship its stage and disturb every
                # other session it serves (the static path draws the same
                # line)
                recoverable = isinstance(e, WorkerLost) \
                    or "no connection" in str(e)
                if not recoverable or recoveries >= MAX_RECOVERIES:
                    raise
                recoveries += 1
                if wid_override is not None and all(
                    s.worker_id != wid for s in self.plan.stages
                ):
                    # the handoff DESTINATION died mid-decode. The
                    # admission point (the plan's prefill worker) is not
                    # implicated — resubmit there with a dead ticket
                    # dropped, instead of "repairing" a healthy worker
                    # (which would re-recruit and re-ship its stage)
                    self.log.warning(
                        "handoff destination lost mid-decode (%s); "
                        "resubmitting prompt + %d delivered tokens at "
                        "the prefill worker (recovery %d/%d)",
                        e, len(delivered), recoveries, MAX_RECOVERIES,
                    )
                    wid_override = None
                    adopt = None
                    # the decode pool just ate our stream once — decode
                    # the resubmission at the admission point instead of
                    # letting the worker's (possibly stale) readiness
                    # cache bounce it toward the same dead destination
                    handoff = False
                    continue
                wid_override = None
                adopt = None
                self.log.warning(
                    "continuous generate lost its worker (%s); re-prefilling "
                    "prompt + %d delivered tokens on a replacement "
                    "(recovery %d/%d)",
                    e, len(delivered), recoveries, MAX_RECOVERIES,
                )
                self._repair(wid)

    def _drain_continuous_stream(
        self, wid: str, body: dict, delivered: list[int], stream_cb
    ) -> tuple[list[int], bool, dict | None]:
        """Issue a streamed continuous GENERATE and drain its relay.
        Returns ``(tokens_so_far, finished, migrated)`` —
        ``finished=False`` with ``migrated=None`` means the worker died
        mid-stream and the caller should resume from ``tokens_so_far`` on
        a replacement; a non-None ``migrated`` dict means the worker
        DRAINED and redirected this stream (live slot migration) — the
        caller re-points at the named destination."""
        import threading

        stream_id = secrets.token_hex(8)
        body = dict(body, stream=stream_id)
        result: dict = {}

        def issue():
            try:
                result["resp"] = self._request(
                    wid, proto.GENERATE, body, _repaired=True
                )
            except Exception as e:
                result["err"] = e

        t = threading.Thread(target=issue, daemon=True)
        t.start()
        toks = list(delivered)
        notified = False
        while True:
            tk = self.node.send_request(
                "next_tokens", {"stream": stream_id, "timeout": 5.0},
                timeout=10.0,
            )
            for _row, tok in tk.get("tokens") or ():
                toks.append(int(tok))
                cancel = stream_cb([int(tok)])
                if cancel and not notified:
                    # confirmed stop match: the worker's slot engine stops
                    # this request at its next emitted token (cancel polls
                    # ride the chunk cadence)
                    notified = True
                    try:
                        self.node.send_request(
                            "send_control",
                            {"peer": self.workers[wid],
                             "tag": proto.STREAM_CANCEL,
                             "body": {"stream": stream_id, "rows": [0]}},
                            timeout=10.0,
                        )
                    # tlint: disable=TL005(best-effort cancel push — the chunk budget bound still applies)
                    except Exception:
                        pass  # best-effort; the budget bound still applies
            if tk.get("done"):
                break
            if tk.get("timeout") and not t.is_alive():
                break  # issuer finished (response or death) with no marker
        t.join(timeout=MAX_WAIT_TIME)
        if "resp" not in result:
            # worker died mid-stream: scoop any frames that beat the crash
            # onto the relay AFTER our last drain, so the resumed request
            # can't re-emit a token the caller already saw
            try:
                tk = self.node.send_request(
                    "next_tokens", {"stream": stream_id, "timeout": 0.5},
                    timeout=5.0,
                )
                for _row, tok in tk.get("tokens") or ():
                    toks.append(int(tok))
                    stream_cb([int(tok)])
            # tlint: disable=TL005(draining trailing tokens of a finished stream — the worker may be gone)
            except Exception:
                pass
        try:
            self.node.send_request(
                "drop_stream", {"stream": stream_id}, timeout=10.0
            )
        # tlint: disable=TL005(best-effort buffer release — the relay's stale-stream bound reclaims it)
        except Exception:
            pass
        if "resp" in result:
            # the response is authoritative (fire-and-forget stream frames
            # may drop); it holds THIS submission's tokens only
            self._note_serving(result["resp"])
            mig = result["resp"].get("migrated")
            if mig is not None:
                # drained mid-stream: hand the redirect up with what the
                # relay delivered so far (the migrated body's
                # tokens_so_far is the authoritative top-up source)
                return toks, False, mig
            resp = result["resp"]
            seq = [int(x) for x in resp["sequences"][0]]
            if resp.get("reattached"):
                # re-attach HIT: sequences spans the ORIGINAL submission
                # (since resume_base) — merge onto the prefix delivered
                # before it, not onto everything we've seen (overlap)
                base = int(resp.get("resume_base", 0))
                return delivered[:base] + seq, True, None
            return delivered + seq, True, None
        err = result.get("err")
        if err is not None and "no connection" not in str(err):
            # compute errors and plain timeouts surface to the caller —
            # only a dead connection licenses the resume-on-replacement
            raise err
        return toks, False, None

    def _generate_pipelined(
        self, prompts, *, max_new_tokens, temperature, top_k=0, top_p=1.0,
        eos_ids=(), seed=0, stream_cb=None, budgets=None,
        presence_penalty=0.0, frequency_penalty=0.0,
    ) -> list[list[int]]:
        """Host-driven decode across stages with per-stage session caches
        (net-new vs the reference, which cannot generate across shards
        without re-running the full forward per token). Sampling knobs may
        be per-row sequences and ``budgets`` caps rows individually — the
        serving batcher co-batches mixed requests on pipelined jobs too.
        Presence/frequency penalties ride the session: the head-holding
        worker keeps the [B, V] context counts across steps
        (ml/worker.py::_sample_from_logits)."""
        prompts = [list(map(int, p)) for p in prompts]
        B = len(prompts)
        T = max(len(p) for p in prompts)
        toks = np.zeros((B, T), np.int32)
        mask = np.zeros((B, T), bool)
        for i, p in enumerate(prompts):
            toks[i, : len(p)] = p
            mask[i, : len(p)] = True

        session = secrets.token_hex(8)
        cache_len = min(self.spec["seq_len"], T + max_new_tokens)
        eos = set(int(e) for e in eos_ids)
        per_row = any(
            isinstance(v, (list, tuple))
            for v in (temperature, top_k, top_p,
                      presence_penalty, frequency_penalty)
        )
        # validate BEFORE anything indexes per-row lists (a short budgets
        # list must raise this message, not an IndexError below)
        for name, v in (("temperature", temperature), ("top_k", top_k),
                        ("top_p", top_p), ("budgets", budgets),
                        ("presence_penalty", presence_penalty),
                        ("frequency_penalty", frequency_penalty)):
            if isinstance(v, (list, tuple)) and len(v) != B:
                raise ValueError(
                    f"per-row {name} has {len(v)} entries for {B} prompts"
                )
        # per-row effective budgets, each capped by its OWN cache room so a
        # long-prompt neighbor can't overrun a short one's slots
        eff = []
        for i, p in enumerate(prompts):
            want = int(budgets[i]) if budgets else int(max_new_tokens)
            eff.append(max(min(want, cache_len - len(p)), 0))
        steps = max(eff) if eff else 0

        def rows(v, cast):
            # all-or-none: if ANY knob is per-row, normalize EVERY knob to a
            # length-B list so the worker builds aligned [B, 1] leaves
            if not per_row:
                return cast(v)
            if isinstance(v, (list, tuple)):
                return [cast(x) for x in v]
            return [cast(v)] * B

        # the head-holding worker samples on-device and ships ONE token id
        # per row per step — not [B, vocab] logits across every hop (at a
        # 151k vocab that transfer alone was ~600 KB/token). Per-row knobs
        # ride as lists (worker builds [B, 1] SamplingParams leaves).
        samp = {
            "temperature": rows(temperature, float),
            "top_k": rows(top_k, int),
            "top_p": rows(top_p, float),
            "presence_penalty": rows(presence_penalty, float),
            "frequency_penalty": rows(frequency_penalty, float),
            "seed": int(seed),
        }

        penalized = (
            _any_nonzero(presence_penalty) or _any_nonzero(frequency_penalty)
        )
        samp0 = dict(samp, step=0)
        if penalized:
            # the head-holding worker sees hidden states, not token ids —
            # ship the prompt once so it can seed the session's [B, V]
            # context counts (subsequent steps fold sampled tokens in
            # worker-side; nothing else crosses per step)
            samp0["prompt_tokens"] = toks
            samp0["prompt_mask"] = mask
        last_idx = mask.sum(-1) - 1

        seqs: list[list[int]] = [[] for _ in range(B)]
        # session/seq state shared with the recovery closures; every session
        # op carries a monotonically-increasing seq so RPC retries and
        # duplicated frames are idempotent on the workers
        state = {"session": session, "seq": 0, "recoveries": 0}
        MAX_RECOVERIES = 3

        def reestablish(step_idx: int):
            """In-flight session recovery: a stage worker died mid-decode.
            Repair every dead stage (validator recruits replacements and
            re-ships their stage slices), drop session remnants on the
            survivors, then re-prefill prompt + tokens-emitted-so-far under
            a FRESH session id. The re-prefilled logits at each row's last
            position equal the incremental decode logits, and the sampler
            key depends only on (seed, step) — so the resumed stream is
            bit-identical to the fault-free run: no duplicated, no missing
            tokens."""
            live = set(self.node.send_request("peers", timeout=10.0))
            for st in self.plan.stages:
                if self.workers.get(st.worker_id) not in live:
                    self._repair(st.worker_id)
            self._end_decode_session(state["session"])
            state["session"] = secrets.token_hex(8)
            rows = [prompts[i] + seqs[i] for i in range(B)]
            lens = np.asarray([len(r) for r in rows], np.int64)
            toks2 = np.zeros((B, int(lens.max())), np.int32)
            mask2 = np.zeros_like(toks2, bool)
            for i, r in enumerate(rows):
                toks2[i, : len(r)] = r
                mask2[i, : len(r)] = True
            samp_r = dict(samp, step=step_idx)
            if penalized:
                # counts at step s = prompt + everything emitted before s —
                # exactly these rows' histogram
                samp_r["prompt_tokens"] = toks2
                samp_r["prompt_mask"] = mask2
            out = self.forward(
                toks2, mask2, session=state["session"], cache_len=cache_len,
                sample=samp_r, last_idx=(lens - 1).astype(np.int32), seq=0,
            )
            state["seq"] = 1
            return out

        def next_tok(step_idx: int, step_tok):
            """The token of sampling step ``step_idx`` — via prefill
            (step 0), an incremental decode step, or session
            re-establishment after a lost worker."""
            mode = "prefill" if step_tok is None else "decode"
            while True:
                try:
                    if mode == "decode":
                        out = self.forward(
                            step_tok[:, None].astype(np.int32),
                            session=state["session"], cache_len=cache_len,
                            sample=dict(samp, step=step_idx),
                            seq=state["seq"],
                        )
                        state["seq"] += 1
                        return out
                    if mode == "prefill":
                        out = self.forward(
                            toks, mask, session=state["session"],
                            cache_len=cache_len, sample=samp0,
                            last_idx=last_idx, seq=0,
                        )
                        state["seq"] = 1
                        return out
                    return reestablish(step_idx)
                except Exception as e:
                    recoverable = isinstance(e, SessionLost) or _transportish(e)
                    if not recoverable or state["recoveries"] >= MAX_RECOVERIES:
                        raise
                    state["recoveries"] += 1
                    self.log.warning(
                        "decode session lost (%s); re-establishing on live "
                        "workers (recovery %d/%d)",
                        e, state["recoveries"], MAX_RECOVERIES,
                    )
                    mode = "reestablish"

        try:
            tok = next_tok(0, None)
            done = np.asarray([e <= 0 for e in eff], bool)
            for step in range(steps):
                emitted: list[int | None] = []
                for i in range(B):
                    if not done[i]:
                        seqs[i].append(int(tok[i]))
                        emitted.append(int(tok[i]))
                    else:
                        emitted.append(None)
                    done[i] |= int(tok[i]) in eos or len(seqs[i]) >= eff[i]
                if stream_cb is not None and any(
                    e is not None for e in emitted
                ):
                    # the callback may return row indices to CANCEL
                    # (confirmed stop-sequence matches): those rows stop
                    # decoding NOW — the pipelined loop is host-driven, so
                    # a stop saves the remaining per-token stage hops
                    # instead of burning the full budget
                    cancel = stream_cb(emitted)
                    for i in cancel or ():
                        if 0 <= int(i) < B:
                            done[int(i)] = True
                if done.all() or step == steps - 1:
                    break
                tok = next_tok(step + 1, tok)
            return seqs
        finally:
            # also on failure paths (exhausted recoveries, compute errors):
            # surviving stages must not leak the session KV + dedup ledger
            self._end_decode_session(state["session"])

    def _end_decode_session(self, session: str) -> None:
        """Drop a session's KV caches (and seq-dedup ledger) on every stage
        worker; best-effort — a dead worker's cache died with it."""
        for stage in self.plan.stages:
            try:
                self._request(
                    stage.worker_id, proto.FORWARD,
                    {"job_id": self.job_id, "op": "end_session",
                     "session": session},
                    timeout=10.0,
                )
            # tlint: disable=TL005(session teardown fanout — a dead stage has no session left to end)
            except Exception:
                pass

    def _generate_beam_pipelined(
        self, prompts, *, num_beams: int, max_new_tokens: int,
        eos_ids=(), length_penalty: float = 1.0,
    ) -> list[list[int]]:
        """Beam search across PIPELINED stages (B=1): the K beams ride the
        session batch axis, the head-holding worker ships K x (K+n_eos)
        candidate (score, id) pairs per step from an on-device top-k
        (never [K, V] logits), the host frontier logic is shared with the
        engine session (engine/generate.py::beam_frontier_step), and each
        step reorders every stage's session cache rows to follow their
        source beam. Closes the r4 'beam needs single-stage' gap —
        BASELINE configs 4-5 (70B/Mixtral) live on this path."""
        from tensorlink_tpu.engine.generate import beam_frontier_step

        prompts = [list(map(int, p)) for p in prompts]
        if len(prompts) != 1:
            raise ValueError("beam search is B=1")
        K = int(num_beams)
        if K < 1:
            raise ValueError("num_beams must be >= 1")
        prompt = prompts[0]
        eos_set = set(int(e) for e in eos_ids)
        cache_len = min(self.spec["seq_len"], len(prompt) + max_new_tokens)
        room = min(max_new_tokens, cache_len - len(prompt))
        if room <= 0:
            return [[]]
        session = secrets.token_hex(8)
        samp = {"beam_k": K, "beam_n_eos": len(eos_set)}
        # K identical prompt rows prefill K identical session caches (the
        # engine-side session prefills once and tiles; across stages the
        # batched identical-row prefill is numerically the same cache)
        toks = np.tile(np.asarray(prompt, np.int32), (K, 1))
        mask = np.ones((K, len(prompt)), bool)
        last_idx = np.full((K,), len(prompt) - 1, np.int32)
        try:
            vals, idx = self.forward(
                toks, mask, session=session, cache_len=cache_len,
                sample=samp, last_idx=last_idx,
            )
            row_v = np.asarray(vals)[0]
            row_i = np.asarray(idx)[0]
            scores = row_v[:K].astype(np.float64)
            beams = [[int(t)] for t in row_i[:K]]
            alive = [int(t) not in eos_set for t in row_i[:K]]
            done_pool: list[tuple[float, list[int]]] = []
            for k, b in enumerate(beams):
                if not alive[k]:
                    done_pool.append((scores[k] / 1.0, b))
            tok = np.asarray([b[-1] for b in beams], np.int32)
            pending_src: list[int] | None = None
            for _step in range(1, room):
                if not any(alive):
                    break
                vals, idx = self.forward(
                    tok[:, None], session=session, cache_len=cache_len,
                    sample=samp,
                    reorder_idx=(
                        np.asarray(pending_src, np.int32)
                        if pending_src is not None else None
                    ),
                )
                nxt = beam_frontier_step(
                    beams, scores, alive, done_pool,
                    np.asarray(vals), np.asarray(idx), K,
                    eos_set, room, length_penalty,
                )
                if nxt is None:
                    break
                beams, scores, alive, src = nxt
                # identity permutations (stable frontier) skip the gather
                pending_src = None if src == list(range(K)) else src
                tok = np.asarray([b[-1] for b in beams], np.int32)
            for k in range(K):
                if alive[k]:
                    done_pool.append(
                        (scores[k] / (len(beams[k]) ** length_penalty),
                         beams[k])
                    )
            _score, best = max(done_pool, key=lambda d: d[0])
            return [best]
        finally:
            self._end_decode_session(session)

    def _generate_lookahead_pipelined(
        self, prompts, *, max_new_tokens: int, eos_ids=(),
        n_draft: int = 8, stream_cb=None,
    ) -> list[list[int]]:
        """Greedy decode with prompt-lookup speculation across PIPELINED
        stages (B=1): draft from the token history's own n-grams
        (engine/generate.py::_lookup_draft — longest suffix first), verify
        the whole draft in ONE multi-token session forward (the head
        worker ships per-position argmax ids), keep the matched prefix +
        correction, and roll back rejected cache positions via a
        length-reset that rides the next forward. Emits EXACTLY the
        vanilla greedy sequence; every accepted token is one fewer
        full-pipeline round trip."""
        from tensorlink_tpu.engine.generate import GenerationEngine

        prompts = [list(map(int, p)) for p in prompts]
        if len(prompts) != 1:
            raise ValueError("lookahead decode is B=1")
        prompt = prompts[0]
        eos_set = set(int(e) for e in eos_ids)
        cache_len = min(self.spec["seq_len"], len(prompt) + max_new_tokens)
        limit = min(max_new_tokens, cache_len - len(prompt))
        if limit <= 0:
            return [[]]
        session = secrets.token_hex(8)
        lookup = GenerationEngine._lookup_draft
        try:
            toks = np.asarray([prompt], np.int32)
            mask = np.ones((1, len(prompt)), bool)
            # prefill: greedy sample of the last position (existing mode)
            tok = int(self.forward(
                toks, mask, session=session, cache_len=cache_len,
                sample={"temperature": 0.0, "seed": 0, "step": 0},
                last_idx=np.asarray([len(prompt) - 1], np.int32),
            )[0])
            history = list(prompt) + [tok]
            seq = [tok]
            if stream_cb is not None:
                stream_cb([tok])
            cur_len = len(prompt)  # cache rows written past the prompt
            # pending rollback: set AFTER a verify pass, applied on the
            # next forward (piggybacked reset_len)
            pending_reset: int | None = None
            while len(seq) < limit and tok not in eos_set:
                remaining = limit - len(seq)
                k = min(n_draft, remaining - 1, cache_len - cur_len - 1 - 1)
                draft = lookup(history, k) if k > 0 else []
                pad_to = len(draft)
                if cur_len + 1 + n_draft + 1 <= cache_len:
                    # FIXED [1, 1+n_draft] verify shape whenever the cache
                    # has room — variable lengths would compile one stage
                    # program per length on every worker
                    pad_to = n_draft if draft else 0
                step_toks = np.zeros((1, 1 + pad_to), np.int32)
                step_toks[0, 0] = tok
                step_toks[0, 1 : 1 + len(draft)] = draft
                targets = self.forward(
                    step_toks, session=session, cache_len=cache_len,
                    sample={"verify": True},
                    reset_len=pending_reset,
                )[0]
                base = cur_len if pending_reset is None else pending_reset
                cur_len = base + step_toks.shape[1]
                accepted = 0
                while (
                    accepted < len(draft)
                    and draft[accepted] == int(targets[accepted])
                ):
                    if draft[accepted] in eos_set:
                        break
                    accepted += 1
                emitted = list(draft[:accepted]) + [int(targets[accepted])]
                pending_reset = base + 1 + accepted
                taken: list[int] = []
                for t in emitted:
                    seq.append(t)
                    history.append(t)
                    taken.append(t)
                    tok = t
                    if t in eos_set or len(seq) >= limit:
                        break
                cancelled = False
                if stream_cb is not None and taken:
                    for t in taken:  # per-token callback contract
                        if stream_cb([t]):
                            cancelled = True  # confirmed stop match (B=1)
                if cancelled or tok in eos_set:
                    break
            return [seq[:limit]]
        finally:
            self._end_decode_session(session)

    # ------------------------------------------------------------------
    # training (reference module.py:348-524 micro-batch threads + autograd
    # router; here: explicit vjp tags + token-weighted accumulation that
    # matches engine/training.py::make_train_step exactly)
    # ------------------------------------------------------------------
    def _train_forward(self, tokens, attn_mask, tag: str) -> Any:
        """Forward chain with train=True; workers record vjps under ``tag``.
        Returns logits (jax array on the user process)."""
        import jax.numpy as jnp

        x = np.asarray(tokens, np.int32)
        out = None
        for stage in self.plan.stages:
            body = {"job_id": self.job_id, "op": "stage", "train": True,
                    "tag": tag}
            if attn_mask is not None:
                body["attn_mask"] = np.asarray(attn_mask, bool)
            if stage.first:
                body["tokens"] = x
            else:
                body["hidden"] = out
            resp = self._request_mirrored(stage, proto.FORWARD, body)
            out = np.asarray(resp["out"])
        last = self.plan.stages[-1]
        if not (last.last and last.holds_head):
            head_stage = next(s for s in self.plan.stages if s.holds_head)
            resp = self._request_mirrored(
                head_stage, proto.FORWARD,
                {"job_id": self.job_id, "op": "head", "hidden": out,
                 "train": True, "tag": tag},
            )
            out = np.asarray(resp["out"])
        return jnp.asarray(out)

    def _train_backward(self, dlogits, tag: str) -> None:
        """Reverse chain: cotangents flow last→first (head hop first when
        the head lives on stage 0)."""
        g = np.asarray(dlogits)
        last = self.plan.stages[-1]
        if not (last.last and last.holds_head):
            head_stage = next(s for s in self.plan.stages if s.holds_head)
            resp = self._request_mirrored(
                head_stage, proto.BACKWARD,
                {"job_id": self.job_id, "op": "head", "tag": tag, "grad": g},
            )
            g = np.asarray(resp["grad"])
        for stage in reversed(self.plan.stages):
            resp = self._request_mirrored(
                stage, proto.BACKWARD,
                {"job_id": self.job_id, "op": "stage", "tag": tag, "grad": g},
            )
            if "grad" in resp:
                g = np.asarray(resp["grad"])

    def init_optimizer(self, name: str = "adamw", **spec) -> None:
        """Fan the optimizer spec out to every stage (reference
        create_distributed_optimizer init, ml/optim.py:81-129).

        Gradient clipping is handled by the DRIVER, not per-stage: each
        stage clipping by its own norm would diverge from the reference
        single-program semantics, so workers get grad_clip=None and the
        driver folds ``min(1, clip/global_norm)`` into the step scale."""
        self._grad_clip = spec.pop("grad_clip", 1.0)
        self._opt_name, self._opt_spec = name, dict(spec)
        for stage in self.plan.stages:
            self._request_mirrored(
                stage, proto.OPTIMIZER,
                {"job_id": self.job_id, "op": "init",
                 "spec": {"name": name, "grad_clip": None, **spec}},
            )
        self._opt_ready = True

    def _global_grad_norm(self, scale: float = 1.0) -> float:
        sq = 0.0
        for stage in self.plan.stages:
            resp = self._request_mirrored(
                stage, proto.OPTIMIZER,
                {"job_id": self.job_id, "op": "grad_norm"},
            )
            sq += float(resp.get("grad_norm", 0.0)) ** 2
        return (sq**0.5) * scale

    def optimizer_step(self, scale: float = 1.0) -> dict:
        """Apply accumulated gradients on every stage; returns the global
        grad norm (of the scaled, pre-clip gradients — same number the
        compiled train step reports)."""
        gnorm = self._global_grad_norm(scale)
        final_scale = scale
        clip = getattr(self, "_grad_clip", None)
        if clip and gnorm > clip:
            final_scale = scale * clip / gnorm
        # once ANY stage has applied its update, a failure leaves the stages
        # on mixed parameter versions — recovery must roll back to the last
        # checkpoint, not merely re-drive (train_step/_recover_training)
        self._opt_step_partial = True
        for stage in self.plan.stages:
            self._request_mirrored(
                stage, proto.OPTIMIZER,
                {"job_id": self.job_id, "op": "step", "scale": final_scale},
            )
        self._opt_step_partial = False
        return {"grad_norm": gnorm}

    def zero_grad(self) -> None:
        for stage in self.plan.stages:
            self._request_mirrored(
                stage, proto.OPTIMIZER,
                {"job_id": self.job_id, "op": "zero"},
            )

    def train_step(
        self,
        tokens: np.ndarray,  # int [B, T]
        loss_mask: np.ndarray | None = None,  # bool [B, T]
        attn_mask: np.ndarray | None = None,
        *,
        step_optimizer: bool = True,
        overlap: bool = True,
    ) -> dict:
        """One durable training step: drives :meth:`_train_step_once` and,
        when a stage worker dies mid-step (:class:`WorkerLost`), repairs the
        dead stages — the replacement restores params AND optimizer state
        from ``_last_ckpt`` (auto-written every ``ckpt_every_steps``) and
        the driver's step counter rolls back to the snapshot — then
        re-drives the whole step from clean gradients. A mid-fine-tune kill
        therefore loses at most ``ckpt_every_steps`` steps, never a partial
        gradient."""
        self._step_active = True
        try:
            for attempt in range(2):
                try:
                    out = self._train_step_once(
                        tokens, loss_mask, attn_mask,
                        step_optimizer=step_optimizer, overlap=overlap,
                    )
                    break
                except Exception as e:
                    if attempt or not (
                        isinstance(e, WorkerLost) or _transportish(e)
                    ):
                        raise
                    self.log.warning(
                        "training step lost a worker (%s); repairing and "
                        "re-driving the step from the last checkpoint", e,
                    )
                    self._recover_training()
        finally:
            self._step_active = False
        if (
            step_optimizer and self._ckpt_every_steps > 0
            and self._step % self._ckpt_every_steps == 0
        ):
            self.save_checkpoint(self._auto_ckpt_dir())
        return out

    def _auto_ckpt_dir(self) -> str:
        if self._ckpt_dir is None:
            import tempfile
            from pathlib import Path

            d = Path(tempfile.gettempdir()) / f"tltpu_ckpt_{self.job_id[:12]}"
            self._ckpt_dir = str(d)
        return self._ckpt_dir

    def _recover_training(self) -> None:
        """Repair every stage whose worker connection died (each repair
        re-ships the stage and restores the last checkpoint on ALL stages,
        _apply_update), then clear half-accumulated gradients everywhere so
        the re-driven step starts clean.

        If the failed step had already begun fanning out its OPTIMIZER
        "step" ops (``_opt_step_partial``), some stages may hold the update
        and others not — re-driving on top of that mixed state would apply
        a second update on the fast stages. Roll EVERY stage back to the
        last checkpoint first (and refuse when there is none)."""
        live = set(self.node.send_request("peers", timeout=10.0))
        for st in self.plan.stages:
            if self.workers.get(st.worker_id) not in live:
                self._repair(st.worker_id)
        if getattr(self, "_opt_step_partial", False):
            if not getattr(self, "_last_ckpt", None):
                raise RuntimeError(
                    "optimizer step failed after possibly applying updates "
                    "on some stages, and no checkpoint exists to roll back "
                    "to — set ckpt_every_steps (auto-checkpoint) to make "
                    "this recoverable"
                )
            for s in self.plan.stages:
                self._request(
                    s.worker_id, proto.CHECKPOINT,
                    {"job_id": self.job_id, "op": "restore",
                     "dir": self._last_ckpt},
                    _repaired=True,
                )
            try:
                import json
                from pathlib import Path

                manifest = json.loads(
                    (Path(self._last_ckpt) / "manifest.json").read_text()
                )
                self._step = int(manifest.get("step", self._step))
            except Exception as e:
                self.log.warning(
                    "checkpoint manifest %s unreadable: %s",
                    self._last_ckpt, e,
                )
            self._opt_step_partial = False
        self.zero_grad()

    def _train_step_once(
        self,
        tokens: np.ndarray,  # int [B, T]
        loss_mask: np.ndarray | None = None,  # bool [B, T]
        attn_mask: np.ndarray | None = None,
        *,
        step_optimizer: bool = True,
        overlap: bool = True,
    ) -> dict:
        """One token-weighted causal-LM training step across the pipeline.

        Numerically equivalent to the single-program
        ``engine.training.make_train_step`` (the parity test for this is the
        backward-correctness check the reference never had, SURVEY §4).

        ``overlap`` runs micro-batches in concurrent driver threads: the IPC
        bridge supports many in-flight requests and each stage worker
        executes its queue in order, so micro ``m+1`` occupies stage 0 while
        micro ``m`` is on stage 1 — 1F1B-style pipelining of the cross-node
        hops (the reference got only accidental thread-timing overlap,
        ml/module.py:374-399; its serial equivalent idles every stage
        (S-1)/S of the time). Gradient accumulation on each worker is a sum,
        so completion order does not change the result beyond float
        summation order.
        """
        assert self.plan is not None
        tokens = np.asarray(tokens, np.int32)
        B = tokens.shape[0]
        n_micro = self.plan.n_micro if B % max(self.plan.n_micro, 1) == 0 else 1
        mb = B // n_micro

        self._step = getattr(self, "_step", 0) + 1
        # Forward and backward are interleaved per micro-batch so each
        # worker holds residuals for a bounded number of micros at a time
        # (one when serial, ≤ n_stages+1 when overlapped) — the memory
        # contract micro-batching exists for. Cotangents are sums (not
        # means), so scaling once by the total token count — computable
        # upfront from the loss masks — reproduces the token-mean gradient.
        def micro_mask(m: int):
            sl = slice(m * mb, (m + 1) * mb)
            am = attn_mask[sl] if attn_mask is not None else None
            lm = loss_mask[sl] if loss_mask is not None else (
                am if am is not None else np.ones_like(tokens[sl], bool)
            )
            return sl, am, np.asarray(lm, bool)

        total_tok = max(
            float(sum(micro_mask(m)[2][:, 1:].sum() for m in range(n_micro))),
            1.0,
        )

        def run_micro(m: int) -> float:
            sl, am, lm = micro_mask(m)
            tag = f"s{self._step}m{m}"
            logits = self._train_forward(tokens[sl], am, tag)
            nll_sum, dlogits, _ = _ce_sum_and_grad(logits, tokens[sl], lm)
            self._train_backward(np.asarray(dlogits), tag)
            return float(nll_sum)

        # merged (co-slice) stages require every member process to see the
        # SAME work-item order — concurrent micro threads would scramble
        # per-member arrival order and deadlock the SPMD collectives
        if any(s.coworkers for s in self.plan.stages):
            overlap = False
        if overlap and n_micro > 1 and self.plan.n_stages > 1:
            from concurrent.futures import ThreadPoolExecutor

            # at most n_stages+1 micros in flight (1F1B bound): enough to
            # keep every stage busy, while each worker's residual store
            # holds O(n_stages) micros instead of all n_micro — preserving
            # the memory contract micro-batching exists for
            in_flight = min(n_micro, self.plan.n_stages + 1)
            with ThreadPoolExecutor(max_workers=in_flight) as pool:
                total_nll = sum(pool.map(run_micro, range(n_micro)))
        else:
            total_nll = sum(run_micro(m) for m in range(n_micro))

        out = {"loss": total_nll / total_tok, "n_tokens": int(total_tok),
               "n_micro": n_micro}
        if step_optimizer:
            if not getattr(self, "_opt_ready", False):
                raise RuntimeError("call init_optimizer() before train_step()")
            out.update(self.optimizer_step(scale=1.0 / total_tok))
        return out

    # ------------------------------------------------------------------
    # checkpointing (net-new: the reference has no mid-training
    # checkpoint/resume, SURVEY §5 — Orbax-style save/restore + HF export)
    # ------------------------------------------------------------------
    def save_checkpoint(self, ckpt_dir: str) -> dict:
        """Each stage writes params (+ optimizer state) to ``ckpt_dir``
        (shared filesystem), plus a manifest for resume. Merged (co-slice)
        stages work too: the work item is MIRRORED to every member so the
        per-leaf host gathers run as lockstep collectives; only the primary
        writes the file (ml/worker.py::_checkpoint)."""
        import json
        from pathlib import Path

        paths = []
        for stage in self.plan.stages:
            resp = self._request_mirrored(
                stage, proto.CHECKPOINT,
                {"job_id": self.job_id, "op": "save", "dir": str(ckpt_dir)},
            )
            paths.append(resp["path"])
        manifest = {
            "model": {k: v for k, v in self.model_spec.items()},
            "plan": self.plan.to_json(),
            "step": getattr(self, "_step", 0),
        }
        Path(ckpt_dir).mkdir(parents=True, exist_ok=True)
        (Path(ckpt_dir) / "manifest.json").write_text(json.dumps(manifest, indent=2))
        self._last_ckpt = str(ckpt_dir)  # repair restores from here
        return {"paths": paths}

    def restore_checkpoint(self, ckpt_dir: str) -> None:
        for stage in self.plan.stages:
            self._request_mirrored(
                stage, proto.CHECKPOINT,
                {"job_id": self.job_id, "op": "restore", "dir": str(ckpt_dir)},
            )

    def export_hf_checkpoint(self, out_dir: str):
        """Download all stage params, merge, and write an HF-layout
        safetensors checkpoint (engine/loader.py::export_hf) — the analogue
        of the reference's parameter download into ``models/<name>/``
        (module.py:614-630), but in the interoperable HF format."""
        from tensorlink_tpu.engine.loader import export_hf

        merged = self._merge_stage_params(self.parameters())
        return export_hf(self.cfg, merged, out_dir)

    def _merge_stage_params(self, trees: list[dict]) -> dict:
        import jax

        full: dict = {}
        layer_trees = []
        for stage, tree in zip(self.plan.stages, trees):
            if stage.first and "embed" in tree:
                full["embed"] = tree["embed"]
            if stage.holds_head:
                if "final_norm" in tree:
                    full["final_norm"] = tree["final_norm"]
                if "lm_head" in tree:
                    full["lm_head"] = tree["lm_head"]
                if "embed" in tree and "embed" not in full:
                    full["embed"] = tree["embed"]
            if "layers" in tree:
                layer_trees.append(tree["layers"])
        full["layers"] = jax.tree.map(
            lambda *xs: np.concatenate(xs, axis=0), *layer_trees
        )
        return full

    # ------------------------------------------------------------------
    # parameters (reference module.py:577-650 downloads state dicts)
    # ------------------------------------------------------------------
    def parameters(self) -> list[dict]:
        """Pull each stage's parameter tree (numpy) from its worker.
        Mirrored on merged co-slice stages (every member runs the gathers,
        the primary ships the bytes) — so HF export and parameter download
        work on merged meshes too."""
        out = []
        for stage in self.plan.stages:
            resp = self._request_mirrored(
                stage, proto.PARAMS_REQ, {"job_id": self.job_id}
            )
            out.append(resp["params"])
        return out

    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        """Release the job: workers drop the stage runtime and free the
        reserved capacity (reference SHUTDOWN-JOB, worker_thread.py:92-95;
        the reference's users leak reservations on exit — see Keeper
        cleanup gap, SURVEY §5 failure-detection notes)."""
        if self.job_id is None:
            return
        peers = set(self.workers.values())
        try:
            peers |= set(self.node.send_request("validators", timeout=10.0))
        except Exception as e:
            self.log.debug("validator list for shutdown fanout failed: %s", e)
        for conn_id in peers:
            try:
                self.node.send_request(
                    "send_control",
                    {"peer": conn_id, "tag": proto.JOB_SHUTDOWN,
                     "body": {"job_id": self.job_id}},
                    timeout=10.0,
                )
            # tlint: disable=TL005(best-effort release fanout — dead peers free the reservation by dying)
            except Exception:
                pass
        self.job_id = None

    def close(self) -> None:
        self.shutdown()
        if self._owns_node:
            self.node.stop()

    def __enter__(self) -> "DistributedModel":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _stage_dict(stage) -> dict:
    from dataclasses import asdict

    return asdict(stage)


def _ce_sum_and_grad(logits, tokens, loss_mask):
    """Next-token cross-entropy SUM (not mean) + dlogits, fp32 — cotangents
    of the sum accumulate linearly across micro-batches, so dividing once by
    the total token count at optimizer-step time reproduces the token-mean
    loss of engine/training.py::causal_lm_loss exactly."""
    import jax
    import jax.numpy as jnp

    logits = jnp.asarray(logits)
    tokens = jnp.asarray(np.asarray(tokens, np.int32))
    mask = jnp.asarray(np.asarray(loss_mask, bool))

    def loss_fn(lg):
        lg32 = lg[:, :-1].astype(jnp.float32)
        tg = tokens[:, 1:]
        m = mask[:, 1:]
        logz = jax.nn.logsumexp(lg32, axis=-1)
        gold = jnp.take_along_axis(lg32, tg[..., None], axis=-1)[..., 0]
        return ((logz - gold) * m).sum()

    nll_sum, dlogits = jax.value_and_grad(loss_fn)(logits)
    n_tok = np.asarray(mask[:, 1:].sum())
    return np.asarray(nll_sum), np.asarray(dlogits), n_tok
