"""DistributedWorker — the ML-process executor on a worker node.

Reference: ml/worker.py:147 (``DistributedWorker``), a 1 kHz poll loop over
five IPC queues per module (main_loop:1349-1437). Here the executor blocks on
one event queue and runs **compiled** programs:

- a *stage* job executes ``stage_forward`` over its contiguous layer slice
  (sharded over the worker's local mesh when it has >1 device),
- a whole-model job additionally serves ``generate`` through the
  :class:`~tensorlink_tpu.engine.generate.GenerationEngine` (compiled
  prefill/decode pair) with per-token streaming over the TOKEN relay,
- decode sessions keep per-stage KV caches on device, keyed by session id —
  the explicit replacement for torch's implicit autograd/cache state
  (reference stores ``intermediates`` per micro-batch, module.py:1543).

Weights come from a checkpoint reference (selective per-stage safetensors
reads, engine/loader.py — the reference's selective shard loading idea,
ml/worker.py:542-638) or from seeded random init for tests/benchmarks; no
pickled modules ever cross the wire (reference trusted mode,
ml/worker.py:473-476, deliberately dropped — SURVEY §7.4).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import numpy as np

from tensorlink_tpu.core.faults import FaultCrash, FaultPlan
from tensorlink_tpu.core.logging import get_logger
from tensorlink_tpu.p2p import protocol as proto


@dataclass
class StageRuntime:
    """One loaded job stage: config + params + live decode sessions."""

    job_id: str
    cfg: Any  # ModelConfig
    stage: dict  # StagePlan as dict (layer_lo/hi, first, last, holds_head)
    params: Any
    # the ORIGINAL model spec this stage was shipped with (name, config,
    # ckpt/seed, quant, flash) — what a drain re-ships to the destination
    # worker so it can load an identical stage before adopting slots
    model_spec: dict = field(default_factory=dict)
    mesh: Any = None
    engine: Any = None  # GenerationEngine for whole-model jobs
    sessions: dict[str, Any] = field(default_factory=dict)  # session -> KVCache
    training: bool = False
    cache_quant: bool = False  # int8 decode-session KV caches ("int8+kv")
    # activation store for cross-host backward: tag -> (bwd_key, inputs,
    # wrt_input) — the explicit replacement for torch's implicit autograd
    # graph the reference replays on the worker (ml/worker.py:233-291).
    # Backward runs a COMPILED (params, x, mask, g) -> grads program cached
    # in ``bwd_cache`` (recomputing the forward inside the program, which
    # remat was doing anyway) instead of replaying an eager vjp closure
    # op-by-op per request.
    saved: dict[str, Any] = field(default_factory=dict)
    bwd_cache: dict[Any, Any] = field(default_factory=dict)
    grad_accum: Any = None  # summed param cotangents across micro-batches
    n_accum: int = 0
    opt: Any = None  # optax transform
    opt_state: Any = None
    # proof-of-learning log: one chained entry per optimizer step
    # (platform/proofs.py; the monitor pulls it via PROOF_REQ)
    proof_log: list = field(default_factory=list)
    opt_steps: int = 0
    # in-flight chunked beam-search sessions: rid -> (BeamState, payload,
    # effective K). A long beam decode advances _BEAM_CHUNK_STEPS at a
    # time and requeues itself, so queued co-batched generates interleave
    # instead of head-of-line-blocking behind it
    beam_sessions: dict[str, Any] = field(default_factory=dict)
    # continuous-batching slot engine (engine/continuous.py) for whole-model
    # jobs: GENERATE requests flagged "continuous" submit into its slot
    # batch and a cont_continue marker drives chunked decode through the
    # work queue — new requests admit at chunk boundaries (FIFO interleave,
    # same shape as the beam chunking above)
    cont: Any = None
    cont_scheduled: bool = False
    # per-session [B, V] context token counts for OpenAI presence/frequency
    # penalties on PIPELINED decode: the head-holding worker samples with
    # them and folds each sampled token back in, so penalized requests work
    # on multi-stage jobs too (the engine path carries its own counts)
    penalty_counts: dict[str, Any] = field(default_factory=dict)
    # idempotency ledger for sequence-numbered session ops: dedup key
    # ("{session}:{phase}") -> last applied seq, and -> the op's cached
    # outcome so a duplicate delivery (frame dup on the wire, RPC retry
    # after a lost reply) re-sends the SAME result instead of re-applying
    # the op's KV writes (ml/module.py drives retries on these seqs)
    session_seq: dict[str, int] = field(default_factory=dict)
    session_resp: dict[str, tuple] = field(default_factory=dict)
    # control-plane crash safety (docs/FAILURE_MODEL.md "Control plane"):
    # journal rid -> live ContinuousRequest for every continuous stream
    # admitted with a jrid, so a recovered validator/client can re-attach
    # to the still-decoding slot (the orphaned-stream survival half of
    # the validator journal)...
    jstreams: dict[str, Any] = field(default_factory=dict)
    # ...and jrid -> {"tokens", "base", "finished", "t"} for streams that
    # FINISHED while orphaned (their GENERATE_RESP went to a dead peer) —
    # a bounded ledger (MLConfig.orphan_keep / orphan_ttl_s) the re-attach
    # ladder drains exactly-once
    orphans: dict[str, dict] = field(default_factory=dict)

    @property
    def n_layers(self) -> int:
        return self.stage["layer_hi"] - self.stage["layer_lo"]

    @property
    def whole_model(self) -> bool:
        return (
            self.stage["first"]
            and self.stage["last"]
            and self.stage["holds_head"]
        )


# beam-search chunk size: steps a beam session may run per trip through the
# worker's serial loop before requeueing itself behind waiting work. Small
# enough that a queued co-batched generate waits one chunk, large enough to
# amortize the session bookkeeping.
_BEAM_CHUNK_STEPS = 32


class DistributedWorker:
    """Event-driven executor; one instance per WorkerNode."""

    def __init__(self, node):
        self.node = node
        self.bridge = node.bridge
        self.log = get_logger(f"ml.worker{node.config.duplicate}")
        self.jobs: dict[str, StageRuntime] = {}
        self._lock = threading.Lock()
        # drain state (live slot migration): set to the DRAIN verb's
        # destination {"id", "addr"} — new continuous requests are
        # redirected there instead of admitted, and the recruiting
        # capacity is zeroed. None = serving normally.
        self.draining: dict | None = None
        # disaggregated prefill/decode (docs/SERVING.md): the decode-pool
        # memberships a prefill-role worker hands completed prefills to —
        # pushed by the validator (HANDOFF frames). Keyed PER JOB (the
        # recruit-time push names the job it was planned for — a job
        # recruited before any decode worker existed must NOT start
        # shipping its streams to another job's pool), with "" as the
        # worker-wide fallback an operator's set_handoff_pool installs.
        # Empty = no handoffs (mixed-style serving even under
        # worker_role="prefill").
        self._handoff_pools: dict[str, list[dict]] = {}
        self._handoff_rr = 0  # round-robin cursor over the pool
        # fleet serving (docs/SERVING.md "Fleet serving"): the sibling-
        # replica memberships pushed by the validator (REPLICA_SET
        # frames, keyed by this worker's job id) — the destination a
        # DRAIN with no explicit dest falls back to, so a rolling-deploy
        # drain lands on a replica that already serves the same model
        self._replica_sets: dict[str, list[dict]] = {}
        # destinations already probed loaded/ready per job — skips the
        # per-handoff MODULE-ship round trip on the steady-state path;
        # invalidated on any ship failure so a restarted destination is
        # re-prepared instead of redirected into blind
        self._handoff_dest_ready: set[tuple[str, str]] = set()
        # (job, dest) prepares currently in flight (the warm-up thread or
        # the run loop): a second prepare for the same key must neither
        # block nor double-ship — a duplicate MODULE load REPLACES the
        # destination's runtime, killing any stream adopted in between
        self._handoff_preparing: set[tuple[str, str]] = set()
        self._handoff_prep_lock = threading.Lock()
        # shared multi-tenant KV page pools (engine/paged.py::
        # SharedPagePool), keyed by page GEOMETRY so only models that can
        # physically share pages do — created lazily at the first
        # continuous engine when MLConfig.cont_pool_pages > 0. Touched
        # only from the serial run loop (the pool's single-driver
        # contract holds because every job's engine steps there too).
        self._kv_pools: dict = {}
        # per-node fault plan (core/faults.py) — an INSTANCE, not the module
        # global, so several worker nodes living in one test process never
        # share fault counters; None (the default) keeps the hot paths free
        # of fault-site calls entirely
        fspec = getattr(node.config, "faults", None)
        self.faults: FaultPlan | None = (
            FaultPlan.from_dict(fspec) if fspec else None
        )
        # join the multi-controller runtime BEFORE first device use when the
        # deployment spans hosts of one slice (parallel/multihost.py) — then
        # jax.devices() is global and planned meshes may span the slice
        ml = node.config.ml
        from tensorlink_tpu.parallel.multihost import maybe_initialize

        maybe_initialize(
            ml.coordinator_address, ml.num_processes, ml.process_id
        )

    # -- capacity -------------------------------------------------------
    def capacity(self) -> dict:
        """What this worker advertises (reference STATS-RESPONSE payload,
        worker_thread.py:245-268): HBM bytes + device count.

        Device acquisition is BOUNDED (core/devices.py): a wedged TPU
        runtime degrades this worker to CPU capacity with a loud warning
        instead of hanging ``WorkerNode.start()`` / the CLI forever."""
        from tensorlink_tpu.core.devices import acquire_devices

        probe = acquire_devices(
            deadline=float(os.environ.get("TLTPU_DEVICE_PROBE_S", "60"))
        )
        devs = probe.devices
        cap = 0.0
        for d in devs:
            stats = {}
            try:
                stats = d.memory_stats() or {}
            # tlint: disable=TL005(memory_stats is backend-optional; no stats = advertise zero capacity)
            except Exception:
                pass
            cap += float(stats.get("bytes_limit", 0.0))
        if not cap:
            gb = self.node.config.ml.max_memory_gb or 4.0
            cap = gb * 1e9 * len(devs)
        if self.node.config.ml.max_memory_gb:
            cap = min(cap, self.node.config.ml.max_memory_gb * 1e9 * len(devs))
        out = {
            "hbm_bytes": cap,
            "n_devices": len(devs),
            "platform": probe.platform,
            "training": True,
            # disaggregated prefill/decode: the pool this worker serves
            # in ("prefill" | "decode" | "mixed") — the validator's
            # placement reads it off every stats sweep (decode workers
            # are reserved as handoff destinations, docs/SERVING.md)
            "serving_role": str(
                getattr(self.node.config.ml, "worker_role", "mixed")
                or "mixed"
            ),
            # explicit tensor parallelism (docs/SHARDING.md): the shard
            # degree this worker's continuous engines run at — the
            # planner/validator treat the whole tp mesh as ONE placement
            # unit (a tp=4 worker is one engine over 4 chips, not 4
            # engines)
            "tensor_parallel": int(
                getattr(self.node.config.ml, "tensor_parallel", 1) or 1
            ),
        }
        # hosts of one TPU slice share an ICI domain: advertise the slice so
        # the planner can merge co-slice workers into one mesh
        # (parallel/planner.py::_merge_co_slice). Configurable override for
        # deployments where the runtime does not expose slice topology.
        sid = self.node.config.ml.slice_id or ""
        if not sid and devs:
            # auto-detect only when TPU_NAME names the pod: a bare
            # slice_index collides across unrelated pods and would merge
            # workers that share no ICI
            sidx = getattr(devs[0], "slice_index", None)
            pod = os.environ.get("TPU_NAME")
            if sidx is not None and probe.platform == "tpu" and pod:
                sid = f"{pod}:{sidx}"
            elif sidx is not None and probe.platform == "tpu":
                # slice topology IS visible but unnamed — without the gate
                # co-slice merging silently never triggers; tell the
                # operator what to set instead of leaving it a mystery
                self.log.info(
                    "TPU slice detected (slice_index=%s) but TPU_NAME is "
                    "unset — not advertising a slice_id; set TPU_NAME (or "
                    "MLConfig.slice_id) to enable co-slice merged planning",
                    sidx,
                )
        if sid:
            out["slice_id"] = sid
        if probe.degraded:
            out["degraded"] = True
            out["device_error"] = probe.error
        return out

    # -- main loop ------------------------------------------------------
    def run(self) -> None:
        while True:
            item = self.bridge.get_work(timeout=1.0)
            if item is None:
                continue
            kind, payload = item
            if kind == "_stop":
                return
            try:
                self._handle(kind, payload)
            except FaultCrash as e:
                # injected node death: kill the network process abruptly so
                # every peer sees a dropped connection (the repair paths'
                # trigger), and exit this loop — no error reply, exactly
                # like a real worker loss mid-request
                self.log.warning("fault injection: %s — node going down", e)
                self.node.crash()
                return
            except Exception as e:
                self.log.exception("work %s failed", kind)
                rid, peer = payload.get("rid"), payload.get("peer")
                if rid and peer:
                    resp_tag = {
                        proto.FORWARD: proto.FORWARD_RESP,
                        proto.BACKWARD: proto.BACKWARD_RESP,
                        proto.GENERATE: proto.GENERATE_RESP,
                        proto.OPTIMIZER: proto.OPTIMIZER_RESP,
                        proto.PARAMS_REQ: proto.PARAMETERS,
                        proto.CHECKPOINT: proto.CHECKPOINT_RESP,
                        proto.PROOF_REQ: proto.PROOF_RESP,
                        proto.MIGRATE: proto.MIGRATE_RESP,
                        proto.DRAIN: proto.DRAIN_RESP,
                        "load_stage": proto.MODULE_LOADED,
                        "beam_continue": proto.GENERATE_RESP,
                    }.get(kind, proto.FORWARD_RESP)
                    # a chained hop's requester is the ORIGINATOR, not the
                    # previous worker — route the error to it (it holds the
                    # rid future) and name the failing worker for repair
                    err_peer = payload.get("reply_to") or peer
                    try:
                        self._respond(
                            err_peer, resp_tag, rid,
                            {"error": f"{type(e).__name__}: {e}",
                             "worker": self.node.node_id},
                        )
                    except Exception as e2:
                        # the requester died too (the chaos suite's
                        # validator kill lands here: the work item fails
                        # BECAUSE the peer is gone, so the error reply
                        # fails the same way) — an undeliverable reply
                        # must never kill this loop; the worker keeps
                        # serving and re-announces on the re-handshake
                        self.log.warning(
                            "error reply for %s to %s undeliverable: %s",
                            kind, str(err_peer)[:8], e2,
                        )

    def _handle(self, kind: str, p: dict) -> None:
        if kind == "load_stage":
            self._load_stage(p)
        elif kind == proto.FORWARD:
            self._forward(p)
        elif kind == proto.GENERATE:
            self._generate(p)
        elif kind == "beam_continue":
            self._beam_step(p["job_id"], p["rid"])
        elif kind == "cont_continue":
            self._cont_step(p["job_id"])
        elif kind == proto.PARAMS_REQ:
            self._params_req(p)
        elif kind == proto.TRAIN_MODE:
            self._train_mode(p)
        elif kind == proto.BACKWARD:
            self._backward(p)
        elif kind == proto.OPTIMIZER:
            self._optimizer(p)
        elif kind == proto.PROOF_REQ:
            self._proof_req(p)
        elif kind == proto.CHECKPOINT:
            self._checkpoint(p)
        elif kind == proto.DRAIN:
            self._drain(p)
        elif kind == proto.MIGRATE:
            self._migrate_in(p)
        elif kind == proto.HANDOFF:
            self._set_handoff_pool(p)
        elif kind == proto.REPLICA_SET:
            self._set_replica_set(p)
        elif kind == "shutdown_job":
            jid = p.get("job_id", "")
            with self._lock:
                rt = self.jobs.pop(jid, None)
            # drop the job's handoff state with it: its decode-pool list
            # and per-destination readiness would otherwise pin per dead
            # job id for the process lifetime (same lifecycle gap the
            # shared KV pools had)
            self._handoff_pools.pop(jid, None)
            self._replica_sets.pop(jid, None)
            with self._handoff_prep_lock:  # vs the warm thread's add
                self._handoff_dest_ready = {
                    k for k in self._handoff_dest_ready if k[0] != jid
                }
            if rt is not None and rt.cont is not None:
                # fail queued/in-flight continuous requests fast rather
                # than letting their clients wait out the RPC timeout
                rt.cont.close(RuntimeError("job shut down"))
                rt.cont = None
                # close() detached the tenant: a now-empty shared pool
                # must release its page arrays, not pin HBM forever
                self._gc_kv_pools()
        elif kind == "token":
            pass  # token relays are user/validator side
        else:
            self.log.warning("unhandled work kind %s", kind)

    def _respond(self, peer: str, tag: str, rid: str, body: dict) -> None:
        self.bridge.request(
            "respond", {"peer": peer, "tag": tag, "rid": rid, "body": body}
        )

    # -- loading --------------------------------------------------------
    def _load_stage(self, p: dict) -> None:
        import jax

        from tensorlink_tpu.models.base import ModelConfig
        from tensorlink_tpu.models.transformer import (
            init_params,
            slice_stage_params,
        )

        t0 = time.monotonic()
        job_id = p["job_id"]
        if p.get("attach_only"):
            # validator re-handshake after a control-plane restart
            # (DistributedModel.from_job(..., attach_only=True)): if the
            # stage is already live, ACK without rebuilding — a full load
            # would swap the engine and kill every live slot, which is
            # exactly what recovery must not do. The ack re-announces this
            # worker's live/orphaned streams so the recovered validator
            # can reconcile its journal (worker wins for tokens). A worker
            # that ALSO restarted falls through to the normal full load.
            with self._lock:
                rt = self.jobs.get(job_id)
            if rt is not None:
                body = {
                    "job_id": job_id, "ok": True, "attached": True,
                    "n_layers": rt.n_layers,
                    "live_slots": (
                        rt.cont.live_slots if rt.cont is not None else 0
                    ),
                    "orphans": self._orphan_report(rt),
                }
                self._respond(p["peer"], proto.MODULE_LOADED, p["rid"], body)
                return
        model = p["model"]
        stage = p["stage"]
        cfg = ModelConfig.from_json(model["config"])
        lo, hi = stage["layer_lo"], stage["layer_hi"]
        first, holds_head = stage["first"], stage["holds_head"]

        if model.get("ckpt"):
            from tensorlink_tpu.engine.loader import load_params

            _, full = load_params(model["ckpt"], cfg, layer_range=(lo, hi))
            # loader returns embed/final_norm/head too; keep what the stage owns
            params = {"layers": full["layers"]} if hi > lo else {}
            if first:
                params["embed"] = full["embed"]
            if holds_head:
                params["final_norm"] = full["final_norm"]
                if "lm_head" in full:
                    params["lm_head"] = full["lm_head"]
                elif "embed" not in params:
                    params["embed"] = full["embed"]
        else:
            seed = int(model.get("seed", 0))
            full = init_params(cfg, jax.random.PRNGKey(seed))
            params = slice_stage_params(
                full, lo, hi, first=first, holds_head=holds_head
            )
            del full

        mesh = self._build_stage_mesh(cfg, stage)
        if mesh is not None:
            params = self._shard_params(params, cfg, stage, mesh)
        training = bool(p.get("training", False))
        if self.node.config.ml.collective_quant and not training:
            # EQuARX-style quantized collectives (parallel/ring.py): the
            # sequence-parallel ring rotates int8 K/V + scales over ICI.
            # SERVING only — quantize_kv's round() has a zero gradient,
            # so a training vjp through a quantized ring would silently
            # lose the K/V gradient (same rule as weight quant below:
            # training needs exact math)
            cfg = cfg.with_(collective_quant=True)
        quant = p.get("model", {}).get("quant")
        if p.get("model", {}).get("flash"):
            # Pallas flash prefill for this job's serving ENGINE — i.e.
            # whole-model stages only (ops/attention.py; the engine gates it
            # to fresh-cache prefills, and a sharded engine routes the
            # kernel through shard_map over data/tensor since GSPMD has no
            # partitioning rule for a pallas_call). The multi-stage session
            # path never reaches the flash gate — say so instead of
            # silently serving einsum.
            if (
                stage["first"] and stage["last"] and stage["holds_head"]
            ):
                cfg = cfg.with_(flash_attention=True)
            else:
                self.log.warning(
                    "flash_attention ignored on a pipelined (multi-stage) "
                    "job — only whole-model serving engines take the "
                    "flash prefill path"
                )
        cache_quant = False
        if quant:
            # weight-only int8 serving (models/quant.py): quantize the
            # stage's matmul weights in place — every serving path
            # (stage_forward, the generation engine) dequantizes on the fly
            # through quant.matmul. "+kv" also stores decode-session and
            # engine KV caches int8. Training needs exact weights for the
            # optimizer. Sharded stages compose: quantizing the
            # already-sharded tree keeps GSPMD shardings on q and scale.
            if quant not in ("int8", "int8+kv"):
                # fail the MODULE load (the user sees the error) rather
                # than silently serving a mode they didn't ask for
                raise ValueError(f"unknown quant mode {quant!r}")
            if training:
                self.log.warning("quant=%s ignored for a TRAINING job", quant)
            else:
                from tensorlink_tpu.models.quant import quantize_params

                params = quantize_params(params)
                cache_quant = quant == "int8+kv"
        rt = StageRuntime(
            job_id=job_id,
            cfg=cfg,
            stage=stage,
            params=params,
            model_spec=dict(model),
            mesh=mesh,
            training=training,
            cache_quant=cache_quant,
        )
        if rt.whole_model:
            from tensorlink_tpu.engine.generate import GenerationEngine

            ml_cfg = self.node.config.ml
            rt.engine = GenerationEngine(
                cfg,
                params,  # already quantized above when quant was requested
                mesh=mesh,
                # batch buckets include 1, so never shard cache batch on the
                # data axis here; kv heads ride the tensor axis
                cache_specs=(
                    self._cache_specs_for(rt, batch=1) if mesh is not None else None
                ),
                max_seq_len=min(cfg.max_seq_len, ml_cfg.max_seq_len),
                seq_buckets=ml_cfg.seq_buckets,
                batch_buckets=ml_cfg.batch_buckets,
                # params are pre-quantized above (quantize_params is
                # idempotent, so the engine's own pass is a no-op); this
                # sets the engine's cache mode for "+kv" AND records the
                # weight mode the serving snapshot / serving_modes report
                # (weights-only "int8" used to pass None here, so the
                # paged engine couldn't tell operators it was quantized)
                quant=quant if not training else None,
            )
        with self._lock:
            old = self.jobs.get(job_id)
            self.jobs[job_id] = rt
        if old is not None and old.cont is not None:
            # a re-shipped stage replaces the runtime: fail the old slot
            # engine's in-flight requests fast (their KV died with the old
            # engine) instead of leaving clients to wait out the RPC timeout
            old.cont.close(RuntimeError("stage reloaded"))
            old.cont = None
        self.log.info(
            "loaded %s layers [%d,%d) first=%s head=%s in %.1fs",
            model.get("name", "?"), lo, hi, first, holds_head, time.monotonic() - t0,
        )
        self._respond(
            p["peer"], proto.MODULE_LOADED, p["rid"],
            {"job_id": job_id, "ok": True, "n_layers": hi - lo},
        )
        warm_toks = self.node.config.ml.warmup_tokens
        if getattr(rt, "engine", None) is not None and warm_toks and not training:
            # AFTER the ack: XLA warmup can take minutes on a real chip and
            # must not time out the deploy (MODULE waits MAX_WAIT_TIME).
            # The run loop is serial, so the first request simply queues
            # behind the warm compile it would otherwise have paid itself;
            # a warmup failure must not double-respond on this rid.
            try:
                dt = rt.engine.warmup(max_new_tokens=warm_toks)
                self.log.info(
                    "warmed serving programs in %.1fs (%d tokens)",
                    dt, warm_toks,
                )
            except Exception:
                self.log.exception("serving warmup failed (serving anyway)")

    def _build_stage_mesh(self, cfg, stage: dict):
        """Build this stage's local device mesh from the plan's axis sizes
        (TP/FSDP/DP/EP inside one worker — GSPMD shards, XLA inserts the
        collectives; SURVEY §2.2 capability upgrades the reference lacks)."""
        from tensorlink_tpu.core.devices import acquire_devices

        axes = {k: int(v) for k, v in (stage.get("mesh_axes") or {}).items()}
        n = 1
        for v in axes.values():
            n *= v
        if n <= 1:
            return None
        devs = acquire_devices().devices
        from tensorlink_tpu.parallel.multihost import is_multihost

        if stage.get("coworkers") and is_multihost():
            # a MERGED co-slice stage spans the pooled devices of every
            # process in the jax.distributed runtime — the GLOBAL list
            # (identically ordered on every process, so all members build
            # the same mesh). Gated on the stage actually being merged: a
            # multihost-joined worker running an ordinary local stage must
            # never mesh over other processes' (non-addressable) devices.
            import jax

            devs = jax.devices()
        if n > len(devs):
            self.log.warning(
                "plan wants %d-device mesh, have %d — running unsharded",
                n, len(devs),
            )
            return None
        from tensorlink_tpu.parallel.mesh import build_mesh

        return build_mesh(axes, devs[:n])

    def _shard_params(self, params, cfg, stage: dict, mesh):
        from tensorlink_tpu.parallel.mesh import put
        from tensorlink_tpu.parallel.planner import StagePlan, stage_param_specs

        specs = stage_param_specs(cfg, StagePlan(**stage))
        try:
            return put(mesh, params, specs)
        except ValueError as e:
            self.log.warning("param sharding failed (%s); replicating", e)
            return params

    def _cache_specs_for(self, rt: StageRuntime, batch: int):
        """KV-cache PartitionSpecs on this stage's mesh: kv heads on tensor
        (when they divide), batch on data only when the batch divides it —
        serving batches of 1 must not fail against a data axis."""
        from tensorlink_tpu.models.transformer import cache_specs

        axes = rt.stage.get("mesh_axes") or {}
        tp = axes.get("tensor", 1)
        dp = axes.get("data", 1)
        return cache_specs(
            rt.cfg,
            data_axis="data" if dp > 1 and batch % dp == 0 else None,
            tensor_axis="tensor" if tp > 1 and rt.cfg.n_kv_heads % tp == 0 else None,
            quantized=rt.cache_quant,
        )

    def _runtime(self, job_id: str) -> StageRuntime:
        rt = self.jobs.get(job_id)
        if rt is None:
            raise KeyError(f"job {job_id} not loaded")
        return rt

    # -- multihost (co-slice merged mesh) transfers ----------------------
    @staticmethod
    def _spans_processes(mesh) -> bool:
        """True when this stage's mesh includes devices of OTHER processes
        (a co-slice merged plan under jax.distributed)."""
        if mesh is None:
            return False
        import jax

        pi = jax.process_index()
        return any(d.process_index != pi for d in mesh.devices.flat)

    def _to_host(self, rt: "StageRuntime", arr):
        """Device → host. On a process-spanning mesh a plain device_get
        would fail on non-addressable shards — gather the full value
        instead (a collective: every member process executes this inside
        the same mirrored work item, so launches stay lockstep)."""
        import jax

        if self._spans_processes(rt.mesh):
            from jax.experimental import multihost_utils

            # tiled=True: for a global jax.Array this returns the FULL
            # global value (per-process host data would be stacked instead)
            return np.asarray(
                multihost_utils.process_allgather(arr, tiled=True)
            )
        return np.asarray(jax.device_get(arr))

    def _to_device(self, rt: "StageRuntime", arr):
        """Host → device. On a process-spanning mesh, commit host data
        replicated over the stage mesh (every member received the same
        bytes in its mirrored work item); otherwise a plain local array."""
        import jax
        import jax.numpy as jnp

        if self._spans_processes(rt.mesh):
            from jax.sharding import NamedSharding, PartitionSpec

            host = np.asarray(arr)
            # rank-expanded replicated spec — the canonical jit cache-key
            # spelling (PartitionSpec() is the same placement but a
            # DIFFERENT key, the PR 17 recompile class; TL101)
            spec = PartitionSpec(*([None] * host.ndim))
            return jax.device_put(host, NamedSharding(rt.mesh, spec))
        return jnp.asarray(np.asarray(arr))

    def _stage_fwd_fn(
        self,
        rt: StageRuntime,
        seq_mesh,
        pp_size: int,
        apply_head: bool,
        *,
        remat: bool = False,
        n_micro: int = 1,
    ):
        """Build the ``(params, x, attn_mask) -> out`` function for this
        stage's layer slice, where ``x`` is tokens (first stage) or hidden
        (later stages). All varying data is an ARGUMENT (not captured) so
        jitted wrappers of the closure are safely cacheable per shape.

        Dispatch, in order: a plan mesh with a ``stage`` axis runs the slice
        through the in-mesh GPipe program (parallel/pipeline.py); a ``seq``
        axis runs ring attention inside ``stage_forward``; otherwise the
        plain compiled stage program. All three are differentiable — the
        training backward is a cached jit of ``jax.vjp`` over this closure
        (the explicit replacement for the reference's torch-autograd replay,
        ml/worker.py:233-291)."""
        from tensorlink_tpu.models.transformer import stage_forward

        first = rt.stage["first"]
        cfg = rt.cfg
        axes = rt.stage.get("mesh_axes") or {}
        if cfg.moe and remat and int(axes.get("expert", 1)) > 1:
            # TRAINING forwards with an expert axis take the capacity-factor
            # sparse dispatch (parallel/expert.py); eval forwards, decode
            # sessions, and the GenerationEngine stay on exact dense
            # dispatch — capacity overflow drops tokens, which must never
            # silently change served/eval logits. Expert-axis sharding
            # still applies to the dense path via GSPMD.
            cfg = cfg.with_(moe_dispatch="sparse")

        if pp_size > 1:
            from tensorlink_tpu.parallel.pipeline import pipelined_stage_forward

            def fwd(params, x, attn_mask):
                out, _ = pipelined_stage_forward(
                    params,
                    cfg,
                    rt.mesh,
                    tokens=x if first else None,
                    hidden=None if first else x,
                    attn_mask=attn_mask,
                    n_micro=n_micro,
                    first=first,
                    last=apply_head,
                    remat=remat,
                )
                return out

            return fwd

        def fwd(params, x, attn_mask):
            out, _ = stage_forward(
                params,
                cfg,
                tokens=x if first else None,
                hidden=None if first else x,
                attn_mask=attn_mask,
                first=first,
                last=apply_head,
                remat=remat,
                seq_mesh=seq_mesh,
            )
            return out

        return fwd

    @staticmethod
    def _pp_n_micro(pp_size: int, batch: int) -> int:
        """Prefer 2 micro-batches per stage (keeps the bubble small),
        degrade to whatever divides the batch; this in-mesh micro count is
        sized to THIS stage's mesh, independent of the cross-worker
        plan.n_micro grad-accumulation knob."""
        for cand in (2 * pp_size, pp_size, 2, 1):
            if batch % cand == 0:
                return cand
        return 1

    def _train_programs(self, rt: StageRuntime, flags: tuple, shapes: tuple):
        """Cached jitted (fwd, bwd) programs for one training configuration.

        ``bwd(params, x, mask, g)`` takes ``jax.vjp`` of the stage closure
        INSIDE jit — the forward recomputes within the compiled program
        (what remat was doing through the eager vjp anyway), so backward is
        one cached XLA execution instead of an op-by-op eager replay per
        request."""
        import jax

        key = (flags, shapes)
        progs = rt.bwd_cache.get(key)
        if progs is not None:
            return progs
        seq_on, pp_size, apply_head, remat, n_micro, wrt_input = flags
        fwd = self._stage_fwd_fn(
            rt,
            rt.mesh if seq_on else None,
            pp_size,
            apply_head,
            remat=remat,
            n_micro=n_micro,
        )
        if wrt_input:

            def bwd(params, x, mask, g):
                _, vjp = jax.vjp(lambda p, xx: fwd(p, xx, mask), params, x)
                return vjp(g)  # (grad_params, grad_x)

        else:  # first stage: tokens are int — grads wrt params only

            def bwd(params, x, mask, g):
                _, vjp = jax.vjp(lambda p: fwd(p, x, mask), params)
                return vjp(g)[0], None

        progs = (jax.jit(fwd), jax.jit(bwd))
        rt.bwd_cache[key] = progs
        return progs

    # -- forward --------------------------------------------------------
    def _forward(self, p: dict) -> None:
        """op="stage": run my layer slice (optionally with a decode-session
        KV cache). op="head": final norm + logits (tied-embedding hop).
        ``train=True`` + ``tag`` records the vjp for a later BACKWARD."""
        import jax
        import jax.numpy as jnp

        from tensorlink_tpu.models.base import KVCache
        from tensorlink_tpu.models.transformer import head_forward, stage_forward

        rt = self._runtime(p["job_id"])
        op = p.get("op", "stage")
        if op == "end_session":
            sid = p.get("session")
            rt.sessions.pop(sid, None)
            rt.penalty_counts.pop(sid, None)
            for phase in ("s", "h"):
                rt.session_seq.pop(f"{sid}:{phase}", None)
                rt.session_resp.pop(f"{sid}:{phase}", None)
            self._respond(p["peer"], proto.FORWARD_RESP, p["rid"], {"ok": True})
            return
        if p.get("session") is not None and p.get("seq") is not None:
            # sequence-numbered session op: a duplicate delivery (frame dup
            # on the wire, RPC retry after a lost reply) must never re-apply
            # the KV writes — re-send the cached outcome instead
            if self._session_dup(rt, p):
                return
        if self.faults is not None and p.get("session") is not None:
            # fault site "worker.session_step" (core/faults.py): counted per
            # APPLIED op so transport dups never perturb the plan's decisions
            self.faults.inject("worker.session_step", op)
        if p.get("trace"):
            # session-op trace propagation (core/trace.py): the admission
            # op carries the admitted requests' trace ids — record this
            # stage's hop under each so pipelined traces name the workers
            # a request's prefill touched
            from tensorlink_tpu.core.trace import get_tracer

            tracer = get_tracer()
            for tid in p["trace"]:
                tracer.record(
                    str(tid), "session_prefill", site=self.node.node_id,
                    layers=f"{rt.stage['layer_lo']}-{rt.stage['layer_hi']}",
                )
        train = bool(p.get("train", False))
        tag = p.get("tag", "")
        if op == "chain" and p.get("head_hop"):
            # final hop of a worker-to-worker chain looping back for the
            # tied-embedding head (ml/module.py::_forward_chain)
            hidden = jnp.asarray(np.asarray(p["hidden"]))
            logits = head_forward(rt.params, hidden, rt.cfg)
            self._finish_fwd(rt, p, logits, True)
            return
        if op == "head":
            hidden = jnp.asarray(np.asarray(p["hidden"]))
            logits = head_forward(rt.params, hidden, rt.cfg)
            if train:
                rt.saved[tag + ".head"] = ("head", None, hidden, None, True)
                self._respond(
                    p["peer"], proto.FORWARD_RESP, p["rid"],
                    {"out": np.asarray(jax.device_get(logits))},
                )
                return
            self._finish_fwd(rt, p, logits, True)
            return

        stage = rt.stage
        first = stage["first"]
        apply_head = stage["last"] and stage["holds_head"]
        kw: dict[str, Any] = {}
        if first:
            kw["tokens"] = self._to_device(rt, np.asarray(p["tokens"], np.int32))
        else:
            kw["hidden"] = self._to_device(rt, np.asarray(p["hidden"]))
        if p.get("attn_mask") is not None:
            kw["attn_mask"] = self._to_device(
                rt, np.asarray(p["attn_mask"], bool)
            )

        # product-path SP/PP (VERDICT r1 #3): a plan whose mesh carries a
        # seq axis runs ring attention inside stage_forward; a stage axis
        # runs the layer slice through the in-mesh GPipe program. Neither
        # applies to the KV-cache (serving session) path — the planner never
        # emits these axes for serving jobs.
        axes = stage.get("mesh_axes") or {}
        seq_mesh = (
            rt.mesh
            if rt.mesh is not None
            and int(axes.get("seq", 1)) > 1
            and kw.get("attn_mask") is None
            else None
        )
        pp_size = int(axes.get("stage", 1)) if rt.mesh is not None else 1
        x_in = kw["tokens"] if first else kw["hidden"]
        mask = kw.get("attn_mask")
        n_micro = self._pp_n_micro(pp_size, int(x_in.shape[0])) if pp_size > 1 else 1

        if train:
            # no KV cache in training; record the inputs keyed by the
            # driver's (batch, micro) tag — cotangents arrive via BACKWARD
            # and run the cached compiled bwd program over these inputs
            flags = (
                seq_mesh is not None, pp_size, apply_head, True, n_micro,
                not first,
            )
            shapes = (
                x_in.shape, str(x_in.dtype),
                None if mask is None else mask.shape,
            )
            fwd_prog, _ = self._train_programs(rt, flags, shapes)
            out = fwd_prog(rt.params, x_in, mask)
            rt.saved[tag] = ("stage", flags, x_in, mask, not first)
            self._respond(
                p["peer"], proto.FORWARD_RESP, p["rid"],
                {"out": np.asarray(jax.device_get(out)), "is_logits": apply_head},
            )
            return

        if p.get("session") is None and (pp_size > 1 or seq_mesh is not None):
            fwd = self._stage_fwd_fn(
                rt, seq_mesh, pp_size, apply_head, n_micro=n_micro
            )
            out = fwd(rt.params, x_in, mask)
            self._finish_fwd(rt, p, out, apply_head)
            return

        session = p.get("session")
        cache = None
        if session is not None:
            cache = rt.sessions.get(session)
            if cache is not None and p.get("reset_rows"):
                # pipelined slot admission (ml/batching.py
                # PipelinedSlotSession): rows whose previous request
                # finished are recycled by zeroing their write offset —
                # the stale KV beyond it is invisible (attention masks by
                # length) and the admitted prompt overwrites it
                rows = jnp.asarray(np.asarray(p["reset_rows"], np.int32))
                cache = KVCache(
                    k=cache.k, v=cache.v,
                    length=cache.length.at[rows].set(0),
                    k_scale=cache.k_scale, v_scale=cache.v_scale,
                )
            if cache is not None and p.get("reset_len") is not None:
                # pipelined speculative decode: roll back the REJECTED
                # draft positions of the previous verify pass by resetting
                # the write offset (stale KV beyond it is invisible —
                # attention masks by length). Rides the forward body like
                # reorder_idx: no extra per-stage round-trip.
                cache = KVCache(
                    k=cache.k, v=cache.v,
                    length=jnp.full_like(
                        cache.length, int(p["reset_len"])
                    ),
                    k_scale=cache.k_scale, v_scale=cache.v_scale,
                )
            if cache is not None and p.get("reorder_idx") is not None:
                # pipelined beam search: this step's cache rows follow
                # their beam's source row (the same [:, idx] gather the
                # engine-side beam session does) — the permutation rides
                # the forward body, so no extra per-stage round-trip
                gidx = jnp.asarray(np.asarray(p["reorder_idx"], np.int32))
                cache = KVCache(
                    k=cache.k[:, gidx], v=cache.v[:, gidx],
                    length=cache.length[gidx],
                    k_scale=None if cache.k_scale is None
                    else cache.k_scale[:, gidx],
                    v_scale=None if cache.v_scale is None
                    else cache.v_scale[:, gidx],
                )
            if cache is None:
                batch = (kw.get("tokens") if first else kw["hidden"]).shape[0]
                scfg = rt.cfg.with_(n_layers=rt.n_layers)
                cache = KVCache.init(
                    scfg, batch,
                    max_len=int(p.get("cache_len", rt.cfg.max_seq_len)),
                    quantized=rt.cache_quant,
                )
                if rt.mesh is not None:
                    from tensorlink_tpu.parallel.mesh import put

                    cache = put(rt.mesh, cache, self._cache_specs_for(rt, batch))
        out, new_cache = stage_forward(
            rt.params, rt.cfg, cache=cache, first=first, last=apply_head, **kw
        )
        if session is not None:
            rt.sessions[session] = new_cache
        self._finish_fwd(rt, p, out, apply_head)

    # chain fields every forwarded hop must carry onward
    _CHAIN_KEYS = (
        "job_id", "session", "cache_len", "attn_mask", "sample",
        "last_idx", "reply_to", "reorder_idx", "reset_len", "reset_rows",
        "seq", "trace",
    )

    # -- session-op idempotency (seq dedup) ------------------------------
    @staticmethod
    def _session_dedup_key(p: dict) -> str:
        # a first+head-holding stage sees TWO ops per decode step (its
        # stage slice, then the tied-embedding head hop) under the same
        # seq — separate phases so the head hop is not mistaken for a dup
        return f"{p['session']}:{'h' if p.get('head_hop') else 's'}"

    def _session_dup(self, rt: "StageRuntime", p: dict) -> bool:
        """True when this seq was already applied for its session/phase.
        For the latest applied seq the cached outcome is re-delivered: a
        direct response is re-sent under the retry's rid, and a mid-chain
        hop re-drives the chain from its cached output (so a retry whose
        original died downstream still reaches the final hop without any
        stage recomputing or re-absorbing KV)."""
        key = self._session_dedup_key(p)
        seq = int(p["seq"])
        if seq > rt.session_seq.get(key, -1):
            return False
        cached = rt.session_resp.get(key)
        if cached is not None and cached[0] == seq:
            _, kind, payload = cached
            if kind == "resp" and p.get("rid"):
                self._respond(
                    p.get("reply_to") or p["peer"], proto.FORWARD_RESP,
                    p["rid"], payload,
                )
            elif kind == "chain":
                body = dict(payload["body"], _rid=p.get("rid"))
                self.bridge.request(
                    "chain_send", {**payload, "body": body}, timeout=150.0
                )
        return True

    def _session_applied(self, rt: "StageRuntime", p: dict, kind: str, payload) -> None:
        """Record a completed session op (seq watermark + cached outcome).
        Recorded at COMPLETION, not at entry, so a failed op stays
        retryable instead of its retry being swallowed as a dup."""
        if p.get("session") is None or p.get("seq") is None:
            return
        key = self._session_dedup_key(p)
        rt.session_seq[key] = int(p["seq"])
        rt.session_resp[key] = (int(p["seq"]), kind, payload)

    def _finish_fwd(self, rt: "StageRuntime", p: dict, out, is_logits: bool) -> None:
        """Deliver a (non-training) forward result: forward to the next
        chain hop worker-to-worker (ml/module.py::_forward_chain — the
        activation never transits the user), sample on-device when this hop
        produced the final logits of a decode step, or respond with the
        array. ``reply_to`` names the chain's originator; per-hop requests
        have none and answer their direct peer."""
        import jax
        import numpy as np

        chain = p.get("chain") or []
        if p.get("op") == "chain" and chain:
            nxt = chain[0]
            body = {
                k: p[k] for k in self._CHAIN_KEYS if p.get(k) is not None
            }
            body.update(
                op="chain",
                chain=chain[1:],
                head_hop=bool(nxt.get("head")),
                hidden=np.asarray(jax.device_get(out)),
                _rid=p["rid"],  # the originator's future resolves on this
            )
            req = {"addr": list(nxt["addr"]), "tag": proto.FORWARD,
                   "body": body}
            self._session_applied(rt, p, "chain", req)
            self.bridge.request(
                "chain_send", req,
                # generous: a multi-GB activation over DCN outlives the
                # 30 s IPC default, and a spurious timeout here would race
                # an error reply against the still-progressing chain
                timeout=150.0,
            )
            return
        reply_peer = p.get("reply_to") or p["peer"]

        def respond_final(body: dict) -> None:
            if p.get("trace"):
                # ship this process's spans for the op's trace ids home
                # (the pipelined admission op carries them): the client
                # ingests, so /trace names the workers the prefill
                # touched. Mid-chain stages in OTHER processes keep
                # their hop spans local — only the responding process's
                # tracer rides this reply.
                from tensorlink_tpu.core.trace import get_tracer

                tracer = get_tracer()
                body["trace_spans"] = {
                    str(t): tracer.collect(str(t)) for t in p["trace"]
                }
            self._session_applied(rt, p, "resp", body)
            self._respond(reply_peer, proto.FORWARD_RESP, p["rid"], body)

        if p.get("sample") is not None and is_logits:
            samp = p["sample"]
            if samp.get("verify"):
                # pipelined speculative decode: ship the ARGMAX id at
                # EVERY position of this step — the driver accepts the
                # matched draft prefix plus the correction token
                # (engine/generate.py::generate_lookahead semantics)
                import jax.numpy as jnp_

                ids = self._to_host(rt, jnp_.argmax(out, axis=-1))
                respond_final({"verify_ids": np.asarray(ids, np.int32)})
                return
            if samp.get("beam_k"):
                # pipelined beam search: ship K x (K+n_eos) candidate
                # (score, id) pairs from an on-device top-k — not [K, V]
                # logits — to the frontier driver (ml/module.py)
                vals, idx = self._beam_topk_from_logits(rt, out, p)
                respond_final({"beam_vals": vals, "beam_idx": idx})
                return
            # final logits of a decode step: sample on-worker and ship one
            # token id per row — the per-token logits transfer (~600 KB at
            # a 151k vocab) never leaves the device host
            tok = self._sample_from_logits(rt, out, p)
            respond_final({"token": tok})
            return
        host_out = self._to_host(rt, out)  # collective on spanning meshes —
        # must run on EVERY member, so it happens before the mirror check
        if p.get("mirror"):
            # co-slice member of a mirrored work item: the launches above
            # were this process's half of the SPMD programs; only the
            # primary's response carries the payload
            self._respond(
                reply_peer, proto.FORWARD_RESP, p["rid"], {"ok": True}
            )
            return
        respond_final({"out": host_out, "is_logits": is_logits})

    def _beam_topk_from_logits(self, rt: "StageRuntime", logits, p: dict):
        """Head-worker half of PIPELINED beam search: gather each row's
        step logits, take the top-(K+n_eos) of the log-softmax on device
        (engine/generate.py::_beam_topk — tie-break parity with stable
        argsort is pinned there) and return host arrays."""
        import jax.numpy as jnp

        from tensorlink_tpu.engine.generate import _beam_topk

        samp = p["sample"]
        last_idx = p.get("last_idx")
        if logits.ndim == 3:
            B = logits.shape[0]
            if last_idx is not None:
                gidx = jnp.asarray(np.asarray(last_idx, np.int32))
            else:
                gidx = jnp.full((B,), logits.shape[1] - 1, jnp.int32)
            step_logits = logits[jnp.arange(B), gidx]
        else:
            step_logits = logits
        K = int(samp["beam_k"])
        kk = K + int(samp.get("beam_n_eos", 0))
        vals, idx = _beam_topk(step_logits[:K], max(kk, 1))
        return self._to_host(rt, vals), self._to_host(rt, idx)

    def _sample_from_logits(self, rt: "StageRuntime", logits, p: dict) -> np.ndarray:
        """Worker-side sampling for pipelined decode (ml/module.py
        _generate_pipelined): gather each row's last real position (prefill)
        or the single decode position, then run the jitted sampler with a
        deterministic (seed, step)-derived key.

        Presence/frequency penalties carry [B, V] context counts ACROSS the
        session's decode steps on this worker (rt.penalty_counts): step 0
        scatters the prompt ids shipped in the sample dict, and each sampled
        token folds back in — so penalized requests work on pipelined
        models instead of 400ing (the reference applies HF sampling
        uniformly regardless of distribution, ml/worker.py:359-430)."""
        import jax
        import jax.numpy as jnp

        from tensorlink_tpu.engine.sampling import SamplingParams, sample

        samp: dict = p["sample"]
        last_idx = p.get("last_idx")
        if logits.ndim == 3:
            B = logits.shape[0]
            if last_idx is not None:
                idx = jnp.asarray(np.asarray(last_idx, np.int32))
            else:
                idx = jnp.full((B,), logits.shape[1] - 1, jnp.int32)
            step_logits = logits[jnp.arange(B), idx]
        else:
            B = logits.shape[0]
            step_logits = logits
        t = samp.get("temperature", 0.0)
        pen_p = samp.get("presence_penalty", 0.0)
        pen_f = samp.get("frequency_penalty", 0.0)

        def any_nonzero(v):
            vals = v if isinstance(v, (list, tuple, np.ndarray)) else [v]
            return any(float(x or 0.0) != 0.0 for x in vals)

        penalized = any_nonzero(pen_p) or any_nonzero(pen_f)
        if samp.get("seeds") is not None:
            # pipelined slot admission (continuous batching): each row
            # samples with its OWN stateless key chain —
            # fold_in(PRNGKey(seed_r), step_r) — so a slot's stream never
            # depends on its neighbors, admission step offsets differ per
            # row, and a recovered session resumes its draws exactly.
            # (Non-penalized only; the slot scheduler routes penalized
            # requests through the co-batch path.)
            from tensorlink_tpu.engine.continuous import (
                _row_keys, _sample_rows,
            )

            def row(v, dtype, fill):
                vals = (
                    list(v) if isinstance(v, (list, tuple, np.ndarray))
                    else [v if v is not None else fill] * B
                )
                return jnp.asarray(np.asarray(vals, dtype))

            keys = _row_keys(
                row(samp["seeds"], np.int32, 0),
                row(samp.get("steps", 0), np.int32, 0),
            )
            tok = _sample_rows(
                step_logits, keys,
                row(t, np.float32, 0.0),
                row(samp.get("top_k", 0), np.int32, 0),
                row(samp.get("top_p", 1.0), np.float32, 1.0),
                row(pen_p, np.float32, 0.0),
                row(pen_f, np.float32, 0.0),
                jnp.zeros((B, rt.cfg.vocab_size), jnp.int32),
            )
            return self._to_host(rt, tok)
        if isinstance(t, (list, tuple, np.ndarray)):
            # batched serving mixes requests with different knobs: [B, 1]
            # leaves ride ONE compiled sampler (engine/sampling.py contract)
            def col(v, dtype):
                # scalars replicate across rows (NOT pad-fill — every row
                # shares the one requested value)
                if not isinstance(v, (list, tuple, np.ndarray)):
                    v = [v] * len(list(t))
                return jnp.asarray(v, dtype).reshape(-1)[:, None]

            sp = SamplingParams(
                temperature=col(t, jnp.float32),
                top_k=col(samp.get("top_k", 0), jnp.int32),
                top_p=col(samp.get("top_p", 1.0), jnp.float32),
                presence_penalty=col(pen_p, jnp.float32),
                frequency_penalty=col(pen_f, jnp.float32),
            )
        else:
            sp = SamplingParams.make(
                temperature=float(t),
                top_k=int(samp.get("top_k", 0)),
                top_p=float(samp.get("top_p", 1.0)),
                presence_penalty=float(pen_p or 0.0),
                frequency_penalty=float(pen_f or 0.0),
            )
        counts = None
        session = p.get("session")
        if penalized and session is not None:
            counts = rt.penalty_counts.get(session)
            if counts is None:
                # session start: counts = the prompt's token histogram
                pt = np.asarray(samp["prompt_tokens"], np.int64)
                pm = np.asarray(samp["prompt_mask"], bool)
                c = np.zeros((pt.shape[0], rt.cfg.vocab_size), np.int32)
                for i in range(pt.shape[0]):
                    np.add.at(c[i], pt[i][pm[i]], 1)
                counts = jnp.asarray(c)
        key = jax.random.fold_in(
            jax.random.PRNGKey(int(samp.get("seed", 0))),
            int(samp.get("step", 0)),
        )
        tok = sample(step_logits, key, sp, counts)
        if counts is not None:
            # fold the sampled token into the context for the next step
            # (rows the driver has finished keep sampling; their counts
            # drift but their outputs are discarded host-side)
            rt.penalty_counts[session] = counts.at[
                jnp.arange(counts.shape[0]), tok
            ].add(1)
        return self._to_host(rt, tok)

    # -- backward (reference _handle_backward replays torch autograd,
    # ml/worker.py:233-291; here it applies the recorded vjp) -------------
    def _backward(self, p: dict) -> None:
        import jax
        import jax.numpy as jnp

        rt = self._runtime(p["job_id"])
        tag = p.get("tag", "")
        op = p.get("op", "stage")
        key = tag + ".head" if op == "head" else tag
        entry = rt.saved.pop(key, None)
        if entry is None:
            raise KeyError(f"no saved activations for tag {key!r}")
        kind, flags, x_in, mask, wrt_input = entry
        g = self._to_device(
            rt, np.asarray(p["grad"])
        ).astype(rt.cfg.dtype)
        if kind == "head":
            grad_params, grad_input = self._head_bwd(rt)(rt.params, x_in, g)
        else:
            shapes = (
                x_in.shape, str(x_in.dtype),
                None if mask is None else mask.shape,
            )
            _, bwd_prog = self._train_programs(rt, flags, shapes)
            grad_params, grad_input = bwd_prog(rt.params, x_in, mask, g)
        self._accumulate(rt, grad_params)
        body = {"ok": True}
        if grad_input is not None:
            host_g = self._to_host(rt, grad_input)  # collective when
            # spanning — run on every member before any mirror slimming
            if not p.get("mirror"):
                body["grad"] = host_g
        self._respond(p["peer"], proto.BACKWARD_RESP, p["rid"], body)

    def _head_bwd(self, rt: StageRuntime):
        """Cached jitted backward for the tied-embedding head hop."""
        import jax

        from tensorlink_tpu.models.transformer import head_forward

        prog = rt.bwd_cache.get("head")
        if prog is None:

            def bwd(params, h, g):
                _, vjp = jax.vjp(
                    lambda prm, hh: head_forward(prm, hh, rt.cfg), params, h
                )
                return vjp(g)

            prog = jax.jit(bwd)
            rt.bwd_cache["head"] = prog
        return prog

    def _accumulate(self, rt: StageRuntime, grads) -> None:
        import jax

        if rt.grad_accum is None:
            rt.grad_accum = grads
        else:
            rt.grad_accum = jax.tree.map(
                lambda a, b: a + b, rt.grad_accum, grads
            )
        rt.n_accum += 1

    # -- optimizer (reference optimizer RPC fan-out, ml/optim.py:81-205;
    # here each stage runs optax on its own sharded params) ---------------
    def _optimizer(self, p: dict) -> None:
        import jax
        import optax

        from tensorlink_tpu.engine.training import make_optimizer

        rt = self._runtime(p["job_id"])
        op = p.get("op")
        if op == "init":
            spec = dict(p.get("spec", {}))
            name = spec.pop("name", "adamw")
            rt.opt = make_optimizer(name, **spec)
            rt.opt_state = rt.opt.init(rt.params)
            self._maybe_shard_opt_state(rt)
            body = {"ok": True, "op": op}
        elif op == "zero":
            rt.grad_accum = None
            rt.n_accum = 0
            body = {"ok": True, "op": op}
        elif op == "grad_norm":
            # this stage's raw accumulated-cotangent norm; the driver
            # combines stages into the true global norm so clipping matches
            # the single-program optimizer chain (engine/training.py)
            gn = (
                float(self._to_host(rt, optax.global_norm(rt.grad_accum)))
                if rt.grad_accum is not None
                else 0.0
            )
            body = {"ok": True, "op": op, "grad_norm": gn}
        elif op == "step":
            if self.faults is not None:
                # fault site "worker.train_step": fires BEFORE the update is
                # applied, so a crash here loses the in-flight step — the
                # situation auto-checkpointing exists to bound
                self.faults.inject("worker.train_step", op)
            if rt.opt is None:
                raise ValueError("optimizer not initialized")
            if rt.grad_accum is None:
                raise ValueError("no accumulated gradients")
            scale = float(p.get("scale", 1.0))
            if scale != 1.0:
                # driver-supplied 1/total_tokens: turns the accumulated
                # sum-NLL cotangents into the token-mean gradient
                rt.grad_accum = jax.tree.map(
                    lambda g: g * scale, rt.grad_accum
                )
            updates, rt.opt_state = rt.opt.update(
                rt.grad_accum, rt.opt_state, rt.params
            )
            rt.params = optax.apply_updates(rt.params, updates)
            if self._zero1_dp(rt) > 1:
                # sharded updates make `p + u` inherit the data-sharded
                # layout — put params back in their stage specs
                # (replicated over data) so the forward programs' input
                # layout never drifts across optimizer steps
                rt.params = self._shard_params(
                    rt.params, rt.cfg, rt.stage, rt.mesh
                )
            if rt.engine is not None:
                rt.engine.params = rt.params
            gnorm = float(self._to_host(rt, optax.global_norm(rt.grad_accum)))
            self._record_proof(rt, gnorm)
            rt.grad_accum = None
            rt.n_accum = 0
            body = {"ok": True, "op": op, "grad_norm": gnorm}
        else:
            raise ValueError(f"unknown optimizer op {op!r}")
        self._respond(p["peer"], proto.OPTIMIZER_RESP, p["rid"], body)

    def _zero1_dp(self, rt: StageRuntime) -> int:
        """The stage's ZeRO-1 data-parallel degree: >1 only when the plan
        gave this training stage a data axis (parallel/planner.py::
        training_update_mode — the one predicate) and a real mesh backs
        it. 0/1 means the unsharded optimizer layout."""
        if rt.mesh is None or not rt.training:
            return 0
        from tensorlink_tpu.parallel.planner import training_update_mode

        axes = rt.stage.get("mesh_axes") or {}
        if training_update_mode(axes, rt.training) != "zero1":
            return 0
        return int(axes.get("data", 1))

    def _maybe_shard_opt_state(self, rt: StageRuntime) -> None:
        """ZeRO-1 on the RPC training path (docs/TRAINING.md): when the
        stage mesh carries a data axis, the optimizer state is DECLARED
        sharded 1/dp over it at init (params stay in their stage specs —
        replicated over data), so the eager optax update runs sharded and
        per-replica optimizer bytes drop to ~1/dp. Same locality the
        compiled zero1 step gets, without new programs on this path."""
        dp = self._zero1_dp(rt)
        if dp <= 1:
            return
        import jax
        from jax.sharding import NamedSharding

        from tensorlink_tpu.engine.training import optimizer_state_specs
        from tensorlink_tpu.parallel.planner import (
            StagePlan,
            stage_param_specs,
        )

        pspecs = stage_param_specs(rt.cfg, StagePlan(**rt.stage))
        sspecs = optimizer_state_specs(
            rt.opt, rt.params, pspecs, dp_axis="data", dp_size=dp,
        )
        rt.opt_state = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(rt.mesh, s)),
            rt.opt_state, sspecs,
        )

    # -- proof of learning (platform/proofs.py; reference scaffolding
    # never wired, ml/proofs.py + job_monitor.py:193-207) -----------------
    MAX_PROOF_LOG = 256
    PROOF_WINDOW = 32  # entries shipped per PROOF_REQ

    def _record_proof(self, rt: StageRuntime, grad_norm: float) -> None:
        from tensorlink_tpu.platform import proofs

        rt.opt_steps += 1
        try:
            sketch = proofs.gradient_sketch(
                rt.grad_accum, seed=int(rt.job_id[:8], 16)
            )
        except Exception:  # noqa: BLE001 — the sketch is telemetry; on a
            # process-spanning mesh its per-leaf gathers may produce
            # non-addressable outputs, and the proof CHAIN (hash over
            # grad_norm) must keep growing regardless
            self.log.debug("gradient sketch unavailable", exc_info=True)
            sketch = np.zeros(0)
        prev = rt.proof_log[-1]["hash"] if rt.proof_log else ""
        rt.proof_log.append(
            proofs.proof_entry(rt.opt_steps, grad_norm, sketch, prev)
        )
        if len(rt.proof_log) > self.MAX_PROOF_LOG:
            del rt.proof_log[: -self.MAX_PROOF_LOG]

    def _proof_req(self, p: dict) -> None:
        rt = self._runtime(p["job_id"])
        window = [dict(e) for e in rt.proof_log[-self.PROOF_WINDOW:]]
        if window and len(rt.proof_log) > len(window):
            # chain root for a truncated window = hash of the entry just
            # before it, so the verifier can still check integrity
            window[0]["_chain_root"] = rt.proof_log[-len(window) - 1]["hash"]
        self._respond(
            p["peer"], proto.PROOF_RESP, p["rid"],
            {"ok": True, "log": window, "total_steps": rt.opt_steps},
        )

    # -- checkpoint (net-new vs reference: no mid-training checkpoint
    # exists there, SURVEY §5) -------------------------------------------
    def _checkpoint(self, p: dict) -> None:
        """Save/restore this stage's params (+ optimizer state). Works on
        merged (process-spanning) co-slice stages too: the work item is
        MIRRORED to every member (ml/module.py::_request_mirrored), each
        member executes the same per-leaf gathers/puts (collectives stay
        lockstep), and only the primary touches the file / carries the
        payload — the coworkers answer a slim ack."""
        import jax

        from tensorlink_tpu.core import serialization as ser

        rt = self._runtime(p["job_id"])
        op = p.get("op", "save")
        mirror = bool(p.get("mirror"))
        path = Path(p["dir"]) / f"stage_{rt.stage['layer_lo']}_{rt.stage['layer_hi']}.tlts"
        if op == "save":
            # _to_host gathers the full value on process-spanning meshes
            # (plain device_get cannot see non-addressable shards); every
            # member must run the gathers even though only the primary writes
            host = jax.tree.map(
                lambda a: self._to_host(rt, a), self._exact_params(rt)
            )
            opt_host = (
                jax.tree.map(lambda a: self._to_host(rt, a), rt.opt_state)
                if rt.opt_state is not None else None
            )
            if mirror:
                self._respond(
                    p["peer"], proto.CHECKPOINT_RESP, p["rid"],
                    {"ok": True, "mirror": True},
                )
                return
            path.parent.mkdir(parents=True, exist_ok=True)
            state = {"params": host, "stage": rt.stage}
            if opt_host is not None:
                state["opt_state"] = opt_host
            ser.encode_to_file(state, path)
            body = {"ok": True, "path": str(path)}
        elif op == "restore":
            import jax.numpy as jnp

            state = ser.decode_from_file(path)
            host = jax.tree.map(np.asarray, state["params"])
            if rt.mesh is not None:
                # re-shard on the stage mesh (every member of a merged stage
                # read the same bytes and builds the same global arrays);
                # a bare jnp.asarray would silently replicate a sharded stage
                rt.params = self._shard_params(host, rt.cfg, rt.stage, rt.mesh)
            else:
                rt.params = jax.tree.map(jnp.asarray, host)
            restored_opt = False
            if "opt_state" in state and rt.opt is not None:
                from jax.sharding import NamedSharding

                tmpl = rt.opt.init(rt.params)
                flat_t, treedef = jax.tree.flatten(tmpl)
                restored = jax.tree.leaves(state["opt_state"])
                leaves = []
                for t_leaf, r in zip(flat_t, restored):
                    sh = getattr(t_leaf, "sharding", None)
                    arr = np.asarray(r)
                    # mesh-sharded template leaves (moments mirroring the
                    # sharded params) get their sharding back — on a
                    # spanning mesh a local jnp.asarray could not mix with
                    # global params in the update. Everything else (step
                    # counters etc.) stays an UNCOMMITTED array: committing
                    # a scalar to one device would conflict with the
                    # mesh-resident moments in the same eager update.
                    leaves.append(
                        jax.device_put(arr, sh)
                        if isinstance(sh, NamedSharding)
                        else jnp.asarray(arr)
                    )
                rt.opt_state = jax.tree.unflatten(treedef, leaves)
                restored_opt = True
            if rt.engine is not None:
                rt.engine.params = rt.params
            body = {"ok": True, "restored_opt": restored_opt,
                    "opt_in_checkpoint": "opt_state" in state}
            if mirror:
                body = {"ok": True, "mirror": True}
        else:
            raise ValueError(f"unknown checkpoint op {op!r}")
        self._respond(p["peer"], proto.CHECKPOINT_RESP, p["rid"], body)

    # -- generate (whole-model jobs) ------------------------------------
    def _generate(self, p: dict) -> None:
        """Compiled generation on a whole-model job. Streams token ids over
        the TOKEN relay when ``stream`` is set (reference worker streamer,
        ml/worker.py:359-447), then resolves with the full sequences."""
        from tensorlink_tpu.engine.sampling import SamplingParams

        rt = self._runtime(p["job_id"])
        if rt.engine is None:
            raise ValueError("generate requires a whole-model stage")
        prompts = [list(map(int, row)) for row in p["prompts"]]
        if p.get("continuous") and self._generate_continuous(rt, p, prompts):
            return  # admitted into the slot batch; responds via on_finish
        knobs = (
            p.get("temperature", 0.0), p.get("top_k", 0), p.get("top_p", 1.0),
            p.get("presence_penalty", 0.0), p.get("frequency_penalty", 0.0),
        )
        # per-row knobs (ml/batching.py mixes requests); a scalar among
        # sequences applies to every row. Scalars are ALSO stacked to
        # [B, 1] leaves so every serving request — solo or co-batched —
        # shares the one warmed program (leaf shapes key the jit cache;
        # engine.warmup() pre-compiles exactly this shape)
        n = len(prompts)

        def rows(v):
            return list(v) if isinstance(v, (list, tuple)) else [v] * n

        per_row = [
            SamplingParams.make(
                temperature=float(t), top_k=int(k), top_p=float(tp),
                presence_penalty=float(pp), frequency_penalty=float(fp),
            )
            for t, k, tp, pp, fp in zip(*(rows(v) for v in knobs))
        ]
        sampling = SamplingParams.stack(per_row, pad_to=n)
        budgets = p.get("budgets")
        reuse_prefix = bool(p.get("reuse_prefix", False)) and len(prompts) == 1
        # prompt-lookup speculation: greedy B=1 only (it IS vanilla greedy,
        # in fewer model passes) — and penalties change greedy's choices,
        # so a penalized request must take the vanilla loop
        greedy = not isinstance(p.get("temperature", 0.0), (list, tuple)) \
            and float(p.get("temperature", 0.0)) <= 0.0
        lookahead = (
            bool(p.get("lookahead", False)) and len(prompts) == 1 and greedy
            and not any(
                isinstance(v, (list, tuple)) or float(v or 0.0) != 0.0
                for v in knobs[3:]
            )
        )
        stream_id = p.get("stream")
        peer = p["peer"]
        chunk_cfg = int(self.node.config.ml.stream_chunk_steps or 0)
        # confirmed stop-sequence cancels ride back from the driving user
        # as STREAM_CANCEL frames parked on the network server; poll them
        # every `poll_every` steps — one blocking IPC round trip per chunk,
        # not per token — so the compiled chunked decode overruns a stop by
        # at most one chunk instead of the full token budget
        poll_every = chunk_cfg if chunk_cfg > 0 else 32
        steps_seen = 0

        def stream_cb(emitted):
            # (row, token) pairs keep attribution for batched streams; the
            # driver reconstructs the per-row emission list
            nonlocal steps_seen
            pairs = [[i, t] for i, t in enumerate(emitted) if t is not None]
            if pairs:
                # fire-and-forget: a blocking round-trip here would add a
                # full IPC latency to every decode step
                self.bridge.notify(
                    "send_token",
                    {"peer": peer, "stream": stream_id, "tokens": pairs},
                )
            steps_seen += 1
            if stream_id and steps_seen % poll_every == 0:
                try:
                    rows = self.bridge.request(
                        "poll_cancel", {"stream": stream_id}, timeout=5.0
                    )
                except Exception:
                    rows = None  # relay hiccup must not kill the decode
                return rows or None
            return None

        if int(p.get("num_beams", 1)) > 1:
            # beams ride the engine's batch axis — clamp to the largest
            # compiled bucket (a deployment-config mismatch must degrade,
            # not surface as an opaque 500) — but never SILENTLY: the API
            # schema promised [1, 8], so the clamp is logged and the
            # effective width rides the response for clients to inspect
            k = min(int(p["num_beams"]), max(rt.engine.batch_buckets))
            if k < int(p["num_beams"]):
                self.log.warning(
                    "num_beams=%d clamped to %d (largest compiled batch "
                    "bucket; configure batch_buckets to serve wider beams)",
                    int(p["num_beams"]), k,
                )
            st = rt.engine.beam_start(
                prompts,
                num_beams=k,
                max_new_tokens=int(p.get("max_new_tokens", 128)),
                eos_ids=p.get("eos_ids", ()),
            )
            rt.beam_sessions[p["rid"]] = (st, p, k)
            self._beam_step(p["job_id"], p["rid"])
            return
        if lookahead:
            result = rt.engine.generate_lookahead(
                prompts,
                max_new_tokens=int(p.get("max_new_tokens", 128)),
                eos_ids=p.get("eos_ids", ()),
                reuse_prefix=reuse_prefix,
                stream_cb=stream_cb if stream_id else None,
            )
            if stream_id:
                self.bridge.request(
                    "send_token",
                    {"peer": peer, "stream": stream_id, "tokens": [],
                     "done": True},
                )
        elif stream_id:
            chunk = int(self.node.config.ml.stream_chunk_steps or 0)
            gen_kw = dict(
                max_new_tokens=int(p.get("max_new_tokens", 128)),
                sampling=sampling,
                eos_ids=p.get("eos_ids", ()),
                seed=int(p.get("seed", 0)),
                stream_cb=stream_cb,
                budgets=budgets,
                reuse_prefix=reuse_prefix,
            )
            if chunk > 0:
                # compiled-chunk streaming: one host round trip per
                # `chunk` tokens instead of per token — the difference
                # between usable and crawling streams over a tunneled chip
                result = rt.engine.generate_chunked(
                    prompts, chunk_steps=chunk, **gen_kw
                )
            else:
                result = rt.engine.generate(prompts, **gen_kw)
            self.bridge.request(
                "send_token",
                {"peer": peer, "stream": stream_id, "tokens": [], "done": True},
            )
        else:
            # non-streaming always takes the fully-compiled loop — per-row
            # budgets ride _decode_loop's limits, so batched mixes stay on
            # device too
            result = rt.engine.generate_compiled(
                prompts,
                max_new_tokens=int(p.get("max_new_tokens", 128)),
                sampling=sampling,
                eos_ids=p.get("eos_ids", ()),
                seed=int(p.get("seed", 0)),
                budgets=budgets,
                reuse_prefix=reuse_prefix,
            )
        if stream_id:
            # release any cancel rows parked for this stream server-side
            self.bridge.notify("clear_cancels", {"stream": stream_id})
        self._respond(
            peer, proto.GENERATE_RESP, p["rid"],
            {
                "sequences": [list(map(int, s)) for s in result.sequences],
                "finished": list(map(bool, result.finished)),
            },
        )

    # -- continuous batching (engine/continuous.py) ----------------------
    def _generate_continuous(self, rt: "StageRuntime", p: dict,
                             prompts: list[list[int]]) -> bool:
        """Admit a GENERATE flagged ``continuous`` into the job's slot
        engine. Returns False when the request can't take the continuous
        path (per-row knob lists, beams, lookahead, or a model the paged
        engine refuses) — the caller then falls through to the static
        engine paths, so the flag can never fail a request."""
        from tensorlink_tpu.engine.sampling import SamplingParams

        knobs = (
            p.get("temperature", 0.0), p.get("top_k", 0),
            p.get("top_p", 1.0), p.get("presence_penalty", 0.0),
            p.get("frequency_penalty", 0.0),
        )
        if (
            len(prompts) != 1
            or any(isinstance(v, (list, tuple)) for v in knobs)
            or int(p.get("num_beams", 1)) > 1
            or p.get("lookahead")
        ):
            return False
        if self.draining is not None:
            # admission fence: this worker is shedding its slots — redirect
            # the request to the drain destination (the client re-issues
            # there; an empty tokens_so_far means a plain resubmission)
            self._respond_migrated(
                rt.cont,
                {"peer": p["peer"], "rid": p["rid"],
                 "stream": p.get("stream"),
                 "trace": str(p.get("trace") or "")},
                self.draining, None, [],
            )
            return True
        cont = self._ensure_cont(rt)
        if cont is None:
            return False
        tid = str(p.get("trace") or "")
        jrid = str(p.get("jrid") or "")
        want = str(p.get("reattach") or "")
        if want and self._reattach_continuous(rt, cont, p, want):
            return True
        # a re-attach MISS falls through here on purpose: the request body
        # already carries prompt+delivered and start_step, so plain
        # admission below IS the re-prefill resume rung (bit-identical by
        # the fold_in sampling contract) — no extra round trip
        t, k, tp, pp, fp = knobs
        sampling = SamplingParams.make(
            temperature=float(t), top_k=int(k), top_p=float(tp),
            presence_penalty=float(pp or 0.0),
            frequency_penalty=float(fp or 0.0),
        )
        stream_id = p.get("stream")
        peer = p["peer"]
        stream_cb, on_finish = self._cont_channels(
            rt, cont, peer=peer, rid=p["rid"], stream_id=stream_id,
            tid=tid, jrid=jrid,
        )
        req = cont.submit(
            prompts[0],
            max_new_tokens=int(p.get("max_new_tokens", 128)),
            sampling=sampling,
            eos_ids=p.get("eos_ids", ()),
            seed=int(p.get("seed", 0)),
            start_step=int(p.get("start_step", 0)),
            priority=p.get("priority"),
            stream_cb=stream_cb if stream_id else None,
            on_finish=on_finish,
            # resume-after-migration: bind the staged KV pages instead of
            # re-prefilling (engine falls back when the ticket is stale)
            adopt=p.get("adopt") or None,
            trace_id=tid,
            # draft/verify opt-in (no-op unless this engine's spec_decode
            # is on; streams bit-identical either way)
            speculative=bool(p.get("speculative", False)),
            # disaggregated prefill/decode: on a prefill-pool worker with
            # a live decode pool, this admission freezes at its
            # prefill→decode boundary and _run_handoffs ships it —
            # unless the request opted out ({"handoff": false}) or is
            # itself a migration resume (adopt) bouncing through
            handoff=bool(
                self._handoff_pool_for(rt.job_id)
                and p.get("handoff", True) is not False
                and not p.get("adopt")
            ),
        )
        # transport context for live migration: a drain must redirect this
        # stream mid-flight, which needs the original peer/rid/stream —
        # the on_finish/stream closures are opaque, this is not
        req.client_meta = {
            "peer": peer, "rid": p["rid"], "stream": stream_id,
            "trace": tid, "jrid": jrid,
        }
        if jrid:
            rt.jstreams[jrid] = req
        self._schedule_cont(rt)
        return True

    def _cont_channels(self, rt: "StageRuntime", cont, *, peer, rid,
                       stream_id, tid, jrid="", resume_base=None):
        """Build the (stream_cb, on_finish) transport-closure pair for a
        continuous stream. Shared by first admission and by the re-attach
        rebinding so both transports behave identically — the only
        difference is ``resume_base``: set on a re-attach, the final
        response carries {"reattached": True, "resume_base": base} so the
        client merges sequences (this-submission tokens) onto its
        delivered[:base] prefix exactly-once."""
        state = {"n": 0}

        def stream_cb(tok: int):
            # fire-and-forget per token; cancel frames (confirmed stop
            # matches) poll once per chunk — overrun bounded like the
            # compiled chunked stream
            self.bridge.notify(
                "send_token",
                {"peer": peer, "stream": stream_id, "tokens": [[0, int(tok)]]},
            )
            state["n"] += 1
            if state["n"] % cont.chunk_steps == 0:
                try:
                    rows = self.bridge.request(
                        "poll_cancel", {"stream": stream_id}, timeout=5.0
                    )
                except Exception:
                    rows = None  # relay hiccup must not kill the decode
                return bool(rows)
            return False

        def on_finish(req):
            if stream_id:
                try:
                    self.bridge.request(
                        "send_token",
                        {"peer": peer, "stream": stream_id, "tokens": [],
                         "done": True},
                    )
                    self.bridge.notify("clear_cancels", {"stream": stream_id})
                except Exception as e:
                    self.log.debug(
                        "stream %s done-marker push failed: %s",
                        stream_id, e,
                    )
            if jrid:
                rt.jstreams.pop(jrid, None)
                if req.error is None:
                    # the GENERATE_RESP below may be going to a dead
                    # validator — keep the result in the bounded orphan
                    # ledger so a re-attach can still drain it
                    self._stash_orphan(rt, jrid, req)
            if req.error is not None:
                try:
                    self._respond(
                        peer, proto.GENERATE_RESP, rid,
                        {"error": f"{type(req.error).__name__}: {req.error}",
                         "worker": self.node.node_id},
                    )
                except Exception as e:
                    # the requester is gone — an undeliverable error reply
                    # must not propagate into step_chunk and error the
                    # ENGINE (closing it evicts every other live stream
                    # that is decoding through the validator outage)
                    self.log.warning(
                        "error response for %s undeliverable: %s", rid, e)
                return
            body = {
                "sequences": [list(map(int, req.tokens))],
                "finished": [bool(req.finished)],
                "continuous": True,
                # engine occupancy + prefix-cache counters ride every
                # response so the validator's /stats can surface them
                # without a dedicated polling RPC
                "serving": cont.serving_snapshot(),
            }
            if resume_base is not None:
                body["reattached"] = True
                body["resume_base"] = int(resume_base)
            if tid:
                # this worker's spans for the request ride home the same
                # way — the validator ingests them so /trace stitches a
                # request's hops without any polling RPC
                body["trace"] = {
                    "id": tid, "spans": cont.tracer.collect(tid),
                }
            try:
                self._respond(peer, proto.GENERATE_RESP, rid, body)
            except Exception as e:
                # dead validator (the crash-safety orphan path): the
                # result is already stashed in the orphan ledger above —
                # letting this propagate would error the ENGINE via
                # step_chunk and evict every OTHER stream still decoding
                # through the outage
                self.log.warning(
                    "final response for %s undeliverable (orphan %s kept): %s",
                    rid, jrid or "-", e)

        return stream_cb, on_finish

    def _stash_orphan(self, rt: "StageRuntime", jrid: str, req) -> None:
        """Record a finished continuous stream in the bounded orphan
        ledger (MLConfig.orphan_keep / orphan_ttl_s). If the final
        response reached a live client the entry just ages out; if the
        validator was dead it is what the re-attach ladder drains
        (popped on delivery — exactly-once)."""
        ml = self.node.config.ml
        keep = int(getattr(ml, "orphan_keep", 64))
        if keep <= 0:
            return
        now = time.monotonic()
        ttl = float(getattr(ml, "orphan_ttl_s", 180.0))
        for k in [k for k, v in rt.orphans.items() if now - v["t"] > ttl]:
            rt.orphans.pop(k, None)
        while len(rt.orphans) >= keep:  # dict preserves insertion order
            rt.orphans.pop(next(iter(rt.orphans)), None)
        rt.orphans[jrid] = {
            "tokens": [int(t) for t in req.tokens],
            "base": int(req.start_step),
            "t": now,
        }

    def _orphan_report(self, rt: "StageRuntime") -> list[dict]:
        """Per-jrid live/finished stream announcement riding the
        attach_only MODULE_LOADED ack — the worker's half of journal
        reconciliation (its token counts are authoritative; the journal's
        high-water marks are only a floor)."""
        out = []
        for jrid, req in rt.jstreams.items():
            out.append({
                "jrid": jrid,
                "n": int(req.start_step) + len(req.tokens),
                "finished": bool(req.finished),
            })
        for jrid, o in rt.orphans.items():
            out.append({
                "jrid": jrid,
                "n": int(o["base"]) + len(o["tokens"]),
                "finished": True,
            })
        return out

    def _reattach_continuous(self, rt: "StageRuntime", cont, p: dict,
                             jrid: str) -> bool:
        """Worker half of the re-attach ladder. Returns True when handled:
        a LIVE orphaned stream is rebound to the new peer/rid/stream (its
        backlog past the client's high-water mark topped up atomically —
        this runs on the same serial ML thread as decode chunks), or a
        FINISHED orphan is replayed from the ledger. False = miss; the
        caller falls through to plain admission (re-prefill resume)."""
        peer, rid = p["peer"], p["rid"]
        stream_id = p.get("stream")
        tid = str(p.get("trace") or "")
        hwm = int(p.get("hwm", 0))
        req = rt.jstreams.get(jrid)
        if req is not None and not req.finished:
            base = int(req.start_step)
            stream_cb, on_finish = self._cont_channels(
                rt, cont, peer=peer, rid=rid, stream_id=stream_id,
                tid=tid, jrid=jrid, resume_base=base,
            )
            req.client_meta = {
                "peer": peer, "rid": rid, "stream": stream_id,
                "trace": tid, "jrid": jrid,
            }
            req.stream_cb = stream_cb if stream_id else None
            req.on_finish = on_finish
            if stream_id:
                # top up the fresh relay with everything the slot emitted
                # past the client's high-water mark while orphaned
                backlog = req.tokens[max(hwm - base, 0):]
                if backlog:
                    self.bridge.notify(
                        "send_token",
                        {"peer": peer, "stream": stream_id,
                         "tokens": [[0, int(t)] for t in backlog]},
                    )
            self.log.info(
                "reattached live stream jrid=%s (slot tokens=%d, "
                "client hwm=%d)", jrid, len(req.tokens), hwm,
            )
            self._schedule_cont(rt)
            return True
        orphan = rt.orphans.pop(jrid, None)
        if orphan is not None:
            toks = [int(t) for t in orphan["tokens"]]
            base = int(orphan["base"])
            if stream_id:
                try:
                    backlog = toks[max(hwm - base, 0):]
                    if backlog:
                        self.bridge.notify(
                            "send_token",
                            {"peer": peer, "stream": stream_id,
                             "tokens": [[0, int(t)] for t in backlog]},
                        )
                    self.bridge.request(
                        "send_token",
                        {"peer": peer, "stream": stream_id, "tokens": [],
                         "done": True},
                    )
                except Exception as e:
                    self.log.debug(
                        "orphan replay stream push failed: %s", e
                    )
            self._respond(
                peer, proto.GENERATE_RESP, rid,
                {"sequences": [toks], "finished": [True],
                 "continuous": True, "reattached": True,
                 "resume_base": base, "serving": cont.serving_snapshot()},
            )
            self.log.info(
                "replayed finished orphan jrid=%s (%d tokens)",
                jrid, len(toks),
            )
            return True
        return False

    def _ensure_cont(self, rt: "StageRuntime"):
        """The job's slot engine, (re)built after load_stage swapped the
        generation engine (old slots died with their engine's cache).
        None when the model can't serve continuous — callers fall back to
        the static paths."""
        cont = rt.cont
        if cont is not None and cont.engine is rt.engine:
            return cont
        from tensorlink_tpu.engine.continuous import ContinuousEngine

        ml = self.node.config.ml
        pool = None
        quota = 0
        if int(getattr(ml, "cont_pool_pages", 0)) > 0:
            pool = self._shared_kv_pool(rt, ml)
            quota = int(
                (rt.model_spec or {}).get("page_quota")
                or getattr(ml, "cont_pool_quota", 0)
            )
        role = str(getattr(ml, "worker_role", "mixed") or "mixed")
        try:
            rt.cont = cont = ContinuousEngine(
                rt.engine,
                # disaggregated prefill/decode: a prefill-role worker's
                # engine freezes opted-in slots at the prefill→decode
                # boundary for _run_handoffs to ship (docs/SERVING.md)
                handoff_after_prefill=(role == "prefill"),
                worker_role=role,
                # co-hosting (docs/SERVING.md): every job whose page
                # geometry matches shares ONE physical pool under a
                # per-model quota; job_id keys the tenant (unique even
                # when one model hosts twice)
                pool=pool, model_id=rt.job_id, page_quota=quota,
                # spans this engine records carry the worker's identity —
                # the cross-worker stitch /trace serves depends on it
                trace_site=str(self.node.node_id or ""),
                max_slots=int(ml.cont_max_slots),
                page_size=int(ml.cont_page_size),
                chunk_steps=int(ml.cont_chunk_steps),
                prefill_chunk=int(ml.prefill_chunk),
                prefix_cache=bool(ml.prefix_cache),
                # tiered prefix cache (engine/kvtier.py): arm the
                # host-RAM spill tier on the worker's slot engine too —
                # single-stage jobs decode here, and an unarmed worker
                # would silently destroy evicted pages while the
                # validator-side batcher advertises host_tier=True
                host_tier_pages=int(
                    getattr(ml, "cont_host_tier_pages", 0)
                ),
                # `or` before str(): a null kv_quant in an operator
                # config must read as "none", not the string "None"
                kv_quant=str(ml.kv_quant or "none"),
                spec_decode=bool(getattr(ml, "spec_decode", False)),
                spec_draft=int(getattr(ml, "spec_draft", 8)),
                spec_budget=int(getattr(ml, "spec_budget", 0)),
                default_priority=str(ml.default_priority),
                sched_queue_cap=int(ml.sched_queue_cap),
                sched_aging_ticks=int(ml.sched_aging_ticks),
                sched_preemption=bool(ml.sched_preemption),
                sched_policy=str(ml.sched_policy),
                sched_max_wait_s=float(ml.sched_max_wait_s),
                # explicit TP (docs/SHARDING.md): shard the hot path over
                # a tp mesh axis; engines that can't (MoE, indivisible
                # heads, too few devices) refuse with ValueError and land
                # in the static fallback below like any other refusal
                tensor_parallel=int(
                    getattr(ml, "tensor_parallel", 1) or 1
                ),
            )
        except ValueError as e:
            # sliding window (or a bad knob): static batcher territory.
            # int8-KV models ("int8+kv") are NOT refused anymore — the
            # paged engine stores int8 pages natively (kv_quant)
            self.log.info("continuous batching unavailable: %s", e)
            return None
        return cont

    def _shared_kv_pool(self, rt: "StageRuntime", ml):
        """Get-or-create the shared multi-tenant page pool this job's
        engine should draw from (MLConfig.cont_pool_pages > 0). Pools are
        keyed by page GEOMETRY — (layers, kv heads, head_dim, page size,
        kv_quant, dtype) — so models that cannot physically share pages
        transparently get separate pools instead of a loud attach error
        at hosting time."""
        import jax.numpy as jnp

        from tensorlink_tpu.engine.paged import SharedPagePool

        cfg = rt.cfg
        kvq = str(ml.kv_quant or "none")
        if rt.cache_quant and kvq == "none":
            kvq = "int8"  # mirror of the engine's cache_quant forcing
        page_size = int(ml.cont_page_size)
        dtype_str = (
            "int8" if kvq in ("int8", "int4")
            else str(jnp.dtype(rt.engine.cache_dtype))
        )
        key = (
            cfg.n_layers, cfg.n_kv_heads, cfg.head_dim, page_size, kvq,
            dtype_str,
        )
        self._gc_kv_pools(keep=key)
        pool = self._kv_pools.get(key)
        if pool is None:
            pool = SharedPagePool(
                cfg, int(ml.cont_pool_pages), page_size=page_size,
                dtype=rt.engine.cache_dtype, kv_quant=kvq,
            )
            self._kv_pools[key] = pool
            self.log.info(
                "created shared KV page pool %s (%d pages, kv_quant=%s)",
                key, int(ml.cont_pool_pages), kvq,
            )
        return pool

    def _gc_kv_pools(self, keep=None) -> None:
        """Drop shared pools whose LAST tenant detached (their page
        arrays would otherwise pin HBM for the life of the process —
        a worker cycling through hosted geometries would accumulate one
        dead full-size pool per geometry key). ``keep`` spares the key
        about to be (re)used so an empty-but-wanted pool is reused, not
        rebuilt. Called from the serial run loop only."""
        for k in [
            k for k, p in self._kv_pools.items()
            if not p.tenants and k != keep
        ]:
            del self._kv_pools[k]
            self.log.info("released empty shared KV page pool %s", k)

    def _schedule_cont(self, rt: "StageRuntime") -> None:
        if not rt.cont_scheduled:
            rt.cont_scheduled = True
            self.bridge.q.work.put(("cont_continue", {"job_id": rt.job_id}))

    def _cont_step(self, job_id: str) -> None:
        """Drive the slot engine one decode chunk, then requeue — FIFO, so
        every GENERATE that arrived meanwhile is admitted before the next
        chunk (a new request starts decoding within ≤ one chunk of an
        in-flight batch; same bounded-occupancy shape as _beam_step)."""
        with self._lock:
            rt = self.jobs.get(job_id)
        if rt is None or rt.cont is None:
            return
        rt.cont_scheduled = False
        if self.faults is not None:
            # fault site "worker.cont_step" (core/faults.py): one count per
            # decode chunk over a continuously-batched slot set
            self.faults.inject("worker.cont_step", job_id)
        try:
            more = rt.cont.step_chunk()
        except FaultCrash:
            raise  # the run loop takes the node down
        except BaseException as e:  # noqa: BLE001 — fan out per request
            self.log.exception("continuous decode chunk failed")
            rt.cont.close(e)  # responds the error on every live rid
            rt.cont = None
            self._gc_kv_pools()  # release a now-tenantless shared pool
            return
        # steady-state prefill→decode handoff: ship every slot the chunk
        # froze at its prefill boundary BEFORE deciding whether to
        # requeue — a frozen slot is invisible to step_chunk's has_work,
        # so resolving the manifest here is what keeps the engine free of
        # parked in-transit slots between work items. Re-check has_work
        # after: an aborted handoff resumes the slot's prefill HERE, and
        # that revived work must requeue even when the chunk saw none.
        self._run_handoffs(rt)
        if more or (rt.cont is not None and rt.cont.has_work()):
            self._schedule_cont(rt)

    # -- live slot migration + drain (docs/FAILURE_MODEL.md) -------------
    # DRAIN (validator → this worker): fence admissions, then move every
    # live continuous stream to the destination worker — KV-page shipping
    # for steady decode slots (bit-identical resume), the crash-recovery
    # re-prefill rung for everything else (mid-prefill slots, queued
    # requests, and any failed export/wire/import). The client learns via
    # a {"migrated": ...} GENERATE_RESP and re-issues at the destination;
    # a stream is never dropped, only redirected.

    # -- disaggregated prefill/decode: steady-state handoff --------------
    # (docs/SERVING.md "Disaggregated prefill/decode") A prefill-role
    # worker is permanently "draining" its completed prefills: every
    # opted-in admission freezes at the prefill→decode boundary and is
    # shipped here to a decode-pool worker through the SAME
    # export/stage/adopt path a drain uses — but with no admission
    # fence, no capacity zeroing, and a per-slot fallback ladder
    # (page-ship → re-prefill redirect at the destination → resume
    # locally) instead of a worker-wide abort. The client follows the
    # redirect exactly like a drain redirect, except the plan keeps
    # pointing HERE — this worker stays the admission point.

    def _set_replica_set(self, p: dict) -> None:
        """A REPLICA_SET push from the validator (mirrors the HANDOFF
        pool push): the other replicas of the fleet this worker's job
        belongs to, as ``[{id, addr, job_id}, ...]``. Pure wire state —
        consulted only when a DRAIN arrives with no destination."""
        peers = [
            dict(e) for e in (p.get("peers") or [])
            if e.get("id") and e.get("id") != self.node.node_id
            and e.get("addr")
        ]
        job_id = str(p.get("job_id") or "")
        self._replica_sets[job_id] = peers
        self.log.info(
            "replica set (%s): %d sibling(s) %s",
            job_id[:8] or "worker-wide", len(peers),
            [str(e["id"])[:8] for e in peers],
        )

    def _set_handoff_pool(self, p: dict) -> None:
        """A HANDOFF push from the validator: the decode-pool membership
        this (prefill-role) worker ships completed prefills to — scoped
        to the named job ("" = worker-wide operator push)."""
        pool = [
            dict(e) for e in (p.get("pool") or [])
            if e.get("id") and e.get("id") != self.node.node_id
            and e.get("addr")
        ]
        job_id = str(p.get("job_id") or "")
        self._handoff_pools[job_id] = pool
        # membership changed: stale readiness could point at a departed
        # worker, and a fresh pool deserves fresh probes — but only for
        # the job whose pool this push names (a new job's recruit must
        # not cost every OTHER job an inline re-probe on the run loop);
        # the worker-wide "" push refreshes everything
        with self._handoff_prep_lock:
            # the lock covers every mutation of _handoff_dest_ready: the
            # warm thread adds concurrently, and an unguarded add during
            # this comprehension's iteration would raise "set changed
            # size during iteration" in the control-frame handler
            if job_id:
                self._handoff_dest_ready = {
                    k for k in self._handoff_dest_ready if k[0] != job_id
                }
            else:
                self._handoff_dest_ready.clear()
        self.log.info(
            "handoff pool set (%s): %d decode worker(s) %s",
            job_id[:8] or "worker-wide", len(pool),
            [str(e["id"])[:8] for e in pool],
        )
        if pool:
            # pre-warm OFF the run loop: a cold destination's stage ship
            # can take minutes (MODULE timeout 120s), and paying it
            # inside _run_handoffs would stall every co-resident
            # stream's decode between chunks. The push arrives at
            # recruit time — usually before any traffic — so the warm
            # thread normally has the readiness cache populated before
            # the first prefill completes; a handoff that races it just
            # pays the old synchronous prepare once.
            threading.Thread(
                target=self._warm_handoff_dests, args=(job_id,),
                name="handoff-warm", daemon=True,
            ).start()

    def _warm_handoff_dests(self, job_id: str) -> None:
        """Background half of the pool push: probe/ship the job's stage
        to every decode-pool member so the run loop's _pick_handoff_dest
        finds them ready instead of preparing them inline. Job-scoped
        pushes wait briefly for the runtime (HANDOFF and MODULE race at
        recruit time); failures are dropped — the synchronous path
        re-probes on demand and the slot falls back locally at worst."""
        deadline = time.monotonic() + 30.0
        while True:
            with self._lock:
                if job_id:
                    rts = [self.jobs[job_id]] if job_id in self.jobs else []
                else:
                    rts = list(self.jobs.values())
            if rts or time.monotonic() >= deadline:
                break
            time.sleep(0.25)
        for rt in rts:
            pool = self._handoff_pool_for(rt.job_id)
            for dest in pool:
                key = (rt.job_id, str(dest.get("id", "")))
                with self._handoff_prep_lock:
                    if key in self._handoff_dest_ready \
                            or key in self._handoff_preparing:
                        continue
                    self._handoff_preparing.add(key)
                try:
                    ok = self._prepare_dest(rt, dest)
                    # the job may have been shut down during the ship
                    # (MODULE can take minutes): marking it ready now
                    # would re-pin the dead job id shutdown_job just
                    # purged
                    with self._lock:
                        alive = rt.job_id in self.jobs
                    if ok and alive:
                        with self._handoff_prep_lock:
                            self._handoff_dest_ready.add(key)
                # tlint: disable=TL005(best-effort warm-up — the handoff path re-probes on demand)
                except Exception:
                    pass
                finally:
                    with self._handoff_prep_lock:
                        self._handoff_preparing.discard(key)

    def _handoff_pool_for(self, job_id: str) -> list[dict]:
        """The decode pool a job's completed prefills ship to: the
        job-scoped push wins; the worker-wide operator push stands in
        for jobs recruited without one."""
        return (
            self._handoff_pools.get(job_id)
            or self._handoff_pools.get("")
            or []
        )

    def _pick_handoff_dest(self, rt: "StageRuntime") -> dict | None:
        """Round-robin over the job's decode pool, skipping members that
        can't host this job right now (unreachable / refusing /
        stage-load failure). Readiness is cached per (job, dest) so the
        steady-state path pays one probe per handoff, not a MODULE round
        trip."""
        pool = self._handoff_pool_for(rt.job_id)
        n = len(pool)
        for j in range(n):
            dest = pool[(self._handoff_rr + j) % n]
            key = (rt.job_id, str(dest["id"]))
            if key in self._handoff_dest_ready:
                self._handoff_rr = (self._handoff_rr + j + 1) % n
                return dest
            with self._handoff_prep_lock:
                if key in self._handoff_preparing:
                    # the warm-up thread is mid-ship to this member:
                    # waiting would stall the run loop and a second
                    # MODULE ship would replace the destination runtime
                    # — try the next member (or resume locally)
                    continue
                self._handoff_preparing.add(key)
            try:
                ok = self._prepare_dest(rt, dest)
            finally:
                with self._handoff_prep_lock:
                    self._handoff_preparing.discard(key)
            if ok:
                with self._handoff_prep_lock:
                    self._handoff_dest_ready.add(key)
                self._handoff_rr = (self._handoff_rr + j + 1) % n
                return dest
        return None

    def _run_handoffs(self, rt: "StageRuntime") -> None:
        """Ship every slot the last chunk froze at its prefill→decode
        boundary. Runs on the worker's serial run loop right after the
        chunk, so every freeze-to-ship window is one work item — no
        frozen slot ever parks across items."""
        cont = rt.cont
        if cont is None:
            return
        manifest = cont.handoff_manifest()
        if not manifest:
            return
        for slot, req in manifest:
            meta = req.client_meta
            if meta is None or self.draining is not None \
                    or not self._handoff_pool_for(rt.job_id):
                # no transport context to redirect (in-process driver),
                # or this worker is itself mid-drain (the drain ladder
                # owns its slots): finish the prefill locally
                cont.abort_handoff(slot)
                continue
            dest = self._pick_handoff_dest(rt)
            if dest is None:
                # no decode worker usable: degrade to mixed serving for
                # this slot — one grant finishes the prompt and the
                # stream decodes here, never dropped, never slower
                self.log.warning(
                    "handoff: no usable decode-pool destination; "
                    "slot %d resumes locally", slot,
                )
                cont.abort_handoff(slot)
                continue
            committed = False
            try:
                if self.faults is not None:
                    # fault site "worker.handoff": error sends the slot
                    # down the re-prefill redirect rung; crash is the
                    # prefill-worker-dies-mid-handoff chaos case
                    self.faults.inject(
                        "worker.handoff", str(meta.get("rid", ""))
                    )
                mig_id = self._ship_migration(rt, cont, slot, dest)
                moved = cont.commit_handoff(slot)
                committed = True
                self._respond_migrated(
                    cont, meta, dest, mig_id, moved.tokens, handoff=True
                )
            except FaultCrash:
                raise  # the run loop takes the node down
            except Exception as e:
                # per-slot containment: ONE failed handoff must neither
                # re-commit a torn-down slot nor abandon the rest of the
                # manifest (the popped entries would freeze forever)
                if committed:
                    # the slot already committed — its pages are staged
                    # at the destination and the redirect send was
                    # already retried (_respond_migrated); landing here
                    # means the client's relay is genuinely gone (peer
                    # hung up), so there is no one left to redirect. The
                    # staged ticket expires via the migration TTL;
                    # nothing to roll back, but say so loudly.
                    self.log.warning(
                        "handoff redirect for slot %d failed post-commit "
                        "(%s); staged ticket left to TTL expiry", slot, e,
                    )
                    continue
                # drop the readiness cache so the NEXT handoff re-probes
                # this member, and kick the warm thread so that re-probe
                # (and a possible stage re-ship to a restarted worker)
                # happens OFF the run loop instead of inline between a
                # future chunk and its handoffs
                with self._handoff_prep_lock:
                    self._handoff_dest_ready.discard(
                        (rt.job_id, str(dest["id"]))
                    )
                threading.Thread(
                    target=self._warm_handoff_dests, args=(rt.job_id,),
                    name="handoff-rewarm", daemon=True,
                ).start()
                try:
                    self._dial_dest(dest)
                except Exception:
                    # the destination is UNREACHABLE, not merely refusing
                    # the transfer: redirecting the client at it would
                    # just bounce off a dead worker — resume locally (the
                    # slot's prefilled state is intact; one grant
                    # finishes the prompt)
                    self.log.warning(
                        "handoff of slot %d failed and destination %s is "
                        "unreachable (%s); resuming locally",
                        slot, str(dest.get("id", ""))[:8], e,
                    )
                    cont.abort_handoff(slot)
                    continue
                self.log.warning(
                    "handoff of slot %d to %s failed (%s); redirecting "
                    "for re-prefill at the destination",
                    slot, str(dest.get("id", ""))[:8], e,
                )
                # the destination hosts the job and is reachable — only
                # the transfer failed. Send this stream down the
                # re-prefill rung: redirect FIRST, commit after — if the
                # redirect send itself fails, the slot is still frozen
                # and the local-resume rung below stays reachable (a
                # commit-first ordering would tear the slot down and
                # strand the stream against its RPC timeout). Its
                # prefill-region pages promote into the trie at commit,
                # so even a bounce-back re-admission here walks them for
                # free.
                try:
                    self._respond_migrated(
                        cont, meta, dest, None, req.tokens, handoff=True,
                    )
                    cont.commit_handoff(slot, fell_back=True)
                except Exception as e2:
                    # even the fallback redirect failed: keep the stream
                    # serving HERE rather than stranding it frozen
                    self.log.warning(
                        "handoff fallback redirect for slot %d failed "
                        "(%s); resuming locally", slot, e2,
                    )
                    if slot in cont.frozen_slots():
                        cont.abort_handoff(slot)

    def _drain(self, p: dict) -> None:
        dest = dict(p.get("dest") or {})
        if self.faults is not None:
            # fault site "worker.drain": a worker that dies the moment it
            # is asked to shed its slots (crash) or refuses (error)
            self.faults.inject("worker.drain", str(dest.get("id", "")))
        if not dest.get("id") or not dest.get("addr"):
            # fleet fallback (docs/SERVING.md "Fleet serving"): a DRAIN
            # with no destination drains onto a sibling replica's entry
            # worker from the REPLICA_SET push — but _drain ships EVERY
            # job to the one destination, so the fallback applies only
            # when the candidate is UNAMBIGUOUS: all pushed sets agree
            # on one sibling (a worker co-hosting two fleets must not
            # drain model A's streams onto model B's sibling)
            candidates = {
                e["id"]: dict(e)
                for peers in self._replica_sets.values()
                for e in peers
                if e.get("id") and e.get("id") != self.node.node_id
                and e.get("addr")
            }
            if len(candidates) == 1:
                dest = next(iter(candidates.values()))
        if not dest.get("id") or not dest.get("addr"):
            self._respond(
                p["peer"], proto.DRAIN_RESP, p["rid"],
                {"ok": False, "error": "drain needs a destination {id, addr}"},
            )
            return
        if dest["id"] == self.node.node_id:
            # a self-targeted drain would make this worker permanently
            # redirect every request back to itself
            self._respond(
                p["peer"], proto.DRAIN_RESP, p["rid"],
                {"ok": False, "error": "refusing to drain a worker onto itself"},
            )
            return
        self.draining = dest
        try:
            # recruiting fence: advertise zero capacity so planners stop
            # placing new stages here while the worker sheds its slots
            self.bridge.request(
                "set_capacity", {"hbm_bytes": 0.0}, timeout=10.0
            )
        except Exception as e:
            self.log.warning("drain: capacity fence failed: %s", e)
        summary = {"ok": True, "jobs": 0, "migrated": 0, "fell_back": 0,
                   "aborted": 0}
        with self._lock:
            jobs = list(self.jobs.items())
        for _job_id, rt in jobs:
            if rt.cont is None:
                continue
            summary["jobs"] += 1
            self._drain_engine(rt, dest, summary)
        if summary["aborted"]:
            # a job the destination can't host keeps serving HERE:
            # redirecting its streams into a jobless worker would drop
            # them. Lower the worker fence and restore the recruiting
            # capacity — the drain failed, loudly, with nothing lost.
            self.draining = None
            try:
                self.bridge.request(
                    "set_capacity", self.capacity(), timeout=30.0
                )
            except Exception as e:
                self.log.warning("drain abort: capacity restore failed: %s", e)
            summary["ok"] = False
            summary["error"] = (
                "destination could not host every job; drain aborted for "
                f"{summary['aborted']} job(s), streams kept serving locally"
            )
        self.log.info(
            "drained to %s: %d migrated, %d fell back, %d aborted",
            str(dest.get("id", ""))[:8], summary["migrated"],
            summary["fell_back"], summary["aborted"],
        )
        self._respond(p["peer"], proto.DRAIN_RESP, p["rid"], summary)

    def _drain_engine(self, rt: "StageRuntime", dest: dict,
                      summary: dict) -> None:
        """Shed one job's slot engine. Runs on the worker's serial run
        loop, so every freeze happens at a chunk boundary by
        construction."""
        cont = rt.cont
        cont.begin_drain()
        if not self._prepare_dest(rt, dest):
            # the destination can't host this job (unreachable, refuses,
            # stage load failed): redirecting streams there would strand
            # them against a jobless worker. Abort THIS job's drain —
            # nothing was shed yet, so lowering the fence resumes serving
            # exactly where it stood.
            cont.end_drain()
            summary["aborted"] += 1
            return
        manifest = cont.live_manifest()
        queued = cont.shed_queued()
        for kind, slot, req in manifest:
            meta = req.client_meta
            if meta is None:
                # no transport context (in-process driver): nothing to
                # redirect — the slot finishes locally under the fence
                continue
            if kind == "decode":
                try:
                    if self.faults is not None:
                        self.faults.inject(
                            "migrate.export", str(meta.get("rid", ""))
                        )
                    cont.freeze_slot(slot)
                    mig_id = self._ship_migration(rt, cont, slot, dest)
                    moved = cont.commit_migration(slot)
                    self._respond_migrated(
                        cont, meta, dest, mig_id, moved.tokens
                    )
                    summary["migrated"] += 1
                    continue
                except FaultCrash:
                    raise  # the run loop takes the node down
                except Exception as e:
                    self.log.warning(
                        "migration of slot %d failed (%s); falling back "
                        "to re-prefill on the destination", slot, e,
                    )
            # fallback ladder: mid-prefill slot, or a failed
            # export/wire/import — redirect for re-prefill resume (the
            # destination hosts the job; only the page transfer failed)
            if slot in cont.frozen_slots():
                moved = cont.commit_migration(slot, fell_back=True)
            else:
                moved = cont.shed_slot(slot)
            self._respond_migrated(
                cont, meta, dest, None, (moved or req).tokens
            )
            summary["fell_back"] += 1
        for req in queued:
            if req.client_meta is not None:
                self._respond_migrated(
                    cont, req.client_meta, dest, None, req.tokens
                )
            else:
                # an in-process submitter can't be redirected: fail fast
                # rather than strand it in a popped-from-queue limbo
                cont.fail_queued(
                    req, RuntimeError("worker draining; resubmit elsewhere")
                )

    def _dial_dest(self, dest: dict) -> str:
        """Peer id of a live connection to the destination worker (the
        network process dedupes dials by address)."""
        return self.bridge.request(
            "connect",
            {"host": dest["addr"][0], "port": int(dest["addr"][1])},
            timeout=15.0,
        )

    def _mig_request(self, peer: str, body: dict, timeout: float = 60.0):
        return self.bridge.request(
            "tensor_request",
            {"peer": peer, "tag": proto.MIGRATE, "body": body,
             "timeout": timeout},
            timeout=timeout + 10.0,
        )

    def _prepare_dest(self, rt: "StageRuntime", dest: dict) -> bool:
        """Make sure the destination can adopt this job's slots: probe it,
        and ship the stage (same model spec → same seeded params → an
        engine whose streams are bit-identical to ours) when it doesn't
        host the job yet. False = page-shipping unavailable; every slot
        takes the re-prefill rung instead."""
        try:
            peer = self._dial_dest(dest)
            pr = self._mig_request(
                peer,
                {"op": "probe", "job_id": rt.job_id,
                 "chain": np.zeros(0, np.int32), "limit": 0},
            )
            if not pr.get("ok"):
                return False
            if not pr.get("loaded"):
                resp = self.bridge.request(
                    "tensor_request",
                    {"peer": peer, "tag": proto.MODULE,
                     "body": {
                         "job_id": rt.job_id,
                         "model": rt.model_spec,
                         "stage": dict(rt.stage, worker_id=dest["id"]),
                         "training": False,
                     },
                     "timeout": 120.0},
                    timeout=130.0,
                )
                if not resp.get("ok"):
                    return False
            return True
        except Exception as e:
            self.log.warning(
                "drain destination %s unreachable/unready: %s",
                str(dest.get("id", ""))[:8], e,
            )
            return False

    def _ship_migration(self, rt: "StageRuntime", cont, slot: int,
                        dest: dict) -> str:
        """Probe + export + transfer one frozen slot's pages. Returns the
        staged ticket id the client's resume request will adopt. Raises
        on any failure — the caller falls back to re-prefill."""
        import secrets

        peer = self._dial_dest(dest)
        chain, limit = cont.migration_chain(slot)
        n_skip = 0
        try:
            pr = self._mig_request(
                peer,
                {"op": "probe", "job_id": rt.job_id,
                 "chain": np.asarray(chain, np.int32), "limit": int(limit)},
            )
            n_skip = int(pr.get("resident_pages", 0) or 0)
        except Exception as e:
            self.log.debug("migration probe failed (%s); shipping all", e)
        blob = cont.export_slot(slot, n_skip=n_skip)
        mig_id = secrets.token_hex(8)
        act = (
            self.faults.inject("migrate.wire", mig_id)
            if self.faults is not None else None
        )
        if act == "drop":
            raise RuntimeError("migrate.wire: transfer dropped")
        if isinstance(act, tuple):  # ("delay", seconds)
            time.sleep(act[1])
        reply = None
        # dup really sends the staging frame twice — idempotency by
        # mig_id is the destination's contract, chaos-tested
        for _ in range(2 if act == "dup" else 1):
            reply = self._mig_request(
                peer,
                {"op": "put", "job_id": rt.job_id, "mig": mig_id,
                 "blob": blob},
            )
        if not (reply or {}).get("ok"):
            raise RuntimeError(
                f"destination refused migration: "
                f"{(reply or {}).get('error', 'not ok')}"
            )
        return mig_id

    def _respond_migrated(self, cont, meta: dict, dest: dict,
                          mig_id: str | None, tokens, *,
                          handoff: bool = False) -> None:
        """Tell the waiting client its stream moved: where to re-issue,
        which staged ticket to adopt (None = plain re-prefill resume), and
        the authoritative emitted-so-far list (fire-and-forget stream
        frames may have dropped — the client tops up exactly-once from
        this). ``cont`` may be None (the admission-fence redirect fires
        before any slot engine exists). ``handoff`` marks a steady-state
        prefill→decode redirect: the client follows it for THIS request
        only and keeps its plan pointed at this worker — the admission
        point — instead of rewriting the plan like a drain redirect."""
        tid = str(meta.get("trace") or "")
        body = {
            "migrated": {
                "worker": dest["id"],
                "addr": list(dest["addr"]),
                "mig": mig_id,
                "tokens_so_far": [int(t) for t in tokens],
                "handoff": bool(handoff),
                # the redirect carries the request's trace id (and, below,
                # the source worker's spans): the client re-issues at the
                # destination under the SAME id, so both halves stitch
                "trace_id": tid or None,
            },
        }
        if cont is not None:
            body["serving"] = cont.serving_snapshot()
            if tid:
                body["trace"] = {"id": tid, "spans": cont.tracer.collect(tid)}
        # the redirect IS the stream at this point — on the handoff path
        # the slot is already torn down and its pages staged at the
        # destination, so a transiently failed send here would strand the
        # client against its RPC timeout (not the recovery ladder, which
        # only catches lost-worker shapes). Absorb transient relay
        # hiccups with short retries — handoffs only: a drain's manifest
        # may hold many slots with hung-up clients, and serializing
        # blocking backoffs across it would stall the run loop for the
        # healthy streams (the drain path keeps its fail-fast shape).
        # The client matches by rid, so a duplicate delivery is dropped
        # as stale.
        attempts = 3 if handoff else 1
        for attempt in range(attempts):
            try:
                self._respond(
                    meta["peer"], proto.GENERATE_RESP, meta["rid"], body
                )
                break
            except Exception as e:
                if attempt == attempts - 1:
                    raise
                self.log.warning(
                    "handoff redirect send failed (attempt %d/%d): %s",
                    attempt + 1, attempts, e,
                )
                time.sleep(0.25 * (attempt + 1))
        if meta.get("stream"):
            try:
                # close the relay so a streaming client's drain loop
                # unblocks immediately instead of riding out its timeout
                self.bridge.request(
                    "send_token",
                    {"peer": meta["peer"], "stream": meta["stream"],
                     "tokens": [], "done": True},
                )
            except Exception as e:
                self.log.debug("migrate stream close failed: %s", e)

    def _migrate_in(self, p: dict) -> None:
        """Destination side of a migration: ``probe`` answers whether the
        job is loaded and how many leading pages of the chain are
        prefix-cache-resident (the exporter skips shipping those);
        ``put`` stages the blob's pages into this engine (idempotent by
        mig id). The staged ticket is adopted by the client's resume
        request (``adopt`` on GENERATE)."""
        op = p.get("op")
        rt = self.jobs.get(p.get("job_id", ""))
        if op in ("probe", "put") and self.draining is not None:
            # worker-level fence: a draining worker must not adopt inbound
            # streams — its engines are fenced, so a staged ticket here
            # could never be adopted (the resume gets redirected away) and
            # its pages would pin until process exit
            self._respond(
                p["peer"], proto.MIGRATE_RESP, p["rid"],
                {"ok": False, "error": "destination is draining"},
            )
            return
        if op == "probe":
            loaded = rt is not None and rt.engine is not None
            body: dict = {"ok": True, "loaded": loaded}
            if loaded:
                cont = self._ensure_cont(rt)
                if cont is None or cont.drain_state != "serving":
                    body = {"ok": False,
                            "error": "destination cannot adopt (no slot "
                                     "engine, or draining itself)"}
                else:
                    chain = [
                        int(t)
                        for t in np.asarray(p.get("chain", [])).reshape(-1)
                    ]
                    body["resident_pages"] = cont.resident_prefix_pages(
                        chain, int(p.get("limit", 0))
                    )
            self._respond(p["peer"], proto.MIGRATE_RESP, p["rid"], body)
            return
        if op == "put":
            if self.faults is not None:
                # fault site "migrate.import": error refuses the staging
                # (source falls back), crash kills the destination mid-
                # migration — the chaos suite's kill-the-receiver case
                self.faults.inject("migrate.import", str(p.get("mig", "")))
            if rt is None or rt.engine is None:
                self._respond(
                    p["peer"], proto.MIGRATE_RESP, p["rid"],
                    {"ok": False, "error": "job not loaded"},
                )
                return
            cont = self._ensure_cont(rt)
            if cont is None:
                self._respond(
                    p["peer"], proto.MIGRATE_RESP, p["rid"],
                    {"ok": False, "error": "continuous unsupported"},
                )
                return
            ok = cont.stage_migration(str(p.get("mig", "")), p["blob"])
            self._respond(
                p["peer"], proto.MIGRATE_RESP, p["rid"],
                {"ok": bool(ok)} if ok else
                {"ok": False,
                 "error": "staging refused (mode mismatch, evicted "
                          "prefix, bad digest, or allocator dry)"},
            )
            return
        if op == "pull":
            # fleet prefix pull (docs/SERVING.md "Tiered prefix cache"):
            # a sibling replica on a local cache miss asks for our
            # resident pages covering its prompt's leading chain. READ-
            # ONLY on this side (gather, never alloc/scatter), so it is
            # deliberately outside the draining fence above — a draining
            # worker's cache is exactly the one worth raiding before its
            # pages die with the drain.
            if self.faults is not None:
                # fault site "kvtier.fetch": error refuses the export
                # (the puller degrades to re-prefill), crash kills this
                # SOURCE mid-pull — the chaos suite's tiered-cache case
                self.faults.inject(
                    "kvtier.fetch", f"pull-src:{p.get('job_id', '')}"
                )
            cont = self._ensure_cont(rt) if (
                rt is not None and rt.engine is not None
            ) else None
            if cont is None:
                self._respond(
                    p["peer"], proto.MIGRATE_RESP, p["rid"],
                    {"ok": False, "error": "job not loaded"},
                )
                return
            chain = [
                int(t) for t in np.asarray(p.get("chain", [])).reshape(-1)
            ]
            blob = cont.export_prefix_pages(
                chain, int(p.get("limit", 0)),
                n_skip=int(p.get("n_skip", 0)),
            )
            # blob=None (chain fell out of both tiers since the digest
            # was published) is ok:True with no blob — losing the race
            # to eviction is a degrade rung, never an error
            self._respond(
                p["peer"], proto.MIGRATE_RESP, p["rid"],
                {"ok": True, "blob": blob},
            )
            return
        if op == "expire":
            # a recovered source validator expiring stranded tickets
            # deterministically at journal replay — without this, a
            # validator restart mid-drain left staged pages pinned until
            # the destination's TTL GC happened to fire
            n = 0
            if rt is not None and rt.cont is not None:
                cont = rt.cont
                want = str(p.get("mig", "") or "")
                for mig_id in cont.staged_migrations():
                    if want and mig_id != want:
                        continue
                    cont.drop_staged_migration(mig_id)
                    n += 1
                cont.check_page_conservation()
            self._respond(
                p["peer"], proto.MIGRATE_RESP, p["rid"],
                {"ok": True, "expired": n},
            )
            return
        raise ValueError(f"unknown migrate op {op!r}")

    def _beam_step(self, job_id: str, rid: str) -> None:
        """Advance an in-flight beam session one bounded chunk. Unfinished
        sessions requeue a light marker on the worker's OWN work queue —
        FIFO, so every generate that arrived meanwhile runs before the
        next chunk (bounded occupancy instead of head-of-line blocking)."""
        rt = self._runtime(job_id)
        entry = rt.beam_sessions.get(rid)
        if entry is None:
            return  # job shut down / duplicate marker
        st, p, k = entry
        try:
            # advance via the engine the session STARTED on (st.engine):
            # a load_stage between chunks may swap rt.engine, and scoring
            # this session's KV under different weights would corrupt it
            done = st.engine.beam_advance(st, max_steps=_BEAM_CHUNK_STEPS)
        except BaseException:
            rt.beam_sessions.pop(rid, None)
            raise  # the run-loop error path responds on this rid
        if not done:
            self.bridge.q.work.put(
                ("beam_continue",
                 {"job_id": job_id, "rid": rid, "peer": p["peer"]})
            )
            return
        rt.beam_sessions.pop(rid, None)
        result = st.engine.beam_finish(st)
        stream_id = p.get("stream")
        if stream_id:
            # beams emit nothing until the search completes; close the
            # relay so a streaming caller never stalls on the drain
            self.bridge.request(
                "send_token",
                {"peer": p["peer"], "stream": stream_id, "tokens": [],
                 "done": True},
            )
        self._respond(
            p["peer"], proto.GENERATE_RESP, rid,
            {"sequences": [list(map(int, s)) for s in result.sequences],
             "finished": list(map(bool, result.finished)),
             "num_beams_used": k},
        )

    # -- parameters -----------------------------------------------------
    @staticmethod
    def _exact_params(rt: StageRuntime):
        """rt.params with int8-serving QTensor leaves dequantized — the
        wire/disk formats carry plain arrays."""
        from tensorlink_tpu.models.quant import QTensor, dequantize

        def fix(node):
            if isinstance(node, dict):
                return {k: fix(v) for k, v in node.items()}
            if isinstance(node, QTensor):
                return dequantize(node, rt.cfg.dtype)
            return node

        return fix(rt.params)

    def _params_req(self, p: dict) -> None:
        """Ship this stage's parameters back (reference parameter download,
        ml/worker.py:1394-1413 writes a file; here it is one bulk frame).
        Mirrored on merged co-slice stages: every member runs the gathers
        (collectives on a spanning mesh), only the primary ships bytes."""
        import jax

        rt = self._runtime(p["job_id"])
        host_params = jax.tree.map(
            lambda a: self._to_host(rt, a), self._exact_params(rt)
        )
        if p.get("mirror"):
            self._respond(
                p["peer"], proto.PARAMETERS, p["rid"], {"ok": True, "mirror": True}
            )
            return
        self._respond(p["peer"], proto.PARAMETERS, p["rid"], {"params": host_params})

    def _train_mode(self, p: dict) -> None:
        import jax

        from tensorlink_tpu.models.quant import QTensor

        rt = self._runtime(p["job_id"])
        quantized = any(
            isinstance(l, QTensor)
            for l in jax.tree.leaves(
                rt.params, is_leaf=lambda x: isinstance(x, QTensor)
            )
        )
        if bool(p.get("training", True)) and quantized:
            raise ValueError(
                "int8-quantized serving job cannot switch to training — "
                "request the job with quant=None for fine-tuning"
            )
        rt.training = bool(p.get("training", True))
        self._respond(
            p["peer"], proto.TRAIN_MODE_ACK, p["rid"],
            {"job_id": rt.job_id, "training": rt.training},
        )
