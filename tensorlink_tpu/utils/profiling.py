"""Profiling and memory diagnostics.

The reference has NO tracing/profiling (SURVEY §5: "none" — only colored
debug prints and byte counters). On TPU this must be first-class:
``jax.profiler`` traces viewable in XProf/TensorBoard, plus HBM live/peak
accounting per device for the capacity math the planner depends on.
"""

from __future__ import annotations

import contextlib
import time
from pathlib import Path
from typing import Any, Iterator

import jax


@contextlib.contextmanager
def trace(log_dir: str | Path, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture an XProf-compatible trace of the enclosed block::

        with profiling.trace("logs/trace"):
            engine.generate_compiled(...)

    View with TensorBoard's profile plugin or xprof."""
    log_dir = str(log_dir)
    Path(log_dir).mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(log_dir, create_perfetto_trace=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region inside a trace (shows up on the trace timeline)."""
    with jax.profiler.TraceAnnotation(name):
        yield


def device_memory() -> list[dict[str, Any]]:
    """Per-device HBM stats (bytes_in_use / peak / limit where the backend
    reports them; CPU backends may report nothing)."""
    out = []
    for d in jax.local_devices():
        stats: dict[str, Any] = {}
        try:
            stats = d.memory_stats() or {}
        # tlint: disable=TL005(memory_stats is backend-optional; CPU backends report nothing)
        except Exception:
            pass
        out.append(
            {
                "device": str(d),
                "platform": d.platform,
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            }
        )
    return out


class StepTimer:
    """Wall-clock step timing with warmup skip — the number bench.py
    reports (compile time excluded the same way everywhere)."""

    def __init__(self, warmup: int = 1):
        self.warmup = warmup
        self.times: list[float] = []
        self._n = 0

    @contextlib.contextmanager
    def step(self) -> Iterator[None]:
        t0 = time.perf_counter()
        yield
        dt = time.perf_counter() - t0
        self._n += 1
        if self._n > self.warmup:
            self.times.append(dt)

    @property
    def mean(self) -> float:
        return sum(self.times) / len(self.times) if self.times else float("nan")

    @property
    def p50(self) -> float:
        if not self.times:
            return float("nan")
        s = sorted(self.times)
        return s[len(s) // 2]
