"""Shared utilities (profiling, diagnostics)."""
