"""TPU-native model zoo.

Where the reference wraps arbitrary HF PyTorch modules and ships them to
workers (ml/module.py, ml/injector.py), this framework owns its model
definitions: one functional decoder-only transformer core
(:mod:`.transformer`) whose per-family behavior is pure configuration
(:mod:`.base`), with stacked layer parameters scanned by ``lax.scan`` so XLA
compiles one block program regardless of depth. HF checkpoints are mapped
onto this scheme by :mod:`tensorlink_tpu.engine.loader`.
"""

from .base import KVCache, ModelConfig
from .registry import config_from_hf, config_presets
from .transformer import forward, init_params, partition_specs

__all__ = [
    "KVCache",
    "ModelConfig",
    "config_from_hf",
    "config_presets",
    "forward",
    "init_params",
    "partition_specs",
]
