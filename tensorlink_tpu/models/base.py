"""Model configuration and KV cache structures.

The reference treats a model as an opaque ``nn.Module`` tree to be split by
memory (ml/graphing.py:202); here a model is data: a :class:`ModelConfig`
plus a parameter pytree. The KV cache is an explicit, donated pytree —
the TPU-native replacement for HF ``DynamicCache`` objects the reference
serializes over the wire (ml/utils.py:569-660).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..core import serialization


@dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters for the unified decoder-only core.

    Families covered (reference supports any HF causal LM via module
    offloading; we cover the families its tests/docs/baseline actually use —
    gpt2, Llama, Qwen2/2.5, Qwen3, Mistral, Mixtral, SmolLM, Gemma, Phi-3,
    GPT-NeoX/Pythia — via config):

    - ``pos="learned"``, ``mlp="fused"``, ``norm="layernorm"`` → GPT-2.
    - ``pos="rope"``, ``mlp="gated"``, ``norm="rmsnorm"`` → Llama-family.
    - ``qk_norm=True`` → Qwen3.
    - ``n_experts>0`` → Mixtral-style sparse MoE.
    - ``embed_scale`` + ``norm_plus_one`` → Gemma.
    - ``parallel_residual`` + ``rope_pct<1`` + layernorm → GPT-NeoX/Pythia.
    - ``norm_position="post"`` + ``qk_norm_full`` → OLMo-2.
    """

    family: str = "llama"
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    head_dim: int = 128
    d_ff: int = 11008
    max_seq_len: int = 4096
    norm_eps: float = 1e-6
    act: str = "silu"  # "silu" | "gelu" (tanh approx) | "gelu_exact" (erf)
    pos: str = "rope"  # "rope" | "learned"
    rope_theta: float = 10000.0
    # rotary applied to the first rope_pct of each head's dims (GPT-NeoX /
    # Pythia rotary_pct; 1.0 = full-dim rotary)
    rope_pct: float = 1.0
    attn_bias: bool = False  # GPT-2 / Qwen2 have qkv biases
    attn_out_bias: bool = False  # GPT-2 / GPT-NeoX bias on the o projection
    mlp_bias: bool = False
    mlp: str = "gated"  # "gated" (gate*up) | "fused" (up->act->down)
    norm: str = "rmsnorm"  # "rmsnorm" | "layernorm"
    norm_plus_one: bool = False  # Gemma rmsnorm: x * rms * (1 + scale)
    qk_norm: bool = False  # Qwen3 per-head-dim RMSNorm on q and k
    # OLMo-2: RMSNorm over the FULL q/k projection dim (not per-head),
    # applied before the head reshape
    qk_norm_full: bool = False
    # "pre" (llama-style input norms) | "post" (OLMo-2: norm applied to the
    # sublayer OUTPUT before the residual add; no input norm)
    norm_position: str = "pre"
    embed_scale: bool = False  # Gemma: embeddings scaled by sqrt(d_model)
    parallel_residual: bool = False  # GPT-NeoX: x + attn(ln1 x) + mlp(ln2 x)
    tie_embeddings: bool = False
    attn_scale: float | None = None  # None → 1/sqrt(head_dim)
    # MoE (Mixtral): 0 experts = dense
    n_experts: int = 0
    n_experts_per_tok: int = 2
    # "dense" runs every token through every expert (exact, small scale);
    # "sparse" is the capacity-factor top-k dispatch (parallel/expert.py) —
    # the worker flips this on when its stage mesh carries an expert axis
    moe_dispatch: str = "dense"
    moe_capacity_factor: float = 2.0
    # sparse dispatch groups tokens so the one-hot scatter einsums scale
    # linearly with sequence length (GShard token grouping)
    moe_group_size: int = 1024
    # sliding-window attention (Mistral); None = full causal
    sliding_window: int | None = None
    dtype: Any = jnp.bfloat16
    # Logit soft-capping (Gemma-style); None = off
    logit_cap: float | None = None
    # Pallas flash-attention for the serving engine's fresh-cache prefill
    # (ops/attention.py): blockwise online softmax, no [T, T] score tensor
    # in HBM. Opt-in; decode and training keep the einsum path.
    flash_attention: bool = False
    # EQuARX-style quantized collectives (parallel/ring.py): sequence-
    # parallel ring attention rotates int8 K/V chunks + per-(position,
    # head) scales over ICI instead of full-precision blocks — half the
    # hop bytes at a bounded, test-pinned divergence. Opt-in
    # (MLConfig.collective_quant applies it at stage load).
    collective_quant: bool = False

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    @property
    def moe(self) -> bool:
        return self.n_experts > 0

    def with_(self, **kw) -> "ModelConfig":
        return replace(self, **kw)

    def to_json(self) -> dict:
        """JSON-safe dict (job specs carry the config over the wire — the
        reference ships whole serialized modules instead, torch_node.py:879)."""
        from dataclasses import asdict

        d = asdict(self)
        d["dtype"] = jnp.dtype(self.dtype).name
        return d

    @classmethod
    def from_json(cls, d: dict) -> "ModelConfig":
        d = dict(d)
        if isinstance(d.get("dtype"), str):
            d["dtype"] = jnp.dtype(d["dtype"]).type
        return cls(**d)

    def param_count(self) -> int:
        """Analytic parameter count (used by the sharding planner's memory
        estimator — TPU analogue of reference ml/utils.py:36-124)."""
        d, f, v, L = self.d_model, self.d_ff, self.vocab_size, self.n_layers
        attn = d * self.q_dim + 2 * d * self.kv_dim + self.q_dim * d
        if self.moe:
            mlp = self.n_experts * 3 * d * f + d * self.n_experts
        elif self.mlp == "gated":
            mlp = 3 * d * f
        else:
            mlp = 2 * d * f
        norms = 2 * d * (2 if self.norm == "layernorm" else 1)
        emb = v * d + (0 if self.tie_embeddings else v * d)
        pos = self.max_seq_len * d if self.pos == "learned" else 0
        return L * (attn + mlp + norms) + emb + pos + d


@jax.tree_util.register_dataclass
@dataclass
class KVCache:
    """Per-model decode cache: ``k``/``v`` are ``[L, B, S_max, n_kv, hd]``,
    ``length`` is the number of valid positions per batch row ``[B]``.

    Stored stacked over layers so the decode ``lax.scan`` indexes its layer
    slice, and donated into the decode step so XLA updates it in place.

    **int8 mode** (``quantized=True``): ``k``/``v`` hold int8 with
    per-(layer, row, position, head) scales in ``k_scale``/``v_scale``
    ``[L, B, S, n_kv, 1]`` — halves the per-token cache stream that grows
    with context (the parameter stream is fixed; at 32k context the KV
    read rivals it) and doubles the servable context per HBM byte.
    Attention dequantizes on read; writes quantize each step's keys
    (models/transformer.py::_block).
    """

    k: jax.Array
    v: jax.Array
    length: jax.Array  # int32 [B]
    k_scale: jax.Array | None = None  # f32, present in int8 mode
    v_scale: jax.Array | None = None

    @classmethod
    def init(
        cls,
        cfg: ModelConfig,
        batch: int,
        max_len: int | None = None,
        dtype=None,
        quantized: bool = False,
    ):
        S = max_len or cfg.max_seq_len
        shape = (cfg.n_layers, batch, S, cfg.n_kv_heads, cfg.head_dim)
        if quantized:
            sshape = shape[:-1] + (1,)
            return cls(
                k=jnp.zeros(shape, jnp.int8),
                v=jnp.zeros(shape, jnp.int8),
                length=jnp.zeros((batch,), jnp.int32),
                k_scale=jnp.zeros(sshape, jnp.float32),
                v_scale=jnp.zeros(sshape, jnp.float32),
            )
        dt = dtype or cfg.dtype
        return cls(
            k=jnp.zeros(shape, dt),
            v=jnp.zeros(shape, dt),
            length=jnp.zeros((batch,), jnp.int32),
        )

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


# Wire format support: KV caches cross the P2P boundary when a job migrates
# between workers (reference ships DynamicCache, ml/utils.py:587-603).
serialization.register_struct(
    "tensorlink.KVCache",
    KVCache,
    lambda c: {
        "k": c.k, "v": c.v, "length": c.length,
        **({"k_scale": c.k_scale, "v_scale": c.v_scale} if c.quantized else {}),
    },
    lambda t: KVCache(
        k=jnp.asarray(np.asarray(t["k"])),
        v=jnp.asarray(np.asarray(t["v"])),
        length=jnp.asarray(np.asarray(t["length"])),
        k_scale=(
            jnp.asarray(np.asarray(t["k_scale"])) if "k_scale" in t else None
        ),
        v_scale=(
            jnp.asarray(np.asarray(t["v_scale"])) if "v_scale" in t else None
        ),
    ),
)
