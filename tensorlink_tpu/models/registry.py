"""HF architecture registry: transformers config → :class:`ModelConfig`.

The reference accepts any HF causal LM and splits its module tree by memory
(ml/graphing.py); here each supported family declares how its HF config maps
onto the unified core and how its checkpoint tensor names map onto our
parameter tree (consumed by engine/loader.py). Families cover everything the
reference's tests, docs, and BASELINE configs exercise: gpt2 / SmolLM (llama)
/ Qwen2.5 / Qwen3 / Llama-3 / Mistral / Mixtral.
"""

from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp

from .base import ModelConfig

# tlint: disable=TL006(family registry — populated at import, read-only after)
_FAMILY_BUILDERS: dict[str, Callable[[dict], ModelConfig]] = {}


def register_family(model_type: str):
    def deco(fn):
        _FAMILY_BUILDERS[model_type] = fn
        return fn

    return deco


def config_from_hf(hf_config: Any, dtype=jnp.bfloat16) -> ModelConfig:
    """Build a ModelConfig from a ``transformers`` config object or dict."""
    d = hf_config if isinstance(hf_config, dict) else hf_config.to_dict()
    mt = d.get("model_type")
    if mt not in _FAMILY_BUILDERS:
        raise ValueError(
            f"unsupported model_type {mt!r}; supported: {sorted(_FAMILY_BUILDERS)}"
        )
    return _FAMILY_BUILDERS[mt](d).with_(dtype=dtype)


@register_family("gpt2")
def _gpt2(d: dict) -> ModelConfig:
    n_embd = d["n_embd"]
    return ModelConfig(
        family="gpt2",
        vocab_size=d["vocab_size"],
        d_model=n_embd,
        n_layers=d["n_layer"],
        n_heads=d["n_head"],
        n_kv_heads=d["n_head"],
        head_dim=n_embd // d["n_head"],
        d_ff=d.get("n_inner") or 4 * n_embd,
        max_seq_len=d["n_positions"],
        norm_eps=d.get("layer_norm_epsilon", 1e-5),
        act="gelu",
        pos="learned",
        attn_bias=True,
        mlp="fused",
        norm="layernorm",
        tie_embeddings=True,
    )


def _llama_like(d: dict, **overrides) -> ModelConfig:
    n_heads = d["num_attention_heads"]
    head_dim = d.get("head_dim") or d["hidden_size"] // n_heads
    kw: dict[str, Any] = dict(
        family="llama",
        vocab_size=d["vocab_size"],
        d_model=d["hidden_size"],
        n_layers=d["num_hidden_layers"],
        n_heads=n_heads,
        n_kv_heads=d.get("num_key_value_heads") or n_heads,
        head_dim=head_dim,
        d_ff=d["intermediate_size"],
        max_seq_len=d.get("max_position_embeddings", 4096),
        norm_eps=d.get("rms_norm_eps", 1e-6),
        act="silu",
        pos="rope",
        rope_theta=d.get("rope_theta", 10000.0),
        mlp="gated",
        norm="rmsnorm",
        tie_embeddings=d.get("tie_word_embeddings", False),
        attn_bias=d.get("attention_bias", False),
        mlp_bias=d.get("mlp_bias", False),
    )
    kw.update(overrides)
    return ModelConfig(**kw)


@register_family("llama")
def _llama(d: dict) -> ModelConfig:
    return _llama_like(d)


@register_family("mistral")
def _mistral(d: dict) -> ModelConfig:
    return _llama_like(
        d, family="mistral", sliding_window=d.get("sliding_window")
    )


@register_family("qwen2")
def _qwen2(d: dict) -> ModelConfig:
    # Qwen2/2.5: llama core + qkv biases
    return _llama_like(d, family="qwen2", attn_bias=True)


@register_family("qwen3")
def _qwen3(d: dict) -> ModelConfig:
    # Qwen3: llama core + per-head q/k RMSNorm, no biases
    return _llama_like(d, family="qwen3", qk_norm=True, attn_bias=False)


@register_family("mixtral")
def _mixtral(d: dict) -> ModelConfig:
    return _llama_like(
        d,
        family="mixtral",
        n_experts=d["num_local_experts"],
        n_experts_per_tok=d["num_experts_per_tok"],
        sliding_window=d.get("sliding_window"),
    )


@register_family("gemma")
def _gemma(d: dict) -> ModelConfig:
    # Gemma: llama layout + sqrt(d_model)-scaled embeddings, (1+w) rmsnorm,
    # tanh-approx gelu, always-tied embeddings, explicit head_dim
    return _llama_like(
        d,
        family="gemma",
        act="gelu",
        embed_scale=True,
        norm_plus_one=True,
        tie_embeddings=True,
    )


@register_family("phi3")
def _phi3(d: dict) -> ModelConfig:
    # Phi-3: llama compute with fused qkv_proj / gate_up_proj checkpoints
    if d.get("rope_scaling"):
        # longrope rescales rotary frequencies at every context length —
        # loading such a checkpoint with plain rope would generate fluent
        # garbage; refuse instead (128k-context Phi-3 variants)
        raise ValueError(
            "phi3 rope_scaling (longrope) is not supported; use a "
            "non-rope-scaled Phi-3 checkpoint"
        )
    return _llama_like(
        d, family="phi3", sliding_window=d.get("sliding_window")
    )


@register_family("olmo2")
def _olmo2(d: dict) -> ModelConfig:
    # OLMo-2: llama layout reordered — RMSNorm on sublayer OUTPUTS
    # (post_attention / post_feedforward), full-projection-dim q/k norms
    return _llama_like(
        d, family="olmo2", norm_position="post", qk_norm_full=True
    )


@register_family("gpt_neox")
def _gpt_neox(d: dict) -> ModelConfig:
    # GPT-NeoX / Pythia: layernorm with biases, parallel attn+mlp residual,
    # partial rotary (rotary_pct), fused-mlp with biases, exact gelu
    n_heads = d["num_attention_heads"]
    return ModelConfig(
        family="gpt_neox",
        vocab_size=d["vocab_size"],
        d_model=d["hidden_size"],
        n_layers=d["num_hidden_layers"],
        n_heads=n_heads,
        n_kv_heads=n_heads,
        head_dim=d["hidden_size"] // n_heads,
        d_ff=d["intermediate_size"],
        max_seq_len=d.get("max_position_embeddings", 2048),
        norm_eps=d.get("layer_norm_eps", 1e-5),
        act="gelu_exact" if d.get("hidden_act", "gelu") == "gelu" else "gelu",
        pos="rope",
        rope_theta=d.get("rotary_emb_base", 10000.0),
        rope_pct=d.get("rotary_pct", 0.25),
        attn_bias=d.get("attention_bias", True),
        attn_out_bias=d.get("attention_bias", True),
        mlp="fused",
        norm="layernorm",
        parallel_residual=d.get("use_parallel_residual", True),
        tie_embeddings=d.get("tie_word_embeddings", False),
    )


# ---------------------------------------------------------------------------
# Checkpoint tensor-name mapping (engine/loader.py)
# ---------------------------------------------------------------------------
# Our tree path -> HF tensor name template ({i} = layer). "~T" marks weights
# stored transposed in HF (torch Linear stores [out, in]; we use [in, out]).
# GPT-2's Conv1D already stores [in, out] (no ~T) and fuses qkv (split rule).


def hf_name_map(cfg: ModelConfig) -> dict[str, Any]:
    if cfg.family == "gpt2":
        return {
            "embed.tok": "wte.weight",
            "embed.pos": "wpe.weight",
            "layers.ln1.scale": "h.{i}.ln_1.weight",
            "layers.ln1.bias": "h.{i}.ln_1.bias",
            "layers.attn.wq": ("split3.0", "h.{i}.attn.c_attn.weight"),
            "layers.attn.wk": ("split3.1", "h.{i}.attn.c_attn.weight"),
            "layers.attn.wv": ("split3.2", "h.{i}.attn.c_attn.weight"),
            "layers.attn.bq": ("split3.0", "h.{i}.attn.c_attn.bias"),
            "layers.attn.bk": ("split3.1", "h.{i}.attn.c_attn.bias"),
            "layers.attn.bv": ("split3.2", "h.{i}.attn.c_attn.bias"),
            "layers.attn.wo": "h.{i}.attn.c_proj.weight",
            "layers.attn.bo": "h.{i}.attn.c_proj.bias",
            "layers.ln2.scale": "h.{i}.ln_2.weight",
            "layers.ln2.bias": "h.{i}.ln_2.bias",
            "layers.mlp.w_up": "h.{i}.mlp.c_fc.weight",
            "layers.mlp.b_up": "h.{i}.mlp.c_fc.bias",
            "layers.mlp.w_down": "h.{i}.mlp.c_proj.weight",
            "layers.mlp.b_down": "h.{i}.mlp.c_proj.bias",
            "final_norm.scale": "ln_f.weight",
            "final_norm.bias": "ln_f.bias",
        }

    if cfg.family == "phi3":
        # fused qkv_proj ([q+2kv, d]) and gate_up_proj ([2f, d]) checkpoints
        q, kv, f = cfg.q_dim, cfg.kv_dim, cfg.d_ff
        qkv = "layers.{i}.self_attn.qkv_proj.weight"
        gu = "layers.{i}.mlp.gate_up_proj.weight"
        m = {
            "embed.tok": "embed_tokens.weight",
            "layers.ln1.scale": "layers.{i}.input_layernorm.weight",
            "layers.attn.wq": (f"rowsT.0.{q}", qkv),
            "layers.attn.wk": (f"rowsT.{q}.{q + kv}", qkv),
            "layers.attn.wv": (f"rowsT.{q + kv}.{q + 2 * kv}", qkv),
            "layers.attn.wo": "~T layers.{i}.self_attn.o_proj.weight",
            "layers.ln2.scale": "layers.{i}.post_attention_layernorm.weight",
            "layers.mlp.w_gate": (f"rowsT.0.{f}", gu),
            "layers.mlp.w_up": (f"rowsT.{f}.{2 * f}", gu),
            "layers.mlp.w_down": "~T layers.{i}.mlp.down_proj.weight",
            "final_norm.scale": "norm.weight",
        }
        if not cfg.tie_embeddings:
            m["lm_head"] = "~T ^lm_head.weight"
        return m

    if cfg.family == "gpt_neox":
        # fused query_key_value with per-head-interleaved q/k/v rows
        qkv_w = "layers.{i}.attention.query_key_value.weight"
        qkv_b = "layers.{i}.attention.query_key_value.bias"
        m = {
            "embed.tok": "embed_in.weight",
            "layers.ln1.scale": "layers.{i}.input_layernorm.weight",
            "layers.ln1.bias": "layers.{i}.input_layernorm.bias",
            "layers.attn.wq": ("neox_qkv.0", qkv_w),
            "layers.attn.wk": ("neox_qkv.1", qkv_w),
            "layers.attn.wv": ("neox_qkv.2", qkv_w),
            "layers.attn.bq": ("neox_qkvb.0", qkv_b),
            "layers.attn.bk": ("neox_qkvb.1", qkv_b),
            "layers.attn.bv": ("neox_qkvb.2", qkv_b),
            "layers.attn.wo": "~T layers.{i}.attention.dense.weight",
            "layers.attn.bo": "layers.{i}.attention.dense.bias",
            "layers.ln2.scale": "layers.{i}.post_attention_layernorm.weight",
            "layers.ln2.bias": "layers.{i}.post_attention_layernorm.bias",
            "layers.mlp.w_up": "~T layers.{i}.mlp.dense_h_to_4h.weight",
            "layers.mlp.b_up": "layers.{i}.mlp.dense_h_to_4h.bias",
            "layers.mlp.w_down": "~T layers.{i}.mlp.dense_4h_to_h.weight",
            "layers.mlp.b_down": "layers.{i}.mlp.dense_4h_to_h.bias",
            "final_norm.scale": "final_layer_norm.weight",
            "final_norm.bias": "final_layer_norm.bias",
        }
        if not cfg.tie_embeddings:
            m["lm_head"] = "~T ^embed_out.weight"
        return m

    m = {
        "embed.tok": "embed_tokens.weight",
        "layers.ln1.scale": "layers.{i}.input_layernorm.weight",
        "layers.attn.wq": "~T layers.{i}.self_attn.q_proj.weight",
        "layers.attn.wk": "~T layers.{i}.self_attn.k_proj.weight",
        "layers.attn.wv": "~T layers.{i}.self_attn.v_proj.weight",
        "layers.attn.wo": "~T layers.{i}.self_attn.o_proj.weight",
        "layers.ln2.scale": "layers.{i}.post_attention_layernorm.weight",
        "final_norm.scale": "norm.weight",
    }
    if cfg.attn_bias:
        m |= {
            "layers.attn.bq": "layers.{i}.self_attn.q_proj.bias",
            "layers.attn.bk": "layers.{i}.self_attn.k_proj.bias",
            "layers.attn.bv": "layers.{i}.self_attn.v_proj.bias",
        }
    if cfg.qk_norm or cfg.qk_norm_full:
        m |= {
            "layers.attn.q_norm": "layers.{i}.self_attn.q_norm.weight",
            "layers.attn.k_norm": "layers.{i}.self_attn.k_norm.weight",
        }
    if cfg.family == "olmo2":
        # post-norm reordering: our ln1 holds post_attention_layernorm, ln2
        # holds post_feedforward_layernorm (no input norms exist)
        m["layers.ln1.scale"] = "layers.{i}.post_attention_layernorm.weight"
        m["layers.ln2.scale"] = "layers.{i}.post_feedforward_layernorm.weight"
    if cfg.moe:
        m |= {
            "layers.mlp.router": "~T layers.{i}.block_sparse_moe.gate.weight",
            "layers.mlp.w_gate": (
                "stackE",
                "~T layers.{i}.block_sparse_moe.experts.{e}.w1.weight",
            ),
            "layers.mlp.w_down": (
                "stackE",
                "~T layers.{i}.block_sparse_moe.experts.{e}.w2.weight",
            ),
            "layers.mlp.w_up": (
                "stackE",
                "~T layers.{i}.block_sparse_moe.experts.{e}.w3.weight",
            ),
        }
    else:
        m |= {
            "layers.mlp.w_gate": "~T layers.{i}.mlp.gate_proj.weight",
            "layers.mlp.w_up": "~T layers.{i}.mlp.up_proj.weight",
            "layers.mlp.w_down": "~T layers.{i}.mlp.down_proj.weight",
        }
        if cfg.mlp_bias:
            m |= {
                "layers.mlp.b_gate": "layers.{i}.mlp.gate_proj.bias",
                "layers.mlp.b_up": "layers.{i}.mlp.up_proj.bias",
                "layers.mlp.b_down": "layers.{i}.mlp.down_proj.bias",
            }
    if not cfg.tie_embeddings:
        m["lm_head"] = "~T ^lm_head.weight"  # ^ = top-level, outside prefix
    return m


# Prefix inside the checkpoint for the backbone tensors, e.g. HF llama stores
# "model.layers.0...." and "lm_head.weight" at top level.
def hf_prefix(cfg: ModelConfig) -> str:
    if cfg.family == "gpt2":
        return "transformer."
    if cfg.family == "gpt_neox":
        return "gpt_neox."
    return "model."


def config_presets() -> dict[str, ModelConfig]:
    """Named presets for tests/benchmarks (no network access needed)."""
    return {
        "gpt2-small": ModelConfig(
            family="gpt2",
            vocab_size=50257,
            d_model=768,
            n_layers=12,
            n_heads=12,
            n_kv_heads=12,
            head_dim=64,
            d_ff=3072,
            max_seq_len=1024,
            norm_eps=1e-5,
            act="gelu",
            pos="learned",
            attn_bias=True,
            mlp="fused",
            norm="layernorm",
            tie_embeddings=True,
        ),
        "qwen3-8b": ModelConfig(
            family="qwen3",
            vocab_size=151936,
            d_model=4096,
            n_layers=36,
            n_heads=32,
            n_kv_heads=8,
            head_dim=128,
            d_ff=12288,
            max_seq_len=40960,
            norm_eps=1e-6,
            rope_theta=1e6,
            qk_norm=True,
            tie_embeddings=False,
        ),
        "qwen3-4b": ModelConfig(
            family="qwen3",
            vocab_size=151936,
            d_model=2560,
            n_layers=36,
            n_heads=32,
            n_kv_heads=8,
            head_dim=128,
            d_ff=9728,
            max_seq_len=40960,
            norm_eps=1e-6,
            rope_theta=1e6,
            qk_norm=True,
            tie_embeddings=True,
        ),
        "qwen3-0p6b": ModelConfig(
            family="qwen3",
            vocab_size=151936,
            d_model=1024,
            n_layers=28,
            n_heads=16,
            n_kv_heads=8,
            head_dim=128,
            d_ff=3072,
            max_seq_len=40960,
            norm_eps=1e-6,
            rope_theta=1e6,
            qk_norm=True,
            tie_embeddings=True,
        ),
        "qwen3-1p7b": ModelConfig(
            family="qwen3",
            vocab_size=151936,
            d_model=2048,
            n_layers=28,
            n_heads=16,
            n_kv_heads=8,
            head_dim=128,
            d_ff=6144,
            max_seq_len=40960,
            norm_eps=1e-6,
            rope_theta=1e6,
            qk_norm=True,
            tie_embeddings=True,
        ),
        "qwen2p5-7b": ModelConfig(
            family="qwen2",
            vocab_size=152064,
            d_model=3584,
            n_layers=28,
            n_heads=28,
            n_kv_heads=4,
            head_dim=128,
            d_ff=18944,
            max_seq_len=32768,
            norm_eps=1e-6,
            rope_theta=1e6,
            attn_bias=True,
        ),
        "llama3-70b": ModelConfig(
            family="llama",
            vocab_size=128256,
            d_model=8192,
            n_layers=80,
            n_heads=64,
            n_kv_heads=8,
            head_dim=128,
            d_ff=28672,
            max_seq_len=8192,
            norm_eps=1e-5,
            rope_theta=5e5,
        ),
        "gemma-7b": ModelConfig(
            family="gemma",
            vocab_size=256000,
            d_model=3072,
            n_layers=28,
            n_heads=16,
            n_kv_heads=16,
            head_dim=256,
            d_ff=24576,
            max_seq_len=8192,
            act="gelu",
            embed_scale=True,
            norm_plus_one=True,
            tie_embeddings=True,
        ),
        "phi3-mini": ModelConfig(
            family="phi3",
            vocab_size=32064,
            d_model=3072,
            n_layers=32,
            n_heads=32,
            n_kv_heads=32,
            head_dim=96,
            d_ff=8192,
            max_seq_len=4096,
            norm_eps=1e-5,
        ),
        "pythia-1b": ModelConfig(
            family="gpt_neox",
            vocab_size=50304,
            d_model=2048,
            n_layers=16,
            n_heads=8,
            n_kv_heads=8,
            head_dim=256,
            d_ff=8192,
            max_seq_len=2048,
            norm_eps=1e-5,
            act="gelu_exact",
            rope_pct=0.25,
            attn_bias=True,
            attn_out_bias=True,
            mlp="fused",
            norm="layernorm",
            parallel_residual=True,
        ),
        "olmo2-7b": ModelConfig(
            family="olmo2",
            vocab_size=100352,
            d_model=4096,
            n_layers=32,
            n_heads=32,
            n_kv_heads=32,
            head_dim=128,
            d_ff=11008,
            max_seq_len=4096,
            norm_eps=1e-6,
            rope_theta=5e5,
            norm_position="post",
            qk_norm_full=True,
        ),
        "mixtral-8x7b": ModelConfig(
            family="mixtral",
            vocab_size=32000,
            d_model=4096,
            n_layers=32,
            n_heads=32,
            n_kv_heads=8,
            head_dim=128,
            d_ff=14336,
            max_seq_len=32768,
            norm_eps=1e-5,
            rope_theta=1e6,
            n_experts=8,
            n_experts_per_tok=2,
        ),
    }
