"""Weight-only int8 quantization for serving.

B=1 decode is HBM-bandwidth bound: every generated token streams all
parameter bytes (BASELINE.md roofline). Storing matmul weights as int8 with
per-output-channel scales halves those bytes; XLA fuses the int8→bf16
upcast and the scale multiply into the matmul read, so the arithmetic stays
on the MXU and the bandwidth roughly doubles. This is a serving-side
transform — training and the checkpoint formats never see it (the reference
has no quantization at all; this is a capability the TPU rebuild adds).

``QTensor`` is a registered pytree, so a quantized parameter tree flows
through ``lax.scan`` (stacked-layer slicing), jit, and donation untouched;
the matmul entry points in models/transformer.py route through
:func:`matmul` which dequantizes on the fly.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass
class QTensor:
    """int8 weight + broadcastable scale; ``q * scale ≈ original``."""

    q: jax.Array  # int8, original shape
    scale: jax.Array  # f32, shape broadcastable to q (per out-channel)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim


def quantize_tensor(w: jax.Array) -> QTensor:
    """Symmetric int8 reducing only the contraction axis (second-to-last):
    a 2D ``[in, out]`` weight gets per-out-channel scales ``[1, out]``; a
    layer-stacked ``[L, in, out]`` weight keeps per-(layer, out-channel)
    scales ``[L, 1, out]`` — layer magnitudes differ too much to share."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=w.ndim - 2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale)


def dequantize(t: QTensor, dtype=jnp.bfloat16) -> jax.Array:
    return (t.q.astype(jnp.float32) * t.scale).astype(dtype)


def matmul(x: jax.Array, w) -> jax.Array:
    """``x @ w`` where ``w`` may be a QTensor (dequantized on the fly —
    XLA fuses the upcast+scale into the weight read) or a plain array."""
    if isinstance(w, QTensor):
        y = x @ w.q.astype(x.dtype)
        # scale is [..., 1, out] (kept per out-channel); collapse the
        # contracted axis so it broadcasts over x's leading dims
        return y * jnp.squeeze(w.scale, axis=-2).astype(x.dtype)
    return x @ w


# tlint: hot-path
def quantize_kv(t: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 over ``head_dim`` for KV rows headed into the paged
    cache (engine/paged.py) or over an ICI hop (parallel/ring.py):
    ``[..., hd] -> (int8 [..., hd], f32 scale [...])`` — one scale per
    (position, head), the dense int8 cache's granularity
    (models/transformer.py::_quant_kv). Per-position scales are what make
    the paged cache's bitwise contract survive quantization: a position's
    (int8 bytes, scale) pair depends only on its own KV row, so chunk
    framing, COW copies, trie promotion and re-prefill all reproduce it
    byte-exactly."""
    tf = t.astype(jnp.float32)
    amax = jnp.max(jnp.abs(tf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(tf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


# tlint: hot-path
def dequantize_kv(q: jax.Array, scale: jax.Array, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv`; the multiply fuses into the read."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


# -- packed int4 KV (engine/paged.py kv_quant="int4") -----------------------
# Layout: SPLIT-HALF nibble packing over head_dim — byte ``j`` of a packed
# row holds element ``j`` in its low nibble and element ``j + hd/2`` in its
# high nibble, so unpacking is one concatenate on the last axis (TPU-friendly;
# a stride-2 interleave would fight the lane layout). Values are symmetric
# int4 in [-7, 7] with the SAME per-(position, head) scale granularity as
# int8 — which is what carries the paged cache's bitwise contract over: a
# position's (packed bytes, scale) pair still depends only on its own KV row.


# tlint: hot-path
def pack_int4(q: jax.Array) -> jax.Array:
    """Pack int values in [-7, 7] ``[..., hd]`` (hd even) into nibbles
    ``[..., hd // 2]`` int8 — split-half layout (see above)."""
    h = q.shape[-1] // 2
    b = q.astype(jnp.int32)
    return ((b[..., :h] & 0xF) | ((b[..., h:] & 0xF) << 4)).astype(jnp.int8)


# tlint: hot-path
def unpack_int4(packed: jax.Array) -> jax.Array:
    """Inverse of :func:`pack_int4`: nibbles ``[..., hd // 2]`` int8 back
    to sign-extended int values ``[..., hd]`` int32 in [-8, 7]. Pure
    bit-ops (and/shift/xor/sub) so the same expression runs inside the
    Pallas kernels' VMEM dequant and in the pure-jnp references."""
    b = packed.astype(jnp.int32) & 0xFF
    lo = ((b & 0xF) ^ 8) - 8
    hi = (((b >> 4) & 0xF) ^ 8) - 8
    return jnp.concatenate([lo, hi], axis=-1)


# tlint: hot-path
def quantize_kv4(t: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int4 over ``head_dim`` for KV rows headed into a packed
    page pool: ``[..., hd] -> (int8 [..., hd // 2], f32 scale [...])`` —
    two values per byte at :func:`quantize_kv`'s per-(position, head)
    scale granularity, so every bitwise-cache argument that held for int8
    (chunk-framing invariance, COW, promotion, re-prefill) holds for int4
    by the same construction. 15 levels instead of 255: the divergence
    bound is looser (tests/test_ops.py pins it) but still independent of
    context length — attention outputs are convex combinations of V rows."""
    tf = t.astype(jnp.float32)
    amax = jnp.max(jnp.abs(tf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(tf / scale[..., None]), -7, 7)
    return pack_int4(q), scale


# tlint: hot-path
def dequantize_kv4(packed: jax.Array, scale: jax.Array, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv4`; unpack + scale fuse into the read."""
    return (
        unpack_int4(packed).astype(jnp.float32) * scale[..., None]
    ).astype(dtype)


# Parameter-tree paths quantized for serving: the large matmul weights.
# Norm scales, biases, and qk-norm vectors stay exact (tiny, and precision
# there is cheap insurance).
_QUANT_LEAF_NAMES = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "router"}
)


def quantize_params(params: dict, *, min_size: int = 1 << 16) -> dict:
    """Quantize the matmul weights of a parameter tree for serving.

    Embeddings (gather-read, also the tied head — handled in the logits
    matmul) and sub-``min_size`` leaves stay full precision. Layer-stacked
    weights ``[L, in, out]`` keep per-(layer, out-channel) scales.
    """

    def walk(node, name=""):
        if isinstance(node, dict):
            return {k: walk(v, k) for k, v in node.items()}
        if isinstance(node, QTensor):  # idempotent on already-quantized trees
            return node
        if (
            name in _QUANT_LEAF_NAMES
            and getattr(node, "ndim", 0) in (2, 3)  # MoE 4D einsum weights
            and node.size >= min_size  # stay exact (einsum path, small win)
        ):
            return quantize_tensor(node)
        return node

    out = dict(walk(params))
    head = params.get("lm_head")
    if (
        head is not None
        and not isinstance(head, QTensor)
        and getattr(head, "ndim", 0) == 2
        and head.size >= min_size
    ):
        out["lm_head"] = quantize_tensor(head)
    return out


def quantized_bytes(params: dict) -> int:
    """Actual parameter bytes of a (possibly quantized) tree — the
    numerator the decode roofline should use."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.nbytes if hasattr(leaf, "nbytes") else 0
    return total


__all__ = [
    "QTensor", "dequantize", "dequantize_kv", "dequantize_kv4", "matmul",
    "pack_int4", "quantize_kv", "quantize_kv4", "quantize_params",
    "quantize_tensor", "quantized_bytes", "unpack_int4",
]
