"""Weight-only int8 quantization for serving.

B=1 decode is HBM-bandwidth bound: every generated token streams all
parameter bytes (BASELINE.md roofline). Storing matmul weights as int8 with
per-output-channel scales halves those bytes; XLA fuses the int8→bf16
upcast and the scale multiply into the matmul read, so the arithmetic stays
on the MXU and the bandwidth roughly doubles. This is a serving-side
transform — training and the checkpoint formats never see it (the reference
has no quantization at all; this is a capability the TPU rebuild adds).

``QTensor`` is a registered pytree, so a quantized parameter tree flows
through ``lax.scan`` (stacked-layer slicing), jit, and donation untouched;
the matmul entry points in models/transformer.py route through
:func:`matmul` which dequantizes on the fly.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass
class QTensor:
    """int8 weight + broadcastable scale; ``q * scale ≈ original``."""

    q: jax.Array  # int8, original shape
    scale: jax.Array  # f32, shape broadcastable to q (per out-channel)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim


def quantize_tensor(w: jax.Array) -> QTensor:
    """Symmetric int8 reducing only the contraction axis (second-to-last):
    a 2D ``[in, out]`` weight gets per-out-channel scales ``[1, out]``; a
    layer-stacked ``[L, in, out]`` weight keeps per-(layer, out-channel)
    scales ``[L, 1, out]`` — layer magnitudes differ too much to share."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=w.ndim - 2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale)


def dequantize(t: QTensor, dtype=jnp.bfloat16) -> jax.Array:
    return (t.q.astype(jnp.float32) * t.scale).astype(dtype)


def matmul(x: jax.Array, w) -> jax.Array:
    """``x @ w`` where ``w`` may be a QTensor (dequantized on the fly —
    XLA fuses the upcast+scale into the weight read) or a plain array."""
    if isinstance(w, QTensor):
        y = x @ w.q.astype(x.dtype)
        # scale is [..., 1, out] (kept per out-channel); collapse the
        # contracted axis so it broadcasts over x's leading dims
        return y * jnp.squeeze(w.scale, axis=-2).astype(x.dtype)
    return x @ w


# tlint: hot-path
def quantize_kv(t: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 over ``head_dim`` for KV rows headed into the paged
    cache (engine/paged.py) or over an ICI hop (parallel/ring.py):
    ``[..., hd] -> (int8 [..., hd], f32 scale [...])`` — one scale per
    (position, head), the dense int8 cache's granularity
    (models/transformer.py::_quant_kv). Per-position scales are what make
    the paged cache's bitwise contract survive quantization: a position's
    (int8 bytes, scale) pair depends only on its own KV row, so chunk
    framing, COW copies, trie promotion and re-prefill all reproduce it
    byte-exactly."""
    tf = t.astype(jnp.float32)
    amax = jnp.max(jnp.abs(tf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(tf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


# tlint: hot-path
def dequantize_kv(q: jax.Array, scale: jax.Array, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv`; the multiply fuses into the read."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


# Parameter-tree paths quantized for serving: the large matmul weights.
# Norm scales, biases, and qk-norm vectors stay exact (tiny, and precision
# there is cheap insurance).
_QUANT_LEAF_NAMES = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "router"}
)


def quantize_params(params: dict, *, min_size: int = 1 << 16) -> dict:
    """Quantize the matmul weights of a parameter tree for serving.

    Embeddings (gather-read, also the tied head — handled in the logits
    matmul) and sub-``min_size`` leaves stay full precision. Layer-stacked
    weights ``[L, in, out]`` keep per-(layer, out-channel) scales.
    """

    def walk(node, name=""):
        if isinstance(node, dict):
            return {k: walk(v, k) for k, v in node.items()}
        if isinstance(node, QTensor):  # idempotent on already-quantized trees
            return node
        if (
            name in _QUANT_LEAF_NAMES
            and getattr(node, "ndim", 0) in (2, 3)  # MoE 4D einsum weights
            and node.size >= min_size  # stay exact (einsum path, small win)
        ):
            return quantize_tensor(node)
        return node

    out = dict(walk(params))
    head = params.get("lm_head")
    if (
        head is not None
        and not isinstance(head, QTensor)
        and getattr(head, "ndim", 0) == 2
        and head.size >= min_size
    ):
        out["lm_head"] = quantize_tensor(head)
    return out


def quantized_bytes(params: dict) -> int:
    """Actual parameter bytes of a (possibly quantized) tree — the
    numerator the decode roofline should use."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += leaf.nbytes if hasattr(leaf, "nbytes") else 0
    return total


__all__ = [
    "QTensor", "dequantize", "dequantize_kv", "matmul", "quantize_kv",
    "quantize_params", "quantize_tensor", "quantized_bytes",
]
