"""Unified decoder-only transformer core (functional, scan-over-layers).

TPU-first design notes (vs. the reference's eager per-``nn.Module`` execution,
ml/worker.py:297-357):

- Parameters are stacked over layers (leading ``L`` axis) and the block is run
  under ``lax.scan`` — XLA compiles ONE block program regardless of depth, and
  the KV cache rides the scan as per-layer xs/ys so decode updates it in place
  (donated).
- Attention is grouped-query by construction: queries are reshaped to
  ``[B, T, n_kv, group, hd]`` and contracted against un-repeated KV, so GQA
  never materializes repeated KV heads in HBM.
- Softmax/norm statistics run in float32 while weights/activations stay in
  bfloat16 (MXU-native).
- All shapes are static; masks are position-index arithmetic, not Python
  control flow, so one compiled program serves any padding.
"""

from __future__ import annotations

import os as _os
from functools import partial
import jax
import jax.numpy as jnp
from jax import lax

from .base import KVCache, ModelConfig
from .quant import matmul as _mm  # dequant-on-the-fly for int8 serving

P = jax.sharding.PartitionSpec


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def init_params(cfg: ModelConfig, key: jax.Array, dtype=None) -> dict:
    """Random-init parameter pytree (shapes double as the loader's schema)."""
    dt = dtype or cfg.dtype
    d, f, hd = cfg.d_model, cfg.d_ff, cfg.head_dim
    L, V = cfg.n_layers, cfg.vocab_size
    keys = iter(jax.random.split(key, 32))

    def dense(k, *shape, scale=None):
        s = scale if scale is not None else shape[-2] ** -0.5
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(dt)

    def norm_p(with_bias: bool, *shape):
        p = {"scale": jnp.ones(shape, dt)}
        if with_bias:
            p["bias"] = jnp.zeros(shape, dt)
        return p

    ln_bias = cfg.norm == "layernorm"
    attn = {
        "wq": dense(next(keys), L, d, cfg.q_dim),
        "wk": dense(next(keys), L, d, cfg.kv_dim),
        "wv": dense(next(keys), L, d, cfg.kv_dim),
        "wo": dense(next(keys), L, cfg.q_dim, d),
    }
    if cfg.attn_bias:
        attn |= {
            "bq": jnp.zeros((L, cfg.q_dim), dt),
            "bk": jnp.zeros((L, cfg.kv_dim), dt),
            "bv": jnp.zeros((L, cfg.kv_dim), dt),
        }
    if cfg.attn_out_bias or cfg.family == "gpt2":
        attn["bo"] = jnp.zeros((L, d), dt)
    if cfg.qk_norm:
        attn |= {"q_norm": jnp.ones((L, hd), dt), "k_norm": jnp.ones((L, hd), dt)}
    if cfg.qk_norm_full:  # OLMo-2: norm over the whole projection dim
        attn |= {
            "q_norm": jnp.ones((L, cfg.q_dim), dt),
            "k_norm": jnp.ones((L, cfg.kv_dim), dt),
        }

    if cfg.moe:
        E = cfg.n_experts
        mlp = {
            "router": dense(next(keys), L, d, E),
            "w_gate": dense(next(keys), L, E, d, f),
            "w_up": dense(next(keys), L, E, d, f),
            "w_down": dense(next(keys), L, E, f, d, scale=f**-0.5),
        }
    elif cfg.mlp == "gated":
        mlp = {
            "w_gate": dense(next(keys), L, d, f),
            "w_up": dense(next(keys), L, d, f),
            "w_down": dense(next(keys), L, f, d, scale=f**-0.5),
        }
        if cfg.mlp_bias:
            mlp |= {
                "b_gate": jnp.zeros((L, f), dt),
                "b_up": jnp.zeros((L, f), dt),
                "b_down": jnp.zeros((L, d), dt),
            }
    else:  # fused (GPT-2): up -> act -> down, with biases
        mlp = {
            "w_up": dense(next(keys), L, d, f),
            "b_up": jnp.zeros((L, f), dt),
            "w_down": dense(next(keys), L, f, d, scale=f**-0.5),
            "b_down": jnp.zeros((L, d), dt),
        }

    params = {
        "embed": {"tok": dense(next(keys), V, d, scale=0.02)},
        "layers": {
            "ln1": norm_p(ln_bias, L, d),
            "attn": attn,
            "ln2": norm_p(ln_bias, L, d),
            "mlp": mlp,
        },
        "final_norm": norm_p(ln_bias, d),
    }
    if cfg.pos == "learned":
        params["embed"]["pos"] = dense(next(keys), cfg.max_seq_len, d, scale=0.02)
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(next(keys), d, V)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def _norm(x: jax.Array, p: dict, cfg: ModelConfig) -> jax.Array:
    xf = x.astype(jnp.float32)
    if cfg.norm == "layernorm":
        mu = xf.mean(-1, keepdims=True)
        var = ((xf - mu) ** 2).mean(-1, keepdims=True)
        out = (xf - mu) * lax.rsqrt(var + cfg.norm_eps)
        out = out * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    else:
        scale = p["scale"].astype(jnp.float32)
        if cfg.norm_plus_one:  # Gemma stores the rmsnorm weight as an offset
            scale = scale + 1.0
        var = (xf**2).mean(-1, keepdims=True)
        out = xf * lax.rsqrt(var + cfg.norm_eps) * scale
    return out.astype(x.dtype)


def _rms_head_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    """Qwen3 per-head RMSNorm over head_dim."""
    xf = x.astype(jnp.float32)
    out = xf * lax.rsqrt((xf**2).mean(-1, keepdims=True) + eps)
    return (out * scale.astype(jnp.float32)).astype(x.dtype)


def rope_tables(positions: jax.Array, head_dim: int, theta: float):
    """cos/sin tables ``[B, T, head_dim]`` in the HF half-split convention
    (rotate_half): frequencies repeat over the two halves."""
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    ang = positions.astype(jnp.float32)[..., None] * inv_freq  # [B, T, half]
    ang = jnp.concatenate([ang, ang], axis=-1)
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, T, H, hd]; cos/sin: [B, T, hd] (HF rotate_half convention)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    xf = x.astype(jnp.float32)
    out = xf * cos[..., None, :] + rotated.astype(jnp.float32) * sin[..., None, :]
    return out.astype(x.dtype)


def _rope_dim(cfg: ModelConfig) -> int:
    """Rotary dims per head (GPT-NeoX applies rotary to a prefix only)."""
    rd = int(cfg.head_dim * cfg.rope_pct)
    return rd - rd % 2


def _embed_tokens(params: dict, tokens: jax.Array, cfg: ModelConfig) -> jax.Array:
    x = params["embed"]["tok"][tokens].astype(cfg.dtype)
    if cfg.embed_scale:  # Gemma normalizer, cast to activation dtype like HF
        x = x * jnp.asarray(cfg.d_model**0.5, cfg.dtype)
    return x


def _act(x: jax.Array, name: str) -> jax.Array:
    if name == "silu":
        return jax.nn.silu(x)
    if name == "gelu_exact":
        return jax.nn.gelu(x, approximate=False)  # GPT-NeoX "gelu"
    return jax.nn.gelu(x, approximate=True)  # GPT-2 gelu_new


def attention(
    q: jax.Array,  # [B, T, Hq, hd]
    k: jax.Array,  # [B, S, Hkv, hd]
    v: jax.Array,  # [B, S, Hkv, hd]
    mask_bias: jax.Array,  # [B, 1, 1, T, S] float32 additive
    scale: float,
) -> jax.Array:
    """Grouped-query attention without materializing repeated KV."""
    B, T, Hq, hd = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    qg = q.reshape(B, T, Hkv, G, hd)
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores * scale + mask_bias
    w = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", w, v)
    return out.reshape(B, T, Hq, hd)


# tlint: hot-path
def _tp_gather(h: jax.Array, tp_axis: str | None, quant: bool) -> jax.Array:
    """Reassemble an activation whose LAST axis is split over ``tp_axis``.

    Identity when ``tp_axis`` is None (the single-device trace is
    unchanged). Inside shard_map, shards concatenate in axis-index order
    — ``lax.all_gather(tiled=True)`` — so the full activation is bitwise
    identical on every participant and to the unsharded compute.
    ``quant`` swaps in the EQuARX-style int8 gather
    (parallel/ring.py::quantized_all_gather): same fixed order, ≈½/¼ the
    wire bytes, bounded divergence (opt-in via collective_quant)."""
    if tp_axis is None:
        return h
    if quant:
        from ..parallel.ring import quantized_all_gather

        return quantized_all_gather(h, tp_axis, axis=h.ndim - 1, tiled=True)
    return lax.all_gather(h, tp_axis, axis=h.ndim - 1, tiled=True)


def _mlp(
    h: jax.Array,
    p: dict,
    cfg: ModelConfig,
    tp_axis: str | None = None,
    tp_quant: bool = False,
) -> jax.Array:
    """MLP block. Under tensor parallelism (``tp_axis``) w_gate/w_up hold
    LOCAL output columns and w_down holds the FULL hidden dim but LOCAL
    output columns — biases are sliced to match, applied before each
    gather (elementwise add commutes with concatenation), and the hidden
    and output reassemble via :func:`_tp_gather`."""
    if cfg.moe:
        return _moe_mlp(h, p, cfg)
    if cfg.mlp == "gated":
        g = _mm(h, p["w_gate"])
        u = _mm(h, p["w_up"])
        if "b_gate" in p:
            g = g + p["b_gate"]
            u = u + p["b_up"]
        mid = _tp_gather(_act(g, cfg.act) * u, tp_axis, tp_quant)
        out = _mm(mid, p["w_down"])
        if "b_down" in p:
            out = out + p["b_down"]
        return _tp_gather(out, tp_axis, tp_quant)
    mid = _tp_gather(_act(_mm(h, p["w_up"]) + p["b_up"], cfg.act), tp_axis, tp_quant)
    out = _mm(mid, p["w_down"]) + p["b_down"]
    return _tp_gather(out, tp_axis, tp_quant)


def _moe_mlp(h: jax.Array, p: dict, cfg: ModelConfig) -> jax.Array:
    """Mixtral-style top-k MoE.

    ``cfg.moe_dispatch == "sparse"`` routes to the capacity-factor top-k
    all-to-all dispatch (parallel/expert.py) — ~E/K× fewer expert FLOPs,
    used when the expert mesh axis is active. The default here is the
    dense-dispatch formulation: every expert sees every token and results
    combine with the (sparse) top-k routing weights — numerically identical
    to gather-based routing, exact, and GSPMD-friendly at small scale.
    """
    if cfg.moe_dispatch == "sparse":
        from ..parallel.expert import sparse_moe_mlp

        return sparse_moe_mlp(h, p, cfg)
    B, T, d = h.shape
    E, K = cfg.n_experts, cfg.n_experts_per_tok
    router_logits = _mm(h, p["router"]).astype(jnp.float32)  # [B, T, E]
    topw, topi = lax.top_k(router_logits, K)
    topw = jax.nn.softmax(topw, axis=-1)  # normalize over selected experts
    gates = jnp.zeros_like(router_logits).at[
        jnp.arange(B)[:, None, None],
        jnp.arange(T)[None, :, None],
        topi,
    ].set(topw)  # [B, T, E] sparse weights
    g = jnp.einsum("btd,edf->btef", h, p["w_gate"])
    u = jnp.einsum("btd,edf->btef", h, p["w_up"])
    y = jnp.einsum("btef,efd->bted", _act(g, cfg.act) * u, p["w_down"])
    return jnp.einsum("bted,bte->btd", y, gates.astype(h.dtype))


def _quant_kv(t: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 over head_dim: per-(row, position, head) scales —
    the int8 KV-cache write path."""
    tf = t.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(tf), axis=-1, keepdims=True), 1e-8) / 127.0
    q = jnp.clip(jnp.round(tf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _block(
    x: jax.Array,
    lp: dict,
    cfg: ModelConfig,
    cos: jax.Array | None,
    sin: jax.Array | None,
    mask_bias: jax.Array,
    # this layer's cache slice: None | (k, v) | (k, v, k_scale, v_scale)
    # — the 4-tuple is the int8 cache (see KVCache int8 mode)
    cache_kv: tuple | None,
    write_at: jax.Array | None,  # [B] int32 write offsets
    attn_fn=None,  # static override: (q, k, v, mask_bias, scale) -> out
):
    B, T, _ = x.shape
    post = cfg.norm_position == "post"  # OLMo-2: norm the sublayer output
    h = x if post else _norm(x, lp["ln1"], cfg)
    ap = lp["attn"]
    q = _mm(h, ap["wq"])
    k = _mm(h, ap["wk"])
    v = _mm(h, ap["wv"])
    if "bq" in ap:
        q, k, v = q + ap["bq"], k + ap["bk"], v + ap["bv"]
    if cfg.qk_norm_full:  # OLMo-2: full-projection-dim RMSNorm pre-reshape
        q = _rms_head_norm(q, ap["q_norm"], cfg.norm_eps)
        k = _rms_head_norm(k, ap["k_norm"], cfg.norm_eps)
    q = q.reshape(B, T, cfg.n_heads, cfg.head_dim)
    k = k.reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = _rms_head_norm(q, ap["q_norm"], cfg.norm_eps)
        k = _rms_head_norm(k, ap["k_norm"], cfg.norm_eps)
    if cos is not None:
        rd = cos.shape[-1]
        if rd == cfg.head_dim:
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        else:  # partial rotary (GPT-NeoX): prefix rotates, rest passes
            q = jnp.concatenate(
                [apply_rope(q[..., :rd], cos, sin), q[..., rd:]], axis=-1
            )
            k = jnp.concatenate(
                [apply_rope(k[..., :rd], cos, sin), k[..., rd:]], axis=-1
            )

    new_cache_kv = cache_kv
    if cache_kv is not None:
        upd = jax.vmap(
            lambda c, u, o: lax.dynamic_update_slice(
                c, u, (o,) + (0,) * (c.ndim - 1)
            )
        )
        if len(cache_kv) == 4:  # int8 cache: quantize writes, dequant reads
            ck, cv, cks, cvs = cache_kv
            k8, ks = _quant_kv(k)
            v8, vs = _quant_kv(v)
            ck = upd(ck, k8, write_at)
            cv = upd(cv, v8, write_at)
            cks = upd(cks, ks, write_at)
            cvs = upd(cvs, vs, write_at)
            k_all = (ck.astype(jnp.float32) * cks).astype(x.dtype)
            v_all = (cv.astype(jnp.float32) * cvs).astype(x.dtype)
            new_cache_kv = (ck, cv, cks, cvs)
        else:
            ck, cv = cache_kv
            ck = upd(ck, k.astype(ck.dtype), write_at)
            cv = upd(cv, v.astype(cv.dtype), write_at)
            k_all, v_all = ck, cv
            new_cache_kv = (ck, cv)
    else:
        k_all, v_all = k, v

    scale = cfg.attn_scale if cfg.attn_scale is not None else cfg.head_dim**-0.5
    impl = attn_fn or attention
    attn_out = impl(q, k_all.astype(q.dtype), v_all.astype(q.dtype), mask_bias, scale)
    attn_out = _mm(attn_out.reshape(B, T, cfg.q_dim), ap["wo"])
    if "bo" in ap:
        attn_out = attn_out + ap["bo"]
    if post:  # OLMo-2: ln1 == post_attention, ln2 == post_feedforward
        x = x + _norm(attn_out, lp["ln1"], cfg)
        x = x + _norm(_mlp(x, lp["mlp"], cfg), lp["ln2"], cfg)
    elif cfg.parallel_residual:  # GPT-NeoX: both branches read the block input
        x = x + attn_out + _mlp(_norm(x, lp["ln2"], cfg), lp["mlp"], cfg)
    else:
        x = x + attn_out
        x = x + _mlp(_norm(x, lp["ln2"], cfg), lp["mlp"], cfg)
    return x, new_cache_kv


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _mask_bias(
    q_pos: jax.Array,  # [B, T] absolute query positions
    kv_len: int,
    valid_kv: jax.Array,  # [B, S] bool — which kv slots hold real tokens
    sliding_window: int | None,
) -> jax.Array:
    """Additive float32 mask ``[B, 1, 1, T, S]``: causal (+ window) over
    absolute positions; padding handled via ``valid_kv``."""
    kv_idx = jnp.arange(kv_len)[None, None, :]  # [1, 1, S]
    qp = q_pos[:, :, None]  # [B, T, 1]
    ok = kv_idx <= qp
    if sliding_window is not None:
        ok &= kv_idx > qp - sliding_window
    ok &= valid_kv[:, None, :]
    return jnp.where(ok, 0.0, -jnp.inf).astype(jnp.float32)[:, None, None]


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "remat", "return_hidden", "seq_mesh", "seq_axis",
        "flash_prefill", "flash_mesh",
    ),
)
def forward(
    params: dict,
    tokens: jax.Array,  # int32 [B, T]
    cfg: ModelConfig,
    cache: KVCache | None = None,
    attn_mask: jax.Array | None = None,  # bool [B, T] valid-token mask
    positions: jax.Array | None = None,  # int32 [B, T] absolute positions
    remat: bool = False,
    return_hidden: bool = False,
    seq_mesh=None,  # Mesh with a ring axis → sequence-parallel attention
    seq_axis: str = "seq",
    # static promise that the cache is FRESH (offset 0) — lets the serving
    # engine's prefill route attention through the Pallas flash kernel
    # when cfg.flash_attention is set (ops/attention.py)
    flash_prefill: bool = False,
    # serving mesh (GSPMD has no partitioning rule for the Pallas kernel, so
    # under a mesh the flash call runs inside shard_map over data/tensor —
    # attention is independent per (batch, head), no collectives needed)
    flash_mesh=None,
):
    """Full forward. Returns ``(logits, new_cache)``.

    - Training / no-cache: causal self-attention over the sequence.
    - Prefill: pass a fresh ``cache``; keys/values land at positions
      ``cache.length + arange(T)`` per row.
    - Decode: same call with ``T=1`` — one compiled program per (B, T) bucket.

    Implemented as the single-stage case of :func:`_stage_impl` — the
    stage-chained pipeline path and this whole-model path share one
    implementation, which is what keeps the "stage chain == forward" parity
    tests (tests/test_stages.py) meaningful.
    """
    if return_hidden:
        x, new_cache = _stage_impl(
            params, cfg, tokens=tokens, cache=cache, attn_mask=attn_mask,
            positions=positions, first=True, last=False, remat=remat,
            seq_mesh=seq_mesh, seq_axis=seq_axis, flash_prefill=flash_prefill,
            flash_mesh=flash_mesh,
        )
        return _norm(x, params["final_norm"], cfg), new_cache
    return _stage_impl(
        params, cfg, tokens=tokens, cache=cache, attn_mask=attn_mask,
        positions=positions, first=True, last=True, remat=remat,
        seq_mesh=seq_mesh, seq_axis=seq_axis, flash_prefill=flash_prefill,
        flash_mesh=flash_mesh,
    )


def _logits(
    params: dict,
    x: jax.Array,
    cfg: ModelConfig,
    tp_axis: str | None = None,
    tp_quant: bool = False,
) -> jax.Array:
    """LM head. Under tensor parallelism a tied head computes the full
    vocab locally (the embedding is replicated — no collective); an
    untied ``lm_head`` holds LOCAL vocab columns and the logits reassemble
    via :func:`_tp_gather` so sampling sees the full distribution,
    identical on every shard."""
    if cfg.tie_embeddings:
        logits = x @ params["embed"]["tok"].T.astype(cfg.dtype)
    else:
        logits = _tp_gather(_mm(x, params["lm_head"]), tp_axis, tp_quant)
    if cfg.logit_cap is not None:
        logits = cfg.logit_cap * jnp.tanh(logits / cfg.logit_cap)
    return logits


# ---------------------------------------------------------------------------
# Stage-wise forward (pipeline parallelism)
# ---------------------------------------------------------------------------
#
# A pipeline stage holds a contiguous layer slice (params["layers"] stacked
# over just those layers) plus, per the plan flags, the embedding
# (StagePlan.first) and final norm + head (StagePlan.holds_head). Chaining
# stage_forward over all stages reproduces forward() exactly — that
# equivalence is the unit test replacing the reference's "logits match the
# unsharded model" check (reference assembles per-worker nn.Module
# fragments, ml/graphing.py).
#
# Flag mapping for executors: pass ``first=stage.first`` and
# ``last=stage.last and stage.holds_head``. When embeddings are tied across
# a multi-stage plan the head lives on stage 0 (holds_head=True there), so
# the final stage returns hidden and the driver finishes with
# :func:`head_forward` on stage 0.


@partial(
    jax.jit,
    static_argnames=("cfg", "first", "last", "remat", "seq_mesh", "seq_axis"),
)
def stage_forward(
    params: dict,
    cfg: ModelConfig,  # FULL model config (stage layer count comes from params)
    *,
    tokens: jax.Array | None = None,  # int32 [B, T] (first stage)
    hidden: jax.Array | None = None,  # [B, T, D] (later stages)
    cache: KVCache | None = None,  # this stage's cache (its layers only)
    attn_mask: jax.Array | None = None,  # bool [B, T]
    positions: jax.Array | None = None,  # int32 [B, T]
    first: bool = False,
    last: bool = False,
    remat: bool = False,
    seq_mesh=None,  # Mesh with a ring axis → sequence-parallel attention
    seq_axis: str = "seq",
):
    """Run one pipeline stage. Returns ``(out, new_cache)`` where ``out`` is
    logits when ``last`` else the hidden state to ship to the next stage.

    ``seq_mesh`` switches attention to the ring formulation
    (parallel/ring.py) with activations sequence-sharded over
    ``mesh[seq_axis]`` — the long-context product path (SURVEY §5: the
    reference scales sequence only by renting a bigger worker). Ring mode
    requires no KV cache, no padding mask, and no sliding window."""
    return _stage_impl(
        params, cfg, tokens=tokens, hidden=hidden, cache=cache,
        attn_mask=attn_mask, positions=positions, first=first, last=last,
        remat=remat, seq_mesh=seq_mesh, seq_axis=seq_axis,
    )


def _stage_impl(
    params: dict,
    cfg: ModelConfig,
    *,
    tokens: jax.Array | None = None,
    hidden: jax.Array | None = None,
    cache: KVCache | None = None,
    attn_mask: jax.Array | None = None,
    positions: jax.Array | None = None,
    first: bool,
    last: bool,
    remat: bool,
    seq_mesh=None,
    seq_axis: str = "seq",
    flash_prefill: bool = False,
    flash_mesh=None,
):
    attn_fn = None
    T_in = tokens.shape[1] if tokens is not None else (
        hidden.shape[1] if hidden is not None else 1
    )
    B_in = tokens.shape[0] if tokens is not None else (
        hidden.shape[0] if hidden is not None else 1
    )
    if (
        flash_prefill
        and cfg.flash_attention
        and cache is not None
        and T_in > 1
        and T_in % min(128, T_in) == 0  # irregular bucket -> einsum, not a
        and seq_mesh is None  # trace-time crash of serving
        # off the TPU the kernel only runs in interpret mode, which
        # BENCH_r10 measured at 0.99x the einsum (pure overhead) — fall
        # through to einsum there unless a test opts in explicitly
        and (
            jax.default_backend() == "tpu"
            or _os.environ.get("TLTPU_FLASH_INTERPRET") == "1"
        )
    ):
        from ..ops.attention import flash_attention

        interp = jax.default_backend() != "tpu"  # env opt-in: interpret mode
        T_flash = T_in
        win = cfg.sliding_window

        def _flash(q, k_all, v_all, scale):
            # fresh cache (offset 0): keys beyond T are zeros the causal
            # mask would hide anyway — attend over the written prefix only
            return flash_attention(
                q, k_all[:, :T_flash], v_all[:, :T_flash],
                scale=scale, interpret=interp, window=win,
            )

        if flash_mesh is None:
            def attn_fn(q, k_all, v_all, _bias, scale):
                return _flash(q, k_all, v_all, scale)
        else:
            # GSPMD cannot partition a pallas_call, so run it manually via
            # shard_map: batch shards on data, heads on tensor — attention
            # is independent per (batch, head), so no collectives
            try:
                from jax import shard_map
            except ImportError:  # pre-0.8 jax
                from jax.experimental.shard_map import shard_map
            import inspect

            # the pallas_call's out_shape carries no varying-axis metadata,
            # so replication checking must be off — but the kwarg's NAME
            # keys on the actual signature, not the import location: some
            # jax versions export jax.shard_map while still taking
            # check_rep
            _sm_params = inspect.signature(shard_map).parameters
            if "check_vma" in _sm_params:
                _sm_kw = {"check_vma": False}
            elif "check_rep" in _sm_params:
                _sm_kw = {"check_rep": False}
            else:
                _sm_kw = {}
            from jax.sharding import PartitionSpec as _P

            sizes = dict(flash_mesh.shape)
            dp = (
                "data"
                if sizes.get("data", 1) > 1 and B_in % sizes["data"] == 0
                else None
            )
            tp = (
                "tensor"
                if sizes.get("tensor", 1) > 1
                and cfg.n_heads % sizes["tensor"] == 0
                and cfg.n_kv_heads % sizes["tensor"] == 0
                else None
            )
            spec = _P(dp, None, tp, None)

            def attn_fn(q, k_all, v_all, _bias, scale):
                return shard_map(
                    lambda ql, kl, vl: _flash(ql, kl, vl, scale),
                    mesh=flash_mesh,
                    in_specs=(spec, spec, spec),
                    out_specs=spec,
                    **_sm_kw,
                )(q, k_all, v_all)
    if seq_mesh is not None:
        if cache is not None:
            raise ValueError("sequence-parallel attention has no KV cache path")
        if attn_mask is not None:
            raise ValueError(
                "sequence-parallel attention does not support padding masks"
            )
        if cfg.sliding_window is not None:
            raise ValueError(
                "sequence-parallel attention does not support sliding windows"
            )
        from ..parallel.ring import ring_attention

        def attn_fn(q, k, v, _bias, scale):  # causal masking is global-
            # position arithmetic inside the ring; _bias is unused
            return ring_attention(
                q, k, v, seq_mesh, axis_name=seq_axis, scale=scale,
                causal=True, quantized=cfg.collective_quant,
            )

    if first:
        if tokens is None:
            raise ValueError("first stage requires tokens")
        B, T = tokens.shape
    else:
        if hidden is None:
            raise ValueError("non-first stage requires hidden")
        B, T = hidden.shape[:2]
    if attn_mask is None:
        attn_mask = jnp.ones((B, T), bool)
    offset = cache.length if cache is not None else jnp.zeros((B,), jnp.int32)
    if positions is None:
        positions = offset[:, None] + jnp.arange(T)[None, :]

    if first:
        x = _embed_tokens(params, tokens, cfg)
        if cfg.pos == "learned":
            x = x + params["embed"]["pos"][positions].astype(cfg.dtype)
    else:
        x = hidden.astype(cfg.dtype)

    cos = sin = None
    if cfg.pos == "rope":
        cos, sin = rope_tables(positions, _rope_dim(cfg), cfg.rope_theta)

    if cache is not None:
        S = cache.max_len
        kv_idx = jnp.arange(S)[None, :]
        new_len = offset + attn_mask.sum(-1).astype(jnp.int32)
        valid_kv = kv_idx < new_len[:, None]
    else:
        valid_kv = attn_mask
    bias = _mask_bias(positions, valid_kv.shape[-1], valid_kv, cfg.sliding_window)

    block = _block
    if remat:
        block = jax.checkpoint(
            _block,
            policy=jax.checkpoint_policies.nothing_saveable,
            static_argnums=(2, 8),  # cfg, attn_fn
        )

    layers = params.get("layers")
    new_cache = cache
    if layers is not None:
        if cache is not None:
            arrays = (cache.k, cache.v)
            if cache.quantized:
                arrays += (cache.k_scale, cache.v_scale)

            def scan_fn(carry, xs):
                lp = xs[0]
                y, ckv = block(
                    carry, lp, cfg, cos, sin, bias, tuple(xs[1:]), offset,
                    attn_fn,
                )
                return y, ckv

            x, outs = lax.scan(scan_fn, x, (layers,) + arrays)
            new_cache = KVCache(
                k=outs[0],
                v=outs[1],
                length=offset + attn_mask.sum(-1).astype(jnp.int32),
                k_scale=outs[2] if cache.quantized else None,
                v_scale=outs[3] if cache.quantized else None,
            )
        else:

            def scan_fn(carry, lp):
                y, _ = block(
                    carry, lp, cfg, cos, sin, bias, None, None, attn_fn
                )
                return y, None

            x, _ = lax.scan(scan_fn, x, layers)

    if last:
        x = _norm(x, params["final_norm"], cfg)
        return _logits(params, x, cfg), new_cache
    return x, new_cache


@partial(jax.jit, static_argnames=("cfg",))
def head_forward(params: dict, hidden: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Final norm + lm head only — serves the tied-embedding hop where the
    last pipeline stage ships hidden states back to stage 0 for logits
    (planner.py marks stage 0 ``last`` when embeddings are tied)."""
    x = _norm(hidden.astype(cfg.dtype), params["final_norm"], cfg)
    return _logits(params, x, cfg)


def slice_stage_params(
    params: dict, lo: int, hi: int, *, first: bool, holds_head: bool
) -> dict:
    """Cut a full parameter tree down to one stage's tree (host-side; used by
    tests and by single-host multi-stage simulations — real workers load only
    their slice from the checkpoint, engine/loader.py)."""
    out: dict = {}
    if first:
        out["embed"] = params["embed"]
    if holds_head:
        out["final_norm"] = params["final_norm"]
        if "lm_head" in params:
            out["lm_head"] = params["lm_head"]
        if "embed" not in out and "lm_head" not in params:
            out["embed"] = params["embed"]  # tied head needs the embedding
    if hi > lo:
        out["layers"] = jax.tree.map(lambda a: a[lo:hi], params["layers"])
    return out


# ---------------------------------------------------------------------------
# Sharding
# ---------------------------------------------------------------------------


def partition_specs(
    cfg: ModelConfig,
    *,
    tensor_axis: str | None = "tensor",
    expert_axis: str | None = None,
    fsdp_axis: str | None = None,
) -> dict:
    """Megatron-style PartitionSpec pytree matching :func:`init_params`.

    The TPU replacement for the reference's per-worker module assignment
    (ml/graphing.py:730-761): sharding is declared per-parameter and GSPMD
    inserts the collectives. qkv/gate/up shard their output dim on
    ``tensor_axis`` (column-parallel); wo/down shard their input dim
    (row-parallel) so each pair needs one psum. ``fsdp_axis`` additionally
    shards the remaining large dim (ZeRO-3 style). Experts shard on
    ``expert_axis``.
    """
    t, e, fs = tensor_axis, expert_axis, fsdp_axis

    def spec(*names):
        return P(*names)

    ln = {"scale": spec(None, None)}
    if cfg.norm == "layernorm":
        ln["bias"] = spec(None, None)
    attn = {
        "wq": spec(None, fs, t),
        "wk": spec(None, fs, t),
        "wv": spec(None, fs, t),
        "wo": spec(None, t, fs),
    }
    if cfg.attn_bias:
        attn |= {"bq": spec(None, t), "bk": spec(None, t), "bv": spec(None, t)}
    if cfg.attn_out_bias or cfg.family == "gpt2":  # must match init_params
        attn["bo"] = spec(None, None)
    if cfg.qk_norm:
        attn |= {"q_norm": spec(None, None), "k_norm": spec(None, None)}
    if cfg.qk_norm_full:  # scales align with the column-sharded projections
        attn |= {"q_norm": spec(None, t), "k_norm": spec(None, t)}

    if cfg.moe:
        mlp = {
            "router": spec(None, None, None),
            "w_gate": spec(None, e, fs, t),
            "w_up": spec(None, e, fs, t),
            "w_down": spec(None, e, t, fs),
        }
    elif cfg.mlp == "gated":
        mlp = {
            "w_gate": spec(None, fs, t),
            "w_up": spec(None, fs, t),
            "w_down": spec(None, t, fs),
        }
        if cfg.mlp_bias:
            mlp |= {
                "b_gate": spec(None, t),
                "b_up": spec(None, t),
                "b_down": spec(None, None),
            }
    else:
        mlp = {
            "w_up": spec(None, fs, t),
            "b_up": spec(None, t),
            "w_down": spec(None, t, fs),
            "b_down": spec(None, None),
        }

    specs = {
        "embed": {"tok": spec(t, fs)},
        "layers": {"ln1": ln, "attn": attn, "ln2": dict(ln), "mlp": mlp},
        "final_norm": {"scale": spec(None)}
        | ({"bias": spec(None)} if cfg.norm == "layernorm" else {}),
    }
    if cfg.pos == "learned":
        specs["embed"]["pos"] = spec(None, fs)
    if not cfg.tie_embeddings:
        specs["lm_head"] = spec(fs, t)
    return specs


def tp_shardable(cfg: ModelConfig, tp: int) -> str | None:
    """Why ``cfg`` can NOT shard ``tp`` ways on the explicit serving TP
    path, or ``None`` when it can.

    The explicit path (``tp_partition_specs`` + shard_map in
    engine/paged.py) slices heads/columns head-major-contiguously and
    reassembles with exact tiled all_gathers, so the constraints are pure
    divisibility plus two structural refusals: MoE (routing is global)
    and ``qk_norm_full`` (its RMSNorm spans the FULL projection dim — a
    local head slice would normalize over the wrong statistics)."""
    tp = int(tp)
    if tp <= 1:
        return None
    if cfg.moe:
        return "MoE routing is not tensor-shardable on the serving path"
    if cfg.qk_norm_full:
        return "qk_norm_full normalizes over the full projection dim"
    for name in ("n_heads", "n_kv_heads", "d_ff", "d_model"):
        val = int(getattr(cfg, name))
        if val % tp:
            return f"{name}={val} is not divisible by tp={tp}"
    if not cfg.tie_embeddings and cfg.vocab_size % tp:
        return f"untied vocab_size={cfg.vocab_size} is not divisible by tp={tp}"
    return None


def tp_partition_specs(cfg: ModelConfig, axis: str = "tp") -> dict:
    """PartitionSpec pytree for the EXPLICIT (shard_map) serving TP path
    — matches :func:`init_params`, walkable by dot-path (engine/loader).

    Unlike the GSPMD :func:`partition_specs` (where wo/w_down are
    row-parallel and XLA inserts psums), every matmul weight here shards
    its OUTPUT dim and activations reassemble with exact tiled
    all_gathers — column-slice matmuls are bitwise equal to slicing the
    full product, and a fixed-order concat is bitwise associative-free,
    which is what keeps tp=N streams bit-identical to tp=1
    (docs/SHARDING.md). Biases shard with the outputs they add onto;
    embeddings/norms replicate; per-head qk_norm scales (``[L, hd]``)
    replicate and apply to local heads unchanged. Gate with
    :func:`tp_shardable` first."""
    if cfg.moe:
        raise ValueError("MoE params have no explicit-TP partition specs")
    t = axis
    rep2, rep1 = P(None, None), P(None)

    ln = {"scale": rep2}
    if cfg.norm == "layernorm":
        ln["bias"] = rep2
    attn = {
        "wq": P(None, None, t),
        "wk": P(None, None, t),
        "wv": P(None, None, t),
        "wo": P(None, None, t),  # output (d_model) columns — input q_dim FULL
    }
    if cfg.attn_bias:
        attn |= {"bq": P(None, t), "bk": P(None, t), "bv": P(None, t)}
    if cfg.attn_out_bias or cfg.family == "gpt2":  # must match init_params
        attn["bo"] = P(None, t)
    if cfg.qk_norm:
        attn |= {"q_norm": rep2, "k_norm": rep2}
    if cfg.qk_norm_full:  # refused by tp_shardable; specs stay replicated
        attn |= {"q_norm": rep2, "k_norm": rep2}

    if cfg.mlp == "gated":
        mlp = {
            "w_gate": P(None, None, t),
            "w_up": P(None, None, t),
            "w_down": P(None, None, t),  # output (d_model) columns — f FULL
        }
        if cfg.mlp_bias:
            mlp |= {"b_gate": P(None, t), "b_up": P(None, t), "b_down": P(None, t)}
    else:
        mlp = {
            "w_up": P(None, None, t),
            "b_up": P(None, t),
            "w_down": P(None, None, t),
            "b_down": P(None, t),
        }

    specs = {
        "embed": {"tok": rep2},
        "layers": {"ln1": ln, "attn": attn, "ln2": dict(ln), "mlp": mlp},
        "final_norm": {"scale": rep1}
        | ({"bias": rep1} if cfg.norm == "layernorm" else {}),
    }
    if cfg.pos == "learned":
        specs["embed"]["pos"] = rep2
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, t)
    return specs


def cache_specs(
    cfg: ModelConfig, *, data_axis="data", tensor_axis="tensor",
    quantized: bool = False,
):
    """KV cache sharding: batch on data, kv heads on tensor (when they
    divide; the planner degrades to replicated heads otherwise)."""
    kv = P(None, data_axis, None, tensor_axis, None)
    return KVCache(
        k=kv,
        v=kv,
        length=P(data_axis),
        k_scale=kv if quantized else None,
        v_scale=kv if quantized else None,
    )
