"""Prompt + response formatting (reference ml/formatter.py, 550 LoC).

Covers the same surface: generation-arg normalization, chat templating
(native HF template when a tokenizer provides one, manual Qwen/Llama/generic
fallbacks), ``<think>``-block reasoning extraction, and the ResponseFormatter
producing OpenAI / simple / raw shapes for non-stream, SSE chunk, SSE final
with usage, and errors.
"""

from __future__ import annotations

import json
import re
import time
import uuid
from typing import Any

# ---------------------------------------------------------------------------
# Generation-argument normalization (reference formatter.py:7-116)
# ---------------------------------------------------------------------------


def normalize_generate_args(
    req: Any,  # GenerationRequest
    *,
    prompt_len: int,
    max_context: int,
) -> dict:
    """Clamp/clean sampling args against the model's context window
    (reference normalize_generate_args: pad/eos fixups, max_new_tokens
    clamping, sampling-param validation, formatter.py:7)."""
    room = max(max_context - prompt_len, 1)
    max_new = min(int(req.max_new_tokens), room)
    if req.max_length:
        max_new = min(max_new, max(int(req.max_length) - prompt_len, 1))
    temperature = float(req.temperature) if req.do_sample else 0.0
    if temperature < 1e-4:
        temperature = 0.0  # greedy
    top_p = min(max(float(req.top_p), 1e-3), 1.0)
    top_k = max(int(req.top_k), 0)
    return {
        "max_new_tokens": max_new,
        "temperature": temperature,
        "top_p": top_p,
        "top_k": top_k,
        # range-validated at parse time ([-2, 2] → 400), passed through
        # like temperature/top_p
        "presence_penalty": float(getattr(req, "presence_penalty", 0.0)),
        "frequency_penalty": float(getattr(req, "frequency_penalty", 0.0)),
    }


# ---------------------------------------------------------------------------
# Chat templating (reference formatter.py:161-323)
# ---------------------------------------------------------------------------


def format_chat_prompt(
    message: str,
    history: list[dict] | None = None,
    *,
    tokenizer: Any = None,
    model_name: str = "",
    system_prompt: str | None = None,
    enable_thinking: bool = False,
) -> str:
    """Render a chat exchange to a single prompt string.

    Prefers the tokenizer's native ``apply_chat_template`` (reference
    formatter.py:238-260); falls back to manual Qwen (ChatML) / Llama-3 /
    generic templates keyed off the model name (formatter.py:161-235).
    """
    msgs = list(history or [])
    if system_prompt and not any(m.get("role") == "system" for m in msgs):
        msgs.insert(0, {"role": "system", "content": system_prompt})
    msgs.append({"role": "user", "content": message})

    if tokenizer is not None and getattr(tokenizer, "chat_template", None):
        kw = {"tokenize": False, "add_generation_prompt": True}
        try:
            return tokenizer.apply_chat_template(
                msgs, enable_thinking=enable_thinking, **kw
            )
        except TypeError:  # template without thinking support
            return tokenizer.apply_chat_template(msgs, **kw)

    name = model_name.lower()
    if "qwen" in name or "chatml" in name:
        out = []
        for m in msgs:
            out.append(f"<|im_start|>{m['role']}\n{m['content']}<|im_end|>")
        out.append("<|im_start|>assistant")
        if not enable_thinking and "qwen3" in name:
            out.append("<think>\n\n</think>\n")
        return "\n".join(out) + "\n"
    if "llama-3" in name or "llama3" in name:
        out = ["<|begin_of_text|>"]
        for m in msgs:
            out.append(
                f"<|start_header_id|>{m['role']}<|end_header_id|>\n\n"
                f"{m['content']}<|eot_id|>"
            )
        out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
        return "".join(out)
    # generic
    out = []
    for m in msgs:
        out.append(f"{m['role'].capitalize()}: {m['content']}")
    out.append("Assistant:")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Reasoning extraction (reference formatter.py:118-159)
# ---------------------------------------------------------------------------

_THINK_RE = re.compile(
    r"<(think|thinking|reasoning|reflection)>(.*?)</\1>\s*",
    re.DOTALL | re.IGNORECASE,
)


def extract_reasoning_and_answer(text: str) -> tuple[str, str]:
    """Split ``<think>``-family blocks from the visible answer. Returns
    ``(reasoning, answer)``; reasoning is "" when no block is present. An
    unterminated block (stream cut mid-thought) counts as all reasoning."""
    blocks = _THINK_RE.findall(text)
    if blocks:
        reasoning = "\n".join(b[1].strip() for b in blocks)
        answer = _THINK_RE.sub("", text).strip()
        return reasoning, answer
    m = re.match(r"\s*<(think|thinking|reasoning)>(.*)", text, re.DOTALL | re.IGNORECASE)
    if m:
        return m.group(2).strip(), ""
    return "", text.strip()


class StopStream:
    """Streaming stop-sequence filter with OpenAI earliest-START semantics.

    The subtlety: with overlapping stops (e.g. ``["X", "bXY"]`` on
    ``"abXY…"``) the first COMPLETED match ("X") is not necessarily the
    earliest-STARTING one ("bXY") — cutting eagerly would emit different
    text than the non-stream path's ``min(find(s))`` truncation. So the
    filter never emits past the earliest position where any stop could
    still start (exact prefix check), and only cuts once no earlier
    candidate remains open. ``flush()`` resolves pending prefixes at end of
    stream (an unfinished prefix is NOT a match)."""

    def __init__(self, stops: list[str], emit):
        self.stops = list(stops)
        self.emit = emit
        self.hold = ""
        self.stopped = False

    def _earliest_open_prefix(self) -> int | None:
        for j in range(len(self.hold)):
            tail = self.hold[j:]
            if any(s.startswith(tail) and len(tail) < len(s)
                   for s in self.stops):
                return j
        return None

    def _scan(self, final: bool) -> None:
        if self.stopped:
            return
        hits = [i for i in (self.hold.find(s) for s in self.stops)
                if i != -1]
        best = min(hits) if hits else None
        pending = None if final else self._earliest_open_prefix()
        if best is not None and (pending is None or pending >= best):
            if best:
                self.emit(self.hold[:best])
            self.hold = ""
            self.stopped = True
            return
        boundary = pending if pending is not None else len(self.hold)
        if boundary:
            self.emit(self.hold[:boundary])
            self.hold = self.hold[boundary:]

    def feed(self, delta: str) -> None:
        if self.stopped or not delta:
            return
        self.hold += delta
        self._scan(final=False)

    def flush(self) -> None:
        self._scan(final=True)


class ThinkStripStream:
    """Incremental ``<think>`` stripper for SSE streams (reference strips
    think blocks in-stream, ml/validator.py:782-808). Feed decoded text
    pieces; emits only visible-answer text."""

    def __init__(self):
        self._buf = ""
        self._in_think = False
        self._done_think = False

    def feed(self, piece: str) -> str:
        self._buf += piece
        out = []
        while self._buf:
            if self._in_think:
                end = self._buf.find("</think>")
                if end < 0:
                    return "".join(out)  # still inside the block
                self._buf = self._buf[end + len("</think>"):]
                self._in_think = False
                self._done_think = True
                self._buf = self._buf.lstrip("\n")
                continue
            start = self._buf.find("<think>")
            if start < 0:
                # hold back a potential partial opening tag at the tail
                safe = len(self._buf)
                for k in range(1, min(len("<think>"), len(self._buf)) + 1):
                    if "<think>".startswith(self._buf[-k:]):
                        safe = len(self._buf) - k
                        break
                out.append(self._buf[:safe])
                self._buf = self._buf[safe:]
                return "".join(out)
            out.append(self._buf[:start])
            self._buf = self._buf[start + len("<think>"):]
            self._in_think = True
        return "".join(out)

    def flush(self) -> str:
        out, self._buf = ("" if self._in_think else self._buf), ""
        return out


# ---------------------------------------------------------------------------
# Response shapes (reference ResponseFormatter, formatter.py:327-550)
# ---------------------------------------------------------------------------


class ResponseFormatter:
    """OpenAI / simple / raw response shapes + SSE wire format."""

    def __init__(self, model: str, fmt: str = "simple"):
        self.model = model
        self.fmt = fmt
        self.id = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        self.created = int(time.time())

    def _usage(self, prompt_tokens: int, completion_tokens: int) -> dict:
        return {
            "prompt_tokens": prompt_tokens,
            "completion_tokens": completion_tokens,
            "total_tokens": prompt_tokens + completion_tokens,
        }

    def complete(
        self,
        text: str,
        *,
        prompt_tokens: int = 0,
        completion_tokens: int = 0,
        reasoning: str = "",
        finish_reason: str = "stop",
        extra: dict | None = None,
    ) -> dict:
        """Non-stream final body (reference formatter.py:331-407).
        ``extra`` merges server-side annotations (e.g. ``num_beams_used``
        when the worker clamped a beam request) into the body top level."""
        if self.fmt == "openai":
            msg = {"role": "assistant", "content": text}
            if reasoning:
                msg["reasoning_content"] = reasoning
            body = {
                "id": self.id,
                "object": "chat.completion",
                "created": self.created,
                "model": self.model,
                "choices": [
                    {"index": 0, "message": msg, "finish_reason": finish_reason}
                ],
                "usage": self._usage(prompt_tokens, completion_tokens),
            }
        elif self.fmt == "raw":
            body = {"output": text, "reasoning": reasoning}
        else:
            body = {
                "response": text,
                "model": self.model,
                "usage": self._usage(prompt_tokens, completion_tokens),
            }
            if reasoning:
                body["reasoning"] = reasoning
        if extra:
            body.update(extra)
        return body

    def complete_multi(self, results: list[dict]) -> dict:
        """OpenAI ``n``-choice completion body: one choice per generated
        result dict ({text, reasoning, finish_reason, prompt_tokens,
        completion_tokens}). Usage counts the prompt once (every choice
        shares it) and sums completions — OpenAI's convention."""
        choices = []
        for i, r in enumerate(results):
            msg = {"role": "assistant", "content": r["text"]}
            if r.get("reasoning"):
                msg["reasoning_content"] = r["reasoning"]
            choices.append(
                {"index": i, "message": msg,
                 "finish_reason": r.get("finish_reason", "stop")}
            )
        prompt = results[0]["prompt_tokens"] if results else 0
        return {
            "id": self.id,
            "object": "chat.completion",
            "created": self.created,
            "model": self.model,
            "choices": choices,
            "usage": self._usage(
                prompt, sum(r.get("completion_tokens", 0) for r in results)
            ),
        }

    def stream_chunk(self, delta_text: str) -> dict:
        """One SSE chunk (reference formatter.py:409-450)."""
        if self.fmt == "openai":
            return {
                "id": self.id,
                "object": "chat.completion.chunk",
                "created": self.created,
                "model": self.model,
                "choices": [
                    {"index": 0, "delta": {"content": delta_text},
                     "finish_reason": None}
                ],
            }
        return {"token": delta_text, "model": self.model}

    def stream_prelude(self, meta: dict) -> dict:
        """First SSE event of a stream, carrying the journal re-attach
        handle (``jrid``, docs/FAILURE_MODEL.md "Control plane") before
        any token — a client can only resume a crash-interrupted stream
        if it learned the jrid ahead of the crash. Shaped as an empty
        delta chunk so strict OpenAI stream parsers pass through it."""
        body = self.stream_chunk("")
        body.update(meta)
        return body

    def stream_final(
        self, *, prompt_tokens: int, completion_tokens: int,
        finish_reason: str = "stop", extra: dict | None = None,
    ) -> dict:
        """Final SSE chunk with usage (reference formatter.py:452-509).
        ``extra`` merges server-side annotations (e.g. ``jrid``) into the
        body top level, like :meth:`complete`."""
        if self.fmt == "openai":
            body = {
                "id": self.id,
                "object": "chat.completion.chunk",
                "created": self.created,
                "model": self.model,
                "choices": [
                    {"index": 0, "delta": {}, "finish_reason": finish_reason}
                ],
                "usage": self._usage(prompt_tokens, completion_tokens),
            }
        else:
            body = {
                "done": True,
                "model": self.model,
                "usage": self._usage(prompt_tokens, completion_tokens),
                "finish_reason": finish_reason,
            }
        if extra:
            body.update(extra)
        return body

    def error(self, message: str, *, status: int = 500, kind: str = "server_error") -> dict:
        """Error body (reference formatter.py:512-549)."""
        if self.fmt == "openai":
            return {"error": {"message": message, "type": kind, "code": status}}
        return {"error": message, "status": status}


def sse_event(data: dict | str) -> bytes:
    """Wire-encode one SSE event (``data: {...}\\n\\n``)."""
    if not isinstance(data, str):
        data = json.dumps(data, separators=(",", ":"))
    return f"data: {data}\n\n".encode()


SSE_DONE = b"data: [DONE]\n\n"
