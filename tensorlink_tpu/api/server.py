"""TensorlinkAPI — the validator's HTTP endpoint.

Reference: api/node.py:94 (FastAPI + uvicorn in a daemon thread, routes
/v1/generate, /v1/chat/completions, /request-model, /model-status, /models,
/model-demand, /stats, /network-history, /node-info). Same routes and wire
shapes, implemented on stdlib asyncio (no fastapi/uvicorn in the TPU image):
an HTTP/1.1 parser, JSON bodies, and SSE streaming fed by the compiled
decode loop through ``loop.call_soon_threadsafe`` (the reference feeds
asyncio queues from the ML thread the same way, api/node.py:440-454).
"""

from __future__ import annotations

import asyncio
import json
import re
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable
from urllib.parse import unquote, urlparse

from tensorlink_tpu.api.formatter import (
    SSE_DONE,
    ResponseFormatter,
    sse_event,
)
from tensorlink_tpu.api.schemas import (
    ChatCompletionRequest,
    GenerationRequest,
    JobRequest,
    ValidationError,
)
from tensorlink_tpu.core.logging import get_logger
from tensorlink_tpu.core.metrics import MetricsRegistry, render_prometheus
from tensorlink_tpu.core.trace import get_tracer, mint_trace_id

MAX_BODY = 8 << 20
MAX_CONCURRENT = 100  # reference api/node.py:537
REQUEST_TIMEOUT = 300.0  # reference api/node.py:506
STREAM_TOKEN_TIMEOUT = 30.0  # reference api/node.py:410


class HTTPError(Exception):
    def __init__(
        self,
        status: int,
        message: str,
        extra: dict | None = None,
        headers: dict | None = None,
    ):
        super().__init__(message)
        self.status = status
        self.body = {"error": message, **(extra or {})}
        self.headers = dict(headers or {})


# client-supplied X-Request-Id values must be safe to echo into a
# response header and to use as a tracer key: token charset only,
# bounded length — anything else (header-injection attempts, unbounded
# ids that could churn the tracer's LRU) gets a freshly minted id
_RID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

# tlint: disable=TL006(read-only constant table — never mutated at runtime)
_STATUS = {
    200: "OK", 400: "Bad Request", 404: "Not Found", 405: "Method Not Allowed",
    408: "Request Timeout", 413: "Payload Too Large", 429: "Too Many Requests",
    500: "Internal Server Error", 503: "Service Unavailable",
}


class TensorlinkAPI:
    """HTTP server bound to a validator node + its ML executor."""

    def __init__(
        self,
        node,  # ValidatorNode (runner)
        executor,  # DistributedValidator
        *,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.node = node
        self.executor = executor
        self.host = host
        self.port = port
        self.log = get_logger("api")
        self._pool = ThreadPoolExecutor(max_workers=8, thread_name_prefix="api-ml")
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._server: asyncio.AbstractServer | None = None
        # the transport-backstop gate: only ever touched on the server's
        # event loop (handler coroutines + the on-loop reject helper)
        self._inflight = 0  #: guarded by the event loop
        # per-connection request id (X-Request-Id / trace id): keyed by
        # writer so the response helpers can echo it on every reply path
        # (success, HTTPError, 500) without threading it through each
        # handler signature
        self._req_ids: dict = {}  #: guarded by the event loop
        # API-level metrics: the server's own registry, merged with every
        # hosted model's engine registry by the /metrics handler
        self.metrics = MetricsRegistry()
        self._m_requests = self.metrics.counter(
            "tlink_http_requests_total", "HTTP requests handled"
        )
        self._m_errors = self.metrics.counter(
            "tlink_http_errors_total", "HTTP error responses sent"
        )
        self.metrics.gauge(
            "tlink_http_inflight", "generations in flight",
            fn=lambda: self._inflight,
        )

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "TensorlinkAPI":
        if self._thread:
            return self
        ready = threading.Event()

        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def boot():
                self._server = await asyncio.start_server(
                    self._handle_conn, self.host, self.port or None
                )
                self.port = self._server.sockets[0].getsockname()[1]

            self._loop.run_until_complete(boot())
            ready.set()
            try:
                self._loop.run_forever()
            finally:
                self._loop.run_until_complete(self._shutdown())
                self._loop.close()

        self._thread = threading.Thread(target=run, name="api-http", daemon=True)
        self._thread.start()
        if not ready.wait(10):
            raise RuntimeError("API server failed to start")
        self.log.info("serving on http://%s:%s", self.host, self.port)
        return self

    async def _shutdown(self):
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    def stop(self) -> None:
        if self._loop:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread:
            self._thread.join(timeout=10)
            self._thread = None
        self._pool.shutdown(wait=False)

    async def _ml(self, fn: Callable, *args) -> Any:
        """Run blocking executor work off the event loop."""
        return await asyncio.get_running_loop().run_in_executor(
            self._pool, fn, *args
        )

    # -- connection handling -------------------------------------------
    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            req = await asyncio.wait_for(self._read_request(reader), 30)
            if req is None:
                return
            method, path, headers, body = req
            # one trace id per request, echoed as X-Request-Id on every
            # response path below. A client-supplied id is honored (so a
            # gateway can pre-mint and correlate) only when it is a safe
            # header token — else a fresh id is minted
            client_rid = headers.get("x-request-id", "")
            rid = (
                client_rid if _RID_RE.match(client_rid)
                else mint_trace_id()
            )
            self._req_ids[writer] = rid
            self._m_requests.inc()
            await self._route(method, path, headers, body, writer)
        except HTTPError as e:
            self._m_errors.inc()
            rid = self._req_ids.get(writer)
            if rid and "trace_id" not in e.body:
                # rejection bodies (429s included) carry the trace id so a
                # client can hand /trace/<rid> to an operator verbatim
                e.body["trace_id"] = rid
            await self._send_json(writer, e.status, e.body, headers=e.headers)
        except asyncio.TimeoutError:
            self._m_errors.inc()
            await self._send_json(writer, 408, {"error": "request timeout"})
        # tlint: disable=TL005(client hung up mid-reply — no one left to answer)
        except (ConnectionError, OSError):
            pass
        except Exception:
            self._m_errors.inc()
            self.log.exception("request failed")
            try:
                await self._send_json(writer, 500, {"error": "internal error"})
            # tlint: disable=TL005(client hung up before the 500 could land — already logged above)
            except (ConnectionError, OSError):
                pass
        finally:
            self._req_ids.pop(writer, None)
            try:
                writer.close()
                await writer.wait_closed()
            # tlint: disable=TL005(closing an already-dead transport)
            except (ConnectionError, OSError):
                pass

    async def _read_request(self, reader):
        line = await reader.readline()
        if not line:
            return None
        try:
            method, target, _version = line.decode("latin1").split(None, 2)
        except ValueError:
            raise HTTPError(400, "malformed request line")
        headers: dict[str, str] = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            if b":" in h:
                k, v = h.decode("latin1").split(":", 1)
                headers[k.strip().lower()] = v.strip()
        length = int(headers.get("content-length", 0))
        if length > MAX_BODY:
            raise HTTPError(413, "body too large")
        body = await reader.readexactly(length) if length else b""
        return method.upper(), target, headers, body

    @staticmethod
    def _json_body(body: bytes) -> dict:
        if not body:
            return {}
        try:
            d = json.loads(body)
        except json.JSONDecodeError:
            raise HTTPError(400, "invalid JSON body")
        if not isinstance(d, dict):
            raise HTTPError(400, "JSON body must be an object")
        return d

    # tlint: on-loop — only called from the response coroutines
    def _rid_header(self, writer) -> str:
        rid = self._req_ids.get(writer)
        return f"X-Request-Id: {rid}\r\n" if rid else ""

    async def _send_json(
        self, writer, status: int, payload: dict,
        headers: dict | None = None,
    ) -> None:
        data = json.dumps(payload, default=str).encode()
        extra = "".join(
            f"{k}: {v}\r\n" for k, v in (headers or {}).items()
        )
        head = (
            f"HTTP/1.1 {status} {_STATUS.get(status, 'OK')}\r\n"
            "Content-Type: application/json\r\n"
            f"Content-Length: {len(data)}\r\n"
            f"{extra}"
            f"{self._rid_header(writer)}"
            "Connection: close\r\n\r\n"
        ).encode()
        writer.write(head + data)
        await writer.drain()

    async def _send_text(
        self, writer, status: int, text: str,
        content_type: str = "text/plain; version=0.0.4; charset=utf-8",
    ) -> None:
        """Plain-text response — the Prometheus exposition's shape."""
        data = text.encode()
        head = (
            f"HTTP/1.1 {status} {_STATUS.get(status, 'OK')}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(data)}\r\n"
            f"{self._rid_header(writer)}"
            "Connection: close\r\n\r\n"
        ).encode()
        writer.write(head + data)
        await writer.drain()

    async def _send_sse_headers(self, writer) -> None:
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            + self._rid_header(writer).encode()
            + b"Connection: close\r\n\r\n"
        )
        await writer.drain()

    # -- routing --------------------------------------------------------
    async def _route(self, method, target, headers, body, writer) -> None:
        path = unquote(urlparse(target).path.rstrip("/") or "/")
        if method == "GET":
            if path == "/health":
                return await self._send_json(writer, 200, {"status": "ok"})
            if path == "/healthz":
                # the LB/router probe: dict reads only, never an
                # ML-process round trip (docs/SERVING.md "Telemetry")
                return await self._send_json(
                    writer, 200, self.executor.health_snapshot()
                )
            if path == "/metrics":
                # Prometheus text exposition: the API registry merged with
                # every hosted model's engine registry (or its last remote
                # serving snapshot as gauges). Rendered off the event loop
                # — collection takes the executor's host lock.
                text = await self._ml(self._metrics_text)
                return await self._send_text(writer, 200, text)
            if path.startswith("/trace/"):
                rid = path[len("/trace/"):]
                spans = get_tracer().collect(rid)
                if not spans and not get_tracer().known(rid):
                    raise HTTPError(404, f"no trace {rid}")
                return await self._send_json(
                    writer, 200, {"trace_id": rid, "spans": spans}
                )
            if path == "/models":
                return await self._send_json(writer, 200, self._models())
            if path == "/v1/models":
                # OpenAI-compatible listing so off-the-shelf clients
                # pointed at this endpoint can enumerate models
                return await self._send_json(writer, 200, {
                    "object": "list",
                    "data": [
                        {"id": j["name"], "object": "model",
                         "owned_by": "tensorlink"}
                        for j in self.executor.hosted_snapshot()
                        if j.get("status") == "ready"
                    ],
                })
            if path == "/model-demand":
                return await self._send_json(
                    writer, 200, {"demand": dict(self.executor.demand)}
                )
            if path.startswith("/model-status/"):
                name = path[len("/model-status/"):]
                return await self._send_json(
                    writer, 200, self.executor.model_status(name)
                )
            if path == "/stats":
                st = await self._ml(self.node.status)
                # per-hosted-model serving telemetry (scheduler counters
                # plus the slot engine's prefix-cache/occupancy snapshot
                # when continuous batching is active) rides the same
                # route operators already poll for node health
                st["models"] = await self._ml(self.executor.hosted_snapshot)
                return await self._send_json(writer, 200, st)
            if path == "/fleet":
                # per-model fleet state: router replica table + routed
                # counts, autopilot status/history (docs/SERVING.md
                # "Fleet serving"). Off the event loop — collection
                # takes the executor's host lock.
                return await self._send_json(
                    writer, 200,
                    {"fleet": await self._ml(self.executor.fleet_snapshot)},
                )
            if path == "/node-info":
                return await self._send_json(writer, 200, self._node_info())
            if path == "/network-history":
                return await self._send_json(
                    writer, 200, await self._ml(self._network_history)
                )
            if path == "/proposal-history":
                hist = await self._ml(
                    lambda: self.node.send_request("proposal_history")
                )
                return await self._send_json(writer, 200, {"proposals": hist})
            if path.startswith("/claim-info/"):
                wid = path[len("/claim-info/"):]
                claim = await self._ml(
                    lambda: self.node.send_request(
                        "claim_info", {"worker_id": wid}
                    )
                )
                return await self._send_json(
                    writer, 200 if "error" not in claim else 404, claim
                )
            raise HTTPError(404, f"no route {path}")
        if method != "POST":
            raise HTTPError(405, f"method {method} not allowed")
        data = self._json_body(body)
        if path == "/v1/generate":
            return await self._generate(data, writer)
        if path == "/v1/chat/completions":
            try:
                chat = ChatCompletionRequest.parse(data)
            except ValidationError as e:
                raise HTTPError(400, str(e))
            gen = chat.to_generation_request()
            return await self._generate_common(gen, writer, n=chat.n)
        if path == "/request-model":
            return await self._request_model(data, writer)
        if path == "/fleet/deploy":
            # operator trigger for a zero-dropped-token rolling deploy:
            # {"model": name, "replicas": ["r0", ...]} (replicas
            # optional = all). The autopilot drains each replica onto a
            # sibling, rebuilds it, rejoins it — streams migrate through
            # the export/stage/adopt path, bit-identical.
            model = str(data.get("model", ""))
            if not model:
                raise HTTPError(400, "deploy needs {'model': name}")
            reps = data.get("replicas")
            if reps is not None and not isinstance(reps, list):
                raise HTTPError(400, "'replicas' must be a list")
            out = await self._ml(
                lambda: self.executor.fleet_deploy(model, reps)
            )
            return await self._send_json(
                writer, 200 if out.get("ok") else 404, out
            )
        raise HTTPError(404, f"no route {path}")

    def _metrics_text(self) -> str:
        groups: list = [({}, self.metrics)]
        groups.extend(self.executor.metrics_groups())
        return render_prometheus(groups)

    # -- route bodies ---------------------------------------------------
    def _models(self) -> dict:
        # snapshot under the executor's lock — pool threads mutate hosted
        return {"models": self.executor.hosted_snapshot()}

    def _node_info(self) -> dict:
        return {
            "id": self.node.node_id,
            "role": self.node.role,
            "port": self.node.port,
            "hosted_models": [j["name"] for j in self.executor.hosted_snapshot()],
        }

    def _network_history(self) -> dict:
        # Keeper daily/weekly statistics (reference keeper.py:502-572)
        hist = self.node.send_request("network_history")
        st = self.node.status()
        roles: dict[str, int] = {}
        for p in st.get("peers", {}).values():
            roles[p.get("role", "?")] = roles.get(p.get("role", "?"), 0) + 1
        hist["current"] = {**hist.get("current", {}), **roles}
        return hist

    async def _request_model(self, data: dict, writer) -> None:
        try:
            jr = JobRequest.parse(data)
        except ValidationError as e:
            raise HTTPError(400, str(e))
        wait = bool(data.get("wait", True))
        if wait:
            job = await self._ml(
                lambda: self.executor.host_model(
                    jr.hf_name, batch=jr.batch, seq_len=jr.seq_len,
                    config=jr.config, quant=jr.quant,
                )
            )
            status = 200 if job.status == "ready" else 503
            out = {"model": jr.hf_name, "status": job.status}
            if job.error:
                out["error"] = job.error
            return await self._send_json(writer, status, out)
        self._pool.submit(
            self.executor.host_model, jr.hf_name,
            batch=jr.batch, seq_len=jr.seq_len, config=jr.config,
            quant=jr.quant,
        )
        await self._send_json(
            writer, 200, {"model": jr.hf_name, "status": "loading"}
        )

    async def _generate(self, data: dict, writer) -> None:
        try:
            gen = GenerationRequest.parse(data)
        except ValidationError as e:
            raise HTTPError(400, str(e))
        await self._generate_common(gen, writer)

    # tlint: on-loop — only called from _generate_common (a coroutine)
    def _reject_if_overloaded(self, job, gen, n: int) -> None:
        """Scheduler-driven backpressure (replaces the old flat
        concurrent-request counter): the hosted model's batcher judges the
        request's priority class against its queue caps and estimated
        wait, and a rejection becomes ``429`` with a ``Retry-After``
        header plus the class/queue-depth detail in the JSON body. The
        flat ``MAX_CONCURRENT`` bound survives only as the transport
        backstop protecting the HTTP pool itself (models without a
        class-aware batcher, requests racing a model reload)."""
        priority = getattr(gen, "priority", "") or None
        if self._inflight + n > MAX_CONCURRENT:
            raise HTTPError(
                429, "too many concurrent requests",
                {"queue_depth": self._inflight, "cap": MAX_CONCURRENT,
                 "priority": priority or "interactive", "retry_after": 1},
                headers={"Retry-After": "1"},
            )
        # a fleet-hosted model's gate is the ROUTER's: admit when any
        # non-draining replica would (docs/SERVING.md "Fleet serving")
        gate = getattr(job, "router", None)
        if gate is None:
            gate = getattr(job, "batcher", None)
        check = getattr(gate, "admission_check", None)
        rej = check(priority, n) if callable(check) else None
        if rej:
            retry = max(1, int(round(float(rej.get("retry_after", 1.0)))))
            raise HTTPError(
                429,
                f"{rej['priority']} queue is full "
                f"({rej['queue_depth']}/{rej['cap']} queued)",
                {"priority": rej["priority"],
                 "queue_depth": rej["queue_depth"],
                 "cap": rej["cap"], "retry_after": retry},
                headers={"Retry-After": str(retry)},
            )

    async def _generate_common(
        self, gen: GenerationRequest, writer, n: int = 1
    ) -> None:
        from tensorlink_tpu.ml.validator import ModelNotReady

        rid = self._req_ids.get(writer, "")
        if getattr(self.executor, "recovering", False):
            # the validator is replaying its control journal (crash
            # recovery, docs/FAILURE_MODEL.md "Control plane") — a finite
            # window during which placements are still re-attaching.
            # Clients hold off and retry; /healthz shows the same flag so
            # LBs stop routing new placements here meanwhile.
            raise HTTPError(
                503, "validator is recovering — retry shortly",
                {"recovering": True, "retry_after": 2},
                headers={"Retry-After": "2"},
            )
        job = self.executor.hosted.get(gen.hf_name)
        if job is None or job.status != "ready":
            # 503 + auto-load trigger (reference api/node.py:143-155)
            if job is None:
                self._pool.submit(self.executor.host_model, gen.hf_name)
                state = "loading"
            else:
                state = job.status
            raise HTTPError(
                503, f"model {gen.hf_name} is {state}",
                {"model": gen.hf_name, "status": state},
            )
        self._reject_if_overloaded(job, gen, n)

        from tensorlink_tpu.engine.scheduler import SchedulerOverloaded

        fmt = ResponseFormatter(gen.hf_name, gen.output_format)
        self._inflight += n
        try:
            if not gen.stream:
                # return_exceptions: every sibling dispatch completes before
                # an error propagates — otherwise one failed choice would
                # orphan n-1 running generations while _inflight is already
                # decremented for all n (silent 429-gate erosion; pinned by
                # test_api_unit.py::test_n_gt_1_failure_does_not_erode_gate)
                results = await asyncio.wait_for(
                    asyncio.gather(
                        *(self._ml(
                            lambda: self.executor.generate_api(
                                gen, trace_id=rid
                            )
                        ) for _ in range(n)),
                        return_exceptions=True,
                    ),
                    REQUEST_TIMEOUT,
                )
                for r in results:
                    if isinstance(r, ModelNotReady):
                        raise HTTPError(503, str(r))
                    if isinstance(r, SchedulerOverloaded):
                        # the engine-side backstop fired (a race admitted
                        # past the API gate): same 429 + Retry-After
                        # contract as the front gate
                        retry = max(1, int(round(r.retry_after)))
                        raise HTTPError(
                            429, str(r),
                            {"priority": r.priority,
                             "queue_depth": r.queue_depth,
                             "cap": r.cap, "retry_after": retry},
                            headers={"Retry-After": str(retry)},
                        )
                    if isinstance(r, ValidationError):
                        # request-vs-model mismatch detected past parse time
                        # (e.g. penalties on a multi-stage model)
                        raise HTTPError(400, str(r))
                    if isinstance(r, BaseException):
                        raise r
                if n > 1:
                    # the n concurrent dispatches coalesced in the batcher;
                    # shape one chat.completion with n choices
                    return await self._send_json(
                        writer, 200, fmt.complete_multi(list(results))
                    )
                result = results[0]
                return await self._send_json(
                    writer, 200,
                    fmt.complete(
                        result["text"],
                        prompt_tokens=result["prompt_tokens"],
                        completion_tokens=result["completion_tokens"],
                        reasoning=result["reasoning"],
                        finish_reason=result["finish_reason"],
                        # only this path can carry the beam-clamp note:
                        # num_beams>1 + stream is rejected at parse time
                        # (schemas.py), and n>1 is a chat-completions-only
                        # field while num_beams is /v1/generate-only.
                        # jrid is the journal re-attach handle
                        # (docs/FAILURE_MODEL.md "Control plane")
                        extra={
                            k: result[k] for k in ("num_beams_used", "jrid")
                            if k in result
                        } or None,
                    ),
                )
            await self._stream_generate(gen, fmt, writer, rid)
        finally:
            self._inflight -= n

    async def _stream_generate(self, gen, fmt, writer, rid: str = "") -> None:
        """SSE: ML thread pushes deltas through call_soon_threadsafe."""
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def on_delta(piece: str) -> None:
            loop.call_soon_threadsafe(q.put_nowait, ("delta", piece))

        def on_meta(meta: dict) -> None:
            # admission metadata — the journal re-attach handle (jrid)
            # must reach the client BEFORE any crash can cut the stream
            loop.call_soon_threadsafe(q.put_nowait, ("meta", meta))

        def work():
            try:
                res = self.executor.generate_api(
                    gen, on_delta=on_delta, trace_id=rid, meta_cb=on_meta
                )
                loop.call_soon_threadsafe(q.put_nowait, ("done", res))
            except Exception as e:
                loop.call_soon_threadsafe(q.put_nowait, ("err", e))

        # not awaited on the timeout path: the generation thread cannot be
        # cancelled mid-decode, and holding the connection (and the caller's
        # inflight slot) for it would stall unrelated requests; the closure
        # keeps q alive, late puts are simply dropped with the queue
        loop.run_in_executor(self._pool, work)
        await self._send_sse_headers(writer)
        while True:
            try:
                kind, item = await asyncio.wait_for(
                    q.get(), STREAM_TOKEN_TIMEOUT
                )
            except asyncio.TimeoutError:
                writer.write(sse_event(fmt.error("stream token timeout", status=408)))
                writer.write(SSE_DONE)
                await writer.drain()
                return
            if kind == "delta":
                writer.write(sse_event(fmt.stream_chunk(item)))
                await writer.drain()
            elif kind == "meta":
                writer.write(sse_event(fmt.stream_prelude(item)))
                await writer.drain()
            elif kind == "done":
                writer.write(
                    sse_event(fmt.stream_final(
                        prompt_tokens=item["prompt_tokens"],
                        completion_tokens=item["completion_tokens"],
                        finish_reason=item["finish_reason"],
                        extra={
                            k: item[k] for k in ("jrid",) if k in item
                        } or None,
                    ))
                )
                writer.write(SSE_DONE)
                await writer.drain()
                return
            else:  # err
                writer.write(sse_event(fmt.error(str(item))))
                writer.write(SSE_DONE)
                await writer.drain()
                return
