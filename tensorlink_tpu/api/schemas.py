"""Request/response schemas (reference api/models.py, pydantic).

Dataclasses + explicit validation: the environment has no pydantic, and the
validation the API actually needs is small (types, ranges, enums). Unlike the
reference's ``GenerationRequest`` — which is mutated in-flight with
``output``/``processing``/``cancelled`` fields as it rides through the
pipeline (api/models.py:17-57) — these are immutable inputs; pipeline state
lives in :class:`~tensorlink_tpu.api.server.PendingRequest`.
"""

from __future__ import annotations

from dataclasses import dataclass, field


class ValidationError(ValueError):
    pass


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValidationError(msg)


@dataclass(frozen=True)
class GenerationRequest:
    """POST /v1/generate body (reference api/models.py:17)."""

    hf_name: str
    message: str = ""
    history: list[dict] = field(default_factory=list)  # [{role, content}]
    max_length: int | None = None
    max_new_tokens: int = 256
    temperature: float = 0.6
    top_p: float = 0.95
    top_k: int = 0
    do_sample: bool = True
    # OpenAI repetition control, APPLIED in the compiled sampler
    # (engine/sampling.py; the reference declares these, api/models.py:73-74,
    # but never uses them). Single-stage jobs only.
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    stream: bool = False
    output_format: str = "simple"  # "simple" | "openai" | "raw"
    enable_thinking: bool = False
    # opt-in speculative decode (prompt-lookup drafting; greedy B=1 —
    # engine/generate.py::generate_lookahead). Emits exactly the vanilla
    # greedy tokens, so honoring it is always safe; ignored when sampling.
    lookahead: bool = False
    # opt-in CONTINUOUS speculative decoding (engine/continuous.py,
    # docs/SERVING.md "Speculative decoding"): the request's decode slot
    # packs prompt-lookup drafts as extra ragged rows and the one
    # compiled step verifies them in-program — works under any sampling,
    # emits the bit-identical stream either way, and is a no-op unless
    # the hosting replica runs MLConfig.spec_decode (see /healthz
    # serving_modes). A pure speed hint, like lookahead.
    speculative: bool = False
    # opt-OUT of the disaggregated prefill→decode handoff (docs/SERVING.md
    # "Disaggregated prefill/decode"): on a pool with prefill/decode
    # worker roles a continuous request prefills on a prefill-pool worker
    # and is handed to a decode-pool worker at the prefill boundary —
    # bit-identical either way, so the default is opted in; false pins
    # the stream to the admission worker (debugging, latency-probing a
    # specific replica). A no-op on single-pool deployments.
    handoff: bool = True
    # beam search width (the reference forwards num_beams to HF generate,
    # ml/formatter.py:88-92; here engine/generate.py::generate_beam on
    # whole-model jobs and ml/module.py::_generate_beam_pipelined on
    # multi-stage jobs). >1: deterministic beam decode — sampling knobs
    # are ignored, streaming is rejected.
    num_beams: int = 1
    # OpenAI-style stop sequences (the reference declares this field,
    # api/models.py:70, but never applies it — here output is truncated at
    # the earliest occurrence, streaming included via api/formatter.py
    # StopStream). A confirmed match CANCELS the row mid-loop on
    # host-driven decode paths (pipelined sessions, streamed engine
    # decode), and on the fully-compiled streamed loop it rides the
    # STREAM_CANCEL backchannel to the worker, which polls at
    # ``stream_chunk_steps`` chunk boundaries — overrun past a stop is at
    # most one chunk, not the full token budget. Non-streamed single-stage
    # requests keep the pure compiled loop (no cancel); completion_tokens
    # always counts tokens generated THROUGH the match, not the full
    # decode.
    # With enable_thinking=true the live stream is unfiltered (raw think
    # text) and only the final answer is truncated.
    stop: list[str] = field(default_factory=list)
    # SLO scheduling class (engine/scheduler.py, docs/SERVING.md
    # "Scheduling"): "interactive" | "batch" | "best_effort". Empty →
    # the validator's MLConfig.default_priority. Orders admission on the
    # continuous serving path (aging keeps low classes starvation-free;
    # an interactive request may preempt a lower-class slot) and selects
    # the 429 backpressure queue the request is judged against.
    priority: str = ""
    # journal rid of a stream a LOST validator was serving (the client
    # re-attach ladder, docs/FAILURE_MODEL.md "Control plane"). Repeat
    # the ORIGINAL request body plus this field against the recovered
    # validator: the stream resumes from the worker's orphan buffer and
    # the response carries the COMPLETE stream from token 0 — clients
    # REPLACE any partial pre-crash text with it (exactly-once by
    # replacement). The jrid itself rides every response body and, on
    # SSE, a prelude event before the first delta.
    reattach: str = ""

    _PRIORITIES = ("interactive", "batch", "best_effort")

    @classmethod
    def _parse_priority(cls, v) -> str:
        if v is None or v == "":
            return ""
        _require(
            isinstance(v, str) and v.lower() in cls._PRIORITIES,
            "priority must be one of interactive|batch|best_effort",
        )
        return v.lower()

    @staticmethod
    def _parse_stop(v) -> list[str]:
        if v is None:
            return []
        if isinstance(v, str):
            v = [v]
        _require(isinstance(v, list) and len(v) <= 4, "stop: up to 4 strings")
        for s in v:
            _require(isinstance(s, str) and s, "stop entries must be "
                     "non-empty strings")
        return list(v)

    @classmethod
    def parse(cls, d: dict) -> "GenerationRequest":
        _require(isinstance(d.get("hf_name"), str) and d["hf_name"], "hf_name required")
        try:
            req = cls(
                hf_name=d["hf_name"],
                message=str(d.get("message", "")),
                history=list(d.get("history", [])),
                max_length=d.get("max_length"),
                max_new_tokens=int(d.get("max_new_tokens", 256)),
                temperature=float(d.get("temperature", 0.6)),
                top_p=float(d.get("top_p", 0.95)),
                top_k=int(d.get("top_k", 0)),
                do_sample=bool(d.get("do_sample", True)),
                presence_penalty=float(d.get("presence_penalty", 0.0)),
                frequency_penalty=float(d.get("frequency_penalty", 0.0)),
                stream=bool(d.get("stream", False)),
                output_format=str(d.get("output_format", "simple")),
                enable_thinking=bool(d.get("enable_thinking", False)),
                lookahead=bool(d.get("lookahead", False)),
                speculative=bool(d.get("speculative", False)),
                handoff=bool(d.get("handoff", True)),
                num_beams=int(d.get("num_beams", 1)),
                stop=cls._parse_stop(d.get("stop")),
                priority=cls._parse_priority(d.get("priority")),
                reattach=str(d.get("reattach", "") or ""),
            )
        except ValidationError:
            raise
        except (TypeError, ValueError) as e:
            # null / non-numeric values in numeric fields must be a 400,
            # not an int()/float() TypeError surfacing as a 500
            raise ValidationError(f"invalid field value: {e}")
        _require(len(req.reattach) <= 64, "reattach rid too long")
        _require(
            not (req.reattach and req.num_beams > 1),
            "reattach cannot combine with num_beams",
        )
        _require(req.max_new_tokens > 0, "max_new_tokens must be positive")
        _require(0.0 <= req.temperature <= 2.0, "temperature must be in [0, 2]")
        _require(0.0 < req.top_p <= 1.0, "top_p must be in (0, 1]")
        _require(req.top_k >= 0, "top_k must be >= 0")
        _require(1 <= req.num_beams <= 8, "num_beams must be in [1, 8]")
        _require(
            req.num_beams == 1 or not req.stream,
            "num_beams > 1 requires stream=false",
        )
        _require(
            req.num_beams == 1 or not req.do_sample,
            "num_beams > 1 is deterministic: set do_sample=false",
        )
        _require(
            req.num_beams == 1
            or (req.presence_penalty == 0 and req.frequency_penalty == 0),
            "num_beams > 1 does not support repetition penalties",
        )
        for nm, v in (("presence_penalty", req.presence_penalty),
                      ("frequency_penalty", req.frequency_penalty)):
            _require(-2.0 <= v <= 2.0, f"{nm} must be in [-2, 2]")
        _require(
            req.output_format in ("simple", "openai", "raw"),
            "output_format must be simple|openai|raw",
        )
        for h in req.history:
            _require(
                isinstance(h, dict) and "role" in h and "content" in h,
                "history entries need role+content",
            )
        return req


@dataclass(frozen=True)
class ChatCompletionRequest:
    """POST /v1/chat/completions body (reference api/models.py:60)."""

    model: str
    messages: list[dict]
    max_tokens: int = 256
    temperature: float = 0.6
    top_p: float = 0.95
    stream: bool = False
    lookahead: bool = False  # speculative decode hint (greedy only)
    # continuous draft/verify hint (see GenerationRequest.speculative)
    speculative: bool = False
    # prefill→decode handoff opt-out (see GenerationRequest.handoff)
    handoff: bool = True
    stop: list[str] = field(default_factory=list)
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # number of choices (OpenAI ``n``; non-streaming only — the n requests
    # dispatch concurrently and the batcher coalesces them into one decode)
    n: int = 1
    # SLO scheduling class (see GenerationRequest.priority)
    priority: str = ""

    @classmethod
    def parse(cls, d: dict) -> "ChatCompletionRequest":
        _require(isinstance(d.get("model"), str) and d["model"], "model required")
        msgs = d.get("messages")
        _require(isinstance(msgs, list) and msgs, "messages required")
        for m in msgs:
            _require(
                isinstance(m, dict) and "role" in m and "content" in m,
                "each message needs role+content",
            )
        try:
            req = cls(
                model=d["model"],
                messages=msgs,
                max_tokens=int(d.get("max_tokens", d.get("max_completion_tokens", 256))),
                temperature=float(d.get("temperature", 0.6)),
                top_p=float(d.get("top_p", 0.95)),
                stream=bool(d.get("stream", False)),
                lookahead=bool(d.get("lookahead", False)),
                speculative=bool(d.get("speculative", False)),
                handoff=bool(d.get("handoff", True)),
                stop=GenerationRequest._parse_stop(d.get("stop")),
                presence_penalty=float(d.get("presence_penalty", 0.0)),
                frequency_penalty=float(d.get("frequency_penalty", 0.0)),
                n=int(d.get("n", 1)),
                priority=GenerationRequest._parse_priority(d.get("priority")),
            )
        except ValidationError:
            raise
        except (TypeError, ValueError) as e:
            raise ValidationError(f"invalid field value: {e}")
        _require(req.max_tokens > 0, "max_tokens must be positive")
        _require(1 <= req.n <= 8, "n must be in [1, 8]")
        _require(
            req.n == 1 or not req.stream, "n > 1 requires stream=false"
        )
        for nm, v in (("presence_penalty", req.presence_penalty),
                      ("frequency_penalty", req.frequency_penalty)):
            _require(-2.0 <= v <= 2.0, f"{nm} must be in [-2, 2]")
        return req

    def to_generation_request(self) -> GenerationRequest:
        """OpenAI messages → internal request (reference
        _parse_chat_messages, api/node.py:53-92): last user message is the
        prompt, the rest is history."""
        history = [
            {"role": m["role"], "content": m["content"]} for m in self.messages[:-1]
        ]
        last = self.messages[-1]
        return GenerationRequest(
            hf_name=self.model,
            message=str(last.get("content", "")),
            history=history,
            max_new_tokens=self.max_tokens,
            temperature=self.temperature,
            top_p=self.top_p,
            stream=self.stream,
            output_format="openai",
            lookahead=self.lookahead,
            speculative=self.speculative,
            handoff=self.handoff,
            stop=self.stop,
            presence_penalty=self.presence_penalty,
            frequency_penalty=self.frequency_penalty,
            priority=self.priority,
        )


@dataclass(frozen=True)
class JobRequest:
    """POST /request-model body (reference api/models.py:9). ``config`` is
    an optional explicit ModelConfig dict — the analogue of the reference's
    custom-distribution job path (user_thread.py:242 explicit jobs)."""

    hf_name: str
    batch: int = 1
    seq_len: int = 2048
    training: bool = False
    config: dict | None = None
    # weight-only-quantized serving ("int8" halves parameter HBM traffic;
    # "int8+kv" also stores KV quantized — docs/SERVING.md "Quantized KV")
    quant: str | None = None

    @classmethod
    def parse(cls, d: dict) -> "JobRequest":
        _require(isinstance(d.get("hf_name"), str) and d["hf_name"], "hf_name required")
        cfg = d.get("config")
        _require(cfg is None or isinstance(cfg, dict), "config must be an object")
        quant = d.get("quant")
        _require(
            quant in (None, "int8", "int8+kv"),
            "quant must be \"int8\" or \"int8+kv\"",
        )
        try:
            req = cls(
                hf_name=d["hf_name"],
                batch=int(d.get("batch", 1)),
                seq_len=int(d.get("seq_len", 2048)),
                training=bool(d.get("training", False)),
                config=cfg,
                quant=quant,
            )
        except ValidationError:
            raise
        except (TypeError, ValueError) as e:
            raise ValidationError(f"invalid field value: {e}")
        _require(req.batch >= 1, "batch must be >= 1")
        _require(req.seq_len >= 1, "seq_len must be >= 1")
        return req
