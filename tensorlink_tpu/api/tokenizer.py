"""Tokenizer resolution for hosted models.

The reference pulls ``AutoTokenizer`` for every hosted job
(ml/validator.py:971 wires tokenizer into the hosted DistributedModel). Here
HF tokenizers are used when a checkpoint/tokenizer is available; otherwise a
deterministic byte-level fallback keeps offline tests and synthetic models
servable (vocab = 256 bytes + BOS/EOS sentinels).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

from tensorlink_tpu.core.logging import get_logger

log = get_logger("api.tokenizer")


class ByteTokenizer:
    """UTF-8 byte fallback: id = byte value; 256=BOS, 257=EOS."""

    vocab_size = 258
    bos_token_id = 256
    eos_token_id = 257
    chat_template = None
    model_max_length = 1 << 20

    def encode(self, text: str, add_special_tokens: bool = False) -> list[int]:
        ids = list(text.encode("utf-8"))
        if add_special_tokens:
            ids = [self.bos_token_id] + ids
        return ids

    def decode(self, ids, skip_special_tokens: bool = True) -> str:
        bs = bytes(i for i in ids if 0 <= int(i) < 256)
        return bs.decode("utf-8", errors="replace")

    def __call__(self, text: str, **kw) -> dict:
        return {"input_ids": self.encode(text)}


class TokenizerAdapter:
    """Uniform surface over HF tokenizers and the byte fallback."""

    def __init__(self, tok: Any):
        self.tok = tok

    @property
    def chat_template(self):
        return getattr(self.tok, "chat_template", None)

    @property
    def eos_ids(self) -> list[int]:
        eid = getattr(self.tok, "eos_token_id", None)
        if eid is None:
            return []
        return [eid] if isinstance(eid, int) else list(eid)

    @property
    def model_max_length(self) -> int:
        n = int(getattr(self.tok, "model_max_length", 1 << 20) or 1 << 20)
        return min(n, 1 << 20)  # HF uses huge sentinels for "unset"

    def apply_chat_template(self, *a, **kw):
        return self.tok.apply_chat_template(*a, **kw)

    def encode(self, text: str) -> list[int]:
        return list(self.tok.encode(text, add_special_tokens=False))

    def decode(self, ids) -> str:
        return self.tok.decode(list(ids), skip_special_tokens=True)


def load_tokenizer(model_spec: dict) -> TokenizerAdapter:
    """Checkpoint dir with tokenizer files → AutoTokenizer; known HF name →
    AutoTokenizer (may hit cache offline); otherwise byte fallback."""
    ckpt = model_spec.get("ckpt")
    candidates = []
    if ckpt and Path(str(ckpt)).is_dir():
        d = Path(str(ckpt))
        if (d / "tokenizer.json").exists() or (d / "tokenizer_config.json").exists():
            candidates.append(str(d))
    name = model_spec.get("name", "")
    if "/" in name:
        candidates.append(name)
    for cand in candidates:
        try:
            from transformers import AutoTokenizer

            return TokenizerAdapter(AutoTokenizer.from_pretrained(cand))
        except Exception as e:
            log.debug("tokenizer candidate %s unavailable: %s", cand, e)
            continue
    return TokenizerAdapter(ByteTokenizer())
