"""HTTP serving layer.

Reference: tensorlink/api (FastAPI + uvicorn, api/node.py:94) with OpenAI-
compatible schemas (api/models.py) and prompt/response formatting
(ml/formatter.py). This environment ships no fastapi/uvicorn/pydantic, so the
server is stdlib asyncio HTTP with dataclass schemas — same routes, same
response shapes, same SSE wire format.
"""

from tensorlink_tpu.api.server import TensorlinkAPI

__all__ = ["TensorlinkAPI"]
