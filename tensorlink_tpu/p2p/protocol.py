"""Wire protocol: frame layout and message tags.

Re-specification of the reference's ad-hoc binary protocol (SURVEY.md §2.3).
The reference delimits frames by scanning for a sentinel
(``EOT_CHAR = b"HELLOCHENQUI"``, p2p/connection.py:67) and dispatches on
variable-length ASCII prefixes (p2p/torch_node.py:119-131). Here every frame
is length-prefixed — O(1) boundary detection, arbitrary binary payloads:

    magic "TLNK" | u8 version | u8 kind | u16 tag_len | u64 payload_len
    | tag (ascii) | payload

``kind`` separates control (JSON payload) from bulk (TLTS array payload)
frames so receivers can route big tensors to spill files without parsing.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass

MAGIC = b"TLNK"
VERSION = 1
HEADER = struct.Struct("<4sBBHQ")  # magic, version, kind, tag_len, payload_len
HEADER_SIZE = HEADER.size

# frame kinds
CONTROL = 0  # payload is UTF-8 JSON
BULK = 1  # payload is a TLTS blob (core/serialization.py) or raw bytes

# Practical ceiling for a single frame; module shipping above this streams
# through spill files on the receiver (reference spills >20 MB to
# tmp/streamed_data_* files, connection.py:110-122).
MAX_FRAME = 64 << 30
SPILL_THRESHOLD = 32 << 20  # frames larger than this land on disk

# ---------------------------------------------------------------------------
# Message tags. Same *semantics* as the reference's (SURVEY.md §2.3) so the
# job lifecycle and mental model carry over; the encoding does not.
# ---------------------------------------------------------------------------

# p2p substrate
PING = "ping"
PONG = "pong"
HELLO = "hello"  # handshake step 1 (initiator)
CHALLENGE = "challenge"  # handshake step 2 (listener)
PROOF = "proof"  # handshake step 3 (initiator)
WELCOME = "welcome"  # handshake step 4 (listener accepts)
DHT_GET = "dht.get"
DHT_GET_RESP = "dht.get.resp"
DHT_STORE = "dht.store"
DHT_DELETE = "dht.delete"
DHT_SYNC = "dht.sync"  # anti-entropy: digest of replicated keys
DHT_SYNC_RESP = "dht.sync.resp"  # entries the requester is missing
PEERS = "peers"  # bootstrap: list of known validators

# job lifecycle (reference validator_thread.py:150-161, worker_thread.py:128)
JOB_REQ = "job.req"
JOB_ACCEPT = "job.accept"
JOB_DECLINE = "job.decline"
JOB_UPDATE = "job.update"
JOB_SHUTDOWN = "job.shutdown"
JOB_REPAIR = "job.repair"  # user pulls a replacement worker for a dead stage
STATS_REQUEST = "stats.req"
STATS_RESPONSE = "stats.resp"
REQUEST_WORKERS = "workers.req"
WORKERS = "workers.resp"
PROPOSAL = "proposal"  # contract round: full proposal body for validation
PROPOSAL_VOTE = "proposal.vote"
PROOF_REQ = "proof.req"  # monitor pulls a worker's PoL log for a job
PROOF_RESP = "proof.resp"

# tensor-node layer (reference torch_node.py:119-131)
MODULE = "module"  # ship a stage assignment (plan + checkpoint ref)
MODULE_LOADED = "module.loaded"
FORWARD = "fwd"
FORWARD_RESP = "fwd.resp"
BACKWARD = "bwd"
BACKWARD_RESP = "bwd.resp"
GENERATE = "gen"
GENERATE_RESP = "gen.resp"
TOKEN = "token"
STREAM_END = "stream.end"
# user -> worker: confirmed stop-sequence matches for rows of a streamed
# generate; the worker's compiled chunked decode checks these at chunk
# boundaries and stops early instead of running out its token budget
STREAM_CANCEL = "stream.cancel"
# live slot migration (docs/FAILURE_MODEL.md "Migration & drain"):
# validator → worker DRAIN (shed every live serving slot to a destination
# worker, zero dropped streams); worker → worker MIGRATE (probe the
# destination's resident prefix, then ship a frozen slot's KV pages
# byte-exactly as one bulk TLTS frame)
MIGRATE = "mig"
MIGRATE_RESP = "mig.resp"
DRAIN = "drain"
DRAIN_RESP = "drain.resp"
# disaggregated prefill/decode pools (docs/SERVING.md "Disaggregated
# prefill/decode"): validator → prefill-pool worker, fire-and-forget —
# the decode-pool membership [{id, addr}, ...] the worker hands its
# completed prefills to through the MIGRATE export/stage/adopt path
HANDOFF = "handoff"
# fleet serving (docs/SERVING.md "Fleet serving"): validator → replica
# entry worker, fire-and-forget — the sibling-replica membership
# [{id, addr, job_id}, ...] this worker may drain onto when a DRAIN
# arrives with no explicit destination (the autopilot's rolling deploy)
REPLICA_SET = "replica.set"
PARAMS_REQ = "params.req"
PARAMETERS = "params"
OPTIMIZER = "opt"
OPTIMIZER_RESP = "opt.resp"
TRAIN_MODE = "train.mode"
TRAIN_MODE_ACK = "train.mode.ack"
CHECKPOINT = "ckpt"  # save/restore stage params + optimizer state
CHECKPOINT_RESP = "ckpt.resp"


def pack_header(kind: int, tag: str, payload_len: int) -> bytes:
    tag_b = tag.encode("ascii")
    if payload_len > MAX_FRAME:
        raise ValueError(f"frame too large: {payload_len}")
    return HEADER.pack(MAGIC, VERSION, kind, len(tag_b), payload_len) + tag_b


@dataclass(frozen=True)
class FrameHeader:
    kind: int
    tag_len: int
    payload_len: int


def unpack_header(data: bytes) -> FrameHeader:
    magic, version, kind, tag_len, payload_len = HEADER.unpack(data)
    if magic != MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if version != VERSION:
        raise ProtocolError(f"unsupported version {version}")
    if payload_len > MAX_FRAME:
        raise ProtocolError(f"oversized frame {payload_len}")
    return FrameHeader(kind, tag_len, payload_len)


class ProtocolError(Exception):
    """Malformed or hostile frame."""


def control(tag: str, body: dict) -> tuple[int, str, bytes]:
    return CONTROL, tag, json.dumps(body, separators=(",", ":")).encode()


def parse_control(payload: bytes | memoryview) -> dict:
    return json.loads(bytes(payload))
