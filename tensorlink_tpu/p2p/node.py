"""P2PNode — authenticated asyncio TCP mesh node.

Capability match for the reference's ``Smartnode`` (p2p/smart_node.py):
listener + handshake, bootstrap to seed validators (smart_node.py:1100-1159),
DHT query routing with timeout + reroute (533-577), per-IP rate limiting
(247-250), tagged logging. Redesigned:

- asyncio event loop in a dedicated thread (reference: thread per socket);
  synchronous callers use :meth:`call`.
- Handshake is a 4-step mutual RSA challenge (HELLO→CHALLENGE→PROOF→WELCOME)
  over the single listener socket — no random-number OAEP dance and no "port
  swap" reconnection (reference smart_node.py:786-955).
- Request/response correlation by explicit ``_rid`` ids instead of polling
  shared dicts.

No jax imports here — the networking process stays device-free.
"""

from __future__ import annotations

import asyncio
import secrets
import threading
import time
from pathlib import Path
from typing import Any, Awaitable, Callable

from tensorlink_tpu.core.logging import get_logger
from tensorlink_tpu.crypto import identity as crypto
from tensorlink_tpu.p2p import protocol as proto
from tensorlink_tpu.p2p.connection import Connection
from tensorlink_tpu.p2p.dht import DHT, hash_key
from tensorlink_tpu.p2p.monitor import RateLimiter
from tensorlink_tpu.p2p.reputation import ReputationTracker

Handler = Callable[[Connection, int, str, Any], Awaitable[None]]

# Record prefixes that replicate across validators: job records (repair
# depends on job:{id} surviving the storing validator) and proposal bodies
# (vote lookups). Everything else stays local-first.
REPLICATED_PREFIXES = ("job:", "proposal:")

# total bound on the handshake's on-chain credential check — the RPC
# client's socket timeouts are per-op, so a slow-drip registry endpoint
# needs an overall ceiling (fails CLOSED on expiry)
CREDENTIAL_CHECK_TIMEOUT = 15.0
# cap on concurrently-outstanding credential-check threads (abandoned
# slow-drip checks keep their thread alive past the timeout); at the cap
# further handshakes fail closed immediately
CREDENTIAL_CHECK_MAX_LIVE = 32


class HandshakeError(Exception):
    pass


class P2PNode:
    def __init__(
        self,
        role: str,
        *,
        host: str = "0.0.0.0",
        port: int = 0,
        key_dir: str | Path = "keys",
        local_test: bool = False,
        spill_dir: str | Path | None = None,
        max_connections: int = 256,
        request_timeout: float = 10.0,
        identity_name: str | None = None,
    ):
        self.role = role
        self.local_test = local_test
        self.host = "127.0.0.1" if local_test else host
        self.port = port
        # identity_name separates keypairs for same-role nodes sharing a
        # key_dir (reference duplicate="1" role suffix, tests/conftest.py:114)
        # while the advertised role stays canonical for peer-role routing.
        self.identity = crypto.load_or_create_identity(identity_name or role, key_dir)
        self.node_id = crypto.node_id_from_public_key(self.identity.public_pem)
        self.spill_dir = spill_dir
        self.max_connections = max_connections
        self.request_timeout = request_timeout
        self.log = get_logger(f"p2p.{role}.{self.node_id[:8]}")

        self.connections: dict[str, Connection] = {}  # node_id -> conn
        self.roles: dict[str, str] = {}  # node_id -> role
        self.addresses: dict[str, tuple[str, int]] = {}  # node_id -> (host, port)
        self.dht = DHT(self.node_id, forward=self._dht_forward)
        self.limiter = RateLimiter()
        self.reputation = ReputationTracker()
        # optional Sybil gate (reference smart_node.py:708-739 checks a
        # peer's chain-registered identity before accepting its role):
        # (node_id, role) -> bool, called off-loop (it may do blocking RPC).
        # None = local reputation only.
        self.credential_check: Callable[[str, str], bool] | None = None
        # count of credential-check threads abandoned mid-RPC (slow-drip
        # registry endpoints) — each holds one daemon thread + socket until
        # the RPC's 1 MB read cap runs out; exposed for observability
        self._cred_abandoned = 0  #: guarded by the node event loop
        # outstanding credential-check threads; bounded so hostile traffic
        # from many IPs cannot accumulate dripping threads without limit —
        # incremented on the loop, decremented from the check threads
        self._cred_live = 0  #: guarded by self._cred_lock
        self._cred_lock = threading.Lock()
        self.handlers: dict[str, Handler] = {}
        self.started = threading.Event()
        self.terminate = threading.Event()

        self._server: asyncio.AbstractServer | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._pending: dict[str, asyncio.Future] = {}
        self._pending_conn: dict[str, Connection] = {}  # rid -> conn it rides
        self._conn_tasks: set[asyncio.Task] = set()

        self.register(proto.DHT_GET, self._handle_dht_get)
        self.register(proto.DHT_STORE, self._handle_dht_store)
        self.register(proto.DHT_DELETE, self._handle_dht_delete)
        self.register(proto.DHT_SYNC, self._handle_dht_sync)
        self.register(proto.PEERS, self._handle_peers)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> None:
        """Run the event loop + listener in a dedicated thread."""
        if self._thread:
            return
        ready = threading.Event()

        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            self._loop.run_until_complete(self._start_server())
            ready.set()
            self.started.set()
            try:
                self._loop.run_forever()
            finally:
                self._loop.run_until_complete(self._shutdown())
                self._loop.close()

        self._t0 = time.monotonic()
        self._thread = threading.Thread(target=run, name=f"p2p-{self.role}", daemon=True)
        self._thread.start()
        if not ready.wait(10):
            raise RuntimeError("p2p node failed to start")
        self.log.info("listening on %s:%s id=%s", self.host, self.port, self.node_id[:16])

    def stop(self) -> None:
        if not self._loop:
            return
        self.terminate.set()
        if not self._loop.is_closed():  # idempotent: double-stop is a no-op
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread:
            self._thread.join(timeout=10)
            self._thread = None

    async def _start_server(self) -> None:
        self._server = await asyncio.start_server(
            self._accept, self.host, self.port or None
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def _shutdown(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        for conn in list(self.connections.values()):
            await conn.close()
        for t in list(self._conn_tasks):
            t.cancel()

    def call(self, coro, timeout: float | None = 30.0):
        """Run a coroutine on the node loop from another thread."""
        assert self._loop is not None, "node not started"
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return fut.result(timeout)

    # ------------------------------------------------------------------
    # handshake
    # ------------------------------------------------------------------
    async def _read_frame(self, reader: asyncio.StreamReader) -> tuple[int, str, bytes]:
        head = await reader.readexactly(proto.HEADER_SIZE)
        hdr = proto.unpack_header(head)
        if hdr.payload_len > 1 << 20:
            raise HandshakeError("oversized handshake frame")
        tag = (await reader.readexactly(hdr.tag_len)).decode("ascii")
        payload = await reader.readexactly(hdr.payload_len)
        return hdr.kind, tag, payload

    @staticmethod
    async def _write_frame(writer: asyncio.StreamWriter, tag: str, body: dict) -> None:
        kind, tag, payload = proto.control(tag, body)
        writer.write(proto.pack_header(kind, tag, len(payload)) + payload)
        await writer.drain()

    def _hello_body(self, nonce: str) -> dict:
        return {
            "pub": self.identity.public_pem.decode(),
            "role": self.role,
            "nonce": nonce,
            "port": self.port,
            "id": self.node_id,
        }

    async def _check_credentials(self, node_id: str, role: str) -> None:
        """On-chain (or otherwise external) identity gate: a fresh Sybil key
        starts clean with every validator's LOCAL reputation, so role
        acceptance must also consult the shared registry (reference
        smart_node.py:708-739). Runs in a worker thread — the check is
        typically a blocking RPC — and BEFORE the handshake completes, so
        the refused peer sees a failed handshake on its own side."""
        if self.credential_check is None:
            return
        # one DEDICATED daemon thread per check — not the loop's default
        # executor (abandoned threads there starve the bridge pumps
        # node-wide) and not a small fixed pool (a slow-drip registry
        # endpoint resets the per-socket-op timeout every byte, so a
        # handful of dripping checks would wedge the pool and deny
        # authentication forever). Outstanding threads are CAPPED: at the
        # cap new handshakes fail closed immediately (a wedge now needs
        # that many concurrently dripping checks, with loud warnings the
        # whole way), and each abandoned thread's lifetime is bounded by
        # the RPC's 1 MB response cap.
        with self._cred_lock:
            if self._cred_live >= CREDENTIAL_CHECK_MAX_LIVE:
                self.log.warning(
                    "credential-check concurrency cap (%d) reached — "
                    "refusing handshake with %s (fail closed); registry "
                    "endpoint is likely hostile or down",
                    CREDENTIAL_CHECK_MAX_LIVE, node_id[:12],
                )
                raise HandshakeError(
                    f"credential check for {node_id[:12]} refused: "
                    "checker saturated"
                )
            self._cred_live += 1
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def deliver(cb) -> None:
            try:
                loop.call_soon_threadsafe(cb)
            # tlint: disable=TL005(loop already closed while the node stops — the result is moot)
            except RuntimeError:
                pass  # loop already closed (node stopping) — result moot

        def run_check() -> None:
            try:
                ok = self.credential_check(node_id, role)
            except BaseException as e:  # noqa: BLE001 — deliver, don't die
                deliver(
                    lambda: fut.set_exception(e) if not fut.done() else None
                )
                return
            finally:
                with self._cred_lock:
                    self._cred_live -= 1
            deliver(lambda: fut.set_result(ok) if not fut.done() else None)

        threading.Thread(
            target=run_check, name="cred-check", daemon=True
        ).start()
        try:
            # total bound, not just the RPC's per-socket-op timeout. On
            # expiry the thread is abandoned to finish; the handshake
            # fails CLOSED now.
            ok = await asyncio.wait_for(fut, timeout=CREDENTIAL_CHECK_TIMEOUT)
        except asyncio.TimeoutError:
            self._cred_abandoned += 1
            self.log.warning(
                "credential check for %s exceeded %.0fs — thread abandoned "
                "(%d total); registry endpoint may be hostile or down",
                node_id[:12], CREDENTIAL_CHECK_TIMEOUT, self._cred_abandoned,
            )
            raise HandshakeError(
                f"credential check for {node_id[:12]} timed out"
            ) from None
        except Exception as e:
            raise HandshakeError(
                f"credential check for {node_id[:12]} errored: {e}"
            ) from None
        if not ok:
            raise HandshakeError(
                f"peer {node_id[:12]} role={role} not registered "
                "with the credential registry"
            )

    async def _accept(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        ip = (writer.get_extra_info("peername") or ("?",))[0]
        if not self.limiter.allow(ip):
            self.log.warning("rate-limited %s", ip)
            writer.close()
            return
        if len(self.connections) >= self.max_connections:
            writer.close()
            return
        try:
            kind, tag, payload = await asyncio.wait_for(self._read_frame(reader), 10)
            if tag != proto.HELLO:
                raise HandshakeError(f"expected hello, got {tag}")
            hello = proto.parse_control(payload)
            peer_pub = hello["pub"].encode()
            if not crypto.authenticate_public_key(peer_pub):
                raise HandshakeError("bad public key")
            peer_id = crypto.node_id_from_public_key(peer_pub)
            if not self.reputation.allowed(peer_id):
                # reject before any further protocol steps so the initiator
                # sees a failed handshake, not a connection that dies later
                raise HandshakeError(
                    f"peer {peer_id[:12]} reputation below threshold "
                    f"({self.reputation.score(peer_id):.1f})"
                )
            nonce_b = secrets.token_hex(32)
            await self._write_frame(
                writer,
                proto.CHALLENGE,
                {
                    **self._hello_body(nonce_b),
                    "sig": crypto.sign(self.identity, hello["nonce"].encode()).hex(),
                },
            )
            kind, tag, payload = await asyncio.wait_for(self._read_frame(reader), 10)
            if tag != proto.PROOF:
                raise HandshakeError(f"expected proof, got {tag}")
            proof = proto.parse_control(payload)
            if not crypto.verify(peer_pub, bytes.fromhex(proof["sig"]), nonce_b.encode()):
                raise HandshakeError("bad proof signature")
            # registry gate AFTER the proof: the peer has demonstrated key
            # possession, so an attacker cannot turn unauthenticated HELLOs
            # into blocking chain RPCs (the refused peer still sees a failed
            # handshake — no WELCOME was sent)
            await self._check_credentials(peer_id, hello.get("role", ""))
            await self._write_frame(writer, proto.WELCOME, {"id": self.node_id})
            await self._register_peer(
                reader, writer, peer_pub, hello["role"], ip, int(hello.get("port", 0))
            )
        except (HandshakeError, asyncio.TimeoutError, asyncio.IncompleteReadError,
                ConnectionError, OSError, KeyError, ValueError) as e:
            self.log.warning("handshake with %s failed: %s", ip, e)
            writer.close()

    async def connect(self, host: str, port: int) -> Connection:
        """Outgoing connection + handshake; returns the live Connection."""
        for conn in self.connections.values():
            if self.addresses.get(conn.node_id) == (host, port):
                return conn
        reader, writer = await asyncio.open_connection(host, port)
        try:
            nonce_a = secrets.token_hex(32)
            await self._write_frame(writer, proto.HELLO, self._hello_body(nonce_a))
            kind, tag, payload = await asyncio.wait_for(self._read_frame(reader), 10)
            if tag != proto.CHALLENGE:
                raise HandshakeError(f"expected challenge, got {tag}")
            ch = proto.parse_control(payload)
            peer_pub = ch["pub"].encode()
            if not crypto.authenticate_public_key(peer_pub):
                raise HandshakeError("bad public key")
            if not crypto.verify(peer_pub, bytes.fromhex(ch["sig"]), nonce_a.encode()):
                raise HandshakeError("bad challenge signature")
            await self._check_credentials(
                crypto.node_id_from_public_key(peer_pub), ch.get("role", "")
            )
            await self._write_frame(
                writer,
                proto.PROOF,
                {"sig": crypto.sign(self.identity, ch["nonce"].encode()).hex()},
            )
            kind, tag, payload = await asyncio.wait_for(self._read_frame(reader), 10)
            if tag != proto.WELCOME:
                raise HandshakeError(f"expected welcome, got {tag}")
            return await self._register_peer(
                reader, writer, peer_pub, ch["role"], host, int(ch.get("port", port))
            )
        except Exception:
            writer.close()
            raise

    async def _register_peer(
        self,
        reader,
        writer,
        peer_pub: bytes,
        peer_role: str,
        host: str,
        listen_port: int,
    ) -> Connection:
        node_id = crypto.node_id_from_public_key(peer_pub)
        if node_id == self.node_id:
            raise HandshakeError("connected to self")
        if not self.reputation.allowed(node_id):
            # reputation gate at handshake (reference smart_node.py:681-698):
            # the peer proved its key, and that key's history disqualifies it
            raise HandshakeError(
                f"peer {node_id[:12]} reputation below threshold "
                f"({self.reputation.score(node_id):.1f})"
            )
        if self.reputation.score(node_id) < 0:
            # clean handshakes only help a tarnished peer crawl back toward
            # neutral — a reconnect loop must not FARM positive credit to
            # absorb later misbehavior (goodwill comes from completed jobs)
            self.reputation.record(node_id, "handshake_ok")
        old = self.connections.get(node_id)
        if old is not None:
            await old.close()
        conn = Connection(reader, writer, spill_dir=self.spill_dir)
        conn.node_id = node_id
        conn.role = peer_role
        conn.pub_pem = peer_pub
        self.connections[node_id] = conn
        self.roles[node_id] = peer_role
        if listen_port:
            self.addresses[node_id] = (host, listen_port)
        self.dht.add_node(node_id)
        task = asyncio.ensure_future(conn.run(self._on_frame))
        self._conn_tasks.add(task)
        task.add_done_callback(lambda t: (self._conn_tasks.discard(t), self._on_disconnect(conn)))
        self.log.info("peer up %s role=%s %s:%s", node_id[:8], peer_role, host, listen_port)
        if self.role == "validator" and peer_role == "validator":
            # validators anti-entropy-sync replicated records on connect so a
            # late-joining validator serves jobs stored before it existed
            t = asyncio.ensure_future(self.sync_dht(conn))
            self._conn_tasks.add(t)
            t.add_done_callback(self._conn_tasks.discard)
        return conn

    def _on_disconnect(self, conn: Connection) -> None:
        if conn.node_id and self.connections.get(conn.node_id) is conn:
            del self.connections[conn.node_id]
            self.log.info("peer down %s", conn.node_id[:8])
        # fail in-flight requests riding this connection immediately —
        # otherwise callers wait out the full request timeout on a peer
        # that is already gone (and repair paths never learn the cause)
        for rid, c in list(self._pending_conn.items()):
            if c is conn:
                fut = self._pending.pop(rid, None)
                self._pending_conn.pop(rid, None)
                if fut is not None and not fut.done():
                    fut.set_exception(
                        ConnectionError(
                            f"no connection to {conn.node_id[:12] if conn.node_id else '?'}"
                            " (peer dropped mid-request)"
                        )
                    )

    # ------------------------------------------------------------------
    # dispatch + request/response
    # ------------------------------------------------------------------
    def register(self, tag: str, handler: Handler) -> None:
        self.handlers[tag] = handler

    async def _on_frame(self, conn: Connection, kind: int, tag: str, payload) -> None:
        body = proto.parse_control(payload) if kind == proto.CONTROL else payload
        if isinstance(body, dict) and body.get("_resp"):
            fut = self._pending.pop(body.get("_rid"), None)
            if fut is not None and not fut.done():
                fut.set_result(body)
            # a reply whose requester timed out must never re-enter the
            # request handlers (a late PEERS reply would otherwise ping-pong)
            return
        handler = self.handlers.get(tag)
        if handler is None:
            conn.ghosts += 1
            self.reputation.record(conn.node_id or "", "ghost")
            self.log.debug("ghost frame tag=%s from %s", tag, conn.node_id and conn.node_id[:8])
            return
        try:
            await handler(conn, kind, tag, body)
        except Exception:
            self.log.exception("handler %s failed", tag)

    async def request(
        self, conn: Connection, tag: str, body: dict, timeout: float | None = None
    ) -> dict:
        """Send a control message and await the correlated reply."""
        rid = secrets.token_hex(8)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        self._pending_conn[rid] = conn
        try:
            await conn.send_control(tag, {**body, "_rid": rid})
            return await asyncio.wait_for(fut, timeout or self.request_timeout)
        finally:
            self._pending.pop(rid, None)
            self._pending_conn.pop(rid, None)

    @staticmethod
    async def respond(conn: Connection, tag: str, request_body: dict, body: dict) -> None:
        await conn.send_control(
            tag, {**body, "_rid": request_body.get("_rid"), "_resp": True}
        )

    # ------------------------------------------------------------------
    # DHT wiring
    # ------------------------------------------------------------------
    async def _dht_forward(self, peer_id: str, key: str, hops: int = 0) -> Any:
        conn = self.connections.get(peer_id)
        if conn is None:
            raise ConnectionError(f"no connection to {peer_id[:8]}")
        reply = await self.request(conn, proto.DHT_GET, {"key": key, "hops": hops})
        if reply.get("value") is None:
            return None
        return reply.get("value"), reply.get("ts")

    async def _handle_dht_get(self, conn, kind, tag, body) -> None:
        key = body["key"]
        hops = int(body.get("hops", 0))
        value = self.dht.get_local(key)
        if value is None and hops < 2:
            pool = [c for c in self.validator_ids() if c != conn.node_id]
            if pool:
                value = await self.dht.query(key, route_pool=pool, hops=hops + 1)
        # origin ts rides the reply so the requester's cache keeps LWW
        # semantics (an untimestamped cache write would beat tombstones)
        await self.respond(
            conn, proto.DHT_GET_RESP, body,
            {"key": key, "value": value, "ts": self.dht.updated_at.get(key)},
        )

    async def _fanout_validators(
        self, tag: str, body: dict, exclude: str | None = None
    ) -> None:
        """Best-effort control-frame push to every connected validator."""
        for nid in self.validator_ids():
            if nid == exclude:
                continue
            peer = self.connections.get(nid)
            if peer is not None:
                try:
                    await peer.send_control(tag, body)
                # tlint: disable=TL005(best-effort fanout — a dead validator peer re-syncs via anti-entropy)
                except (ConnectionError, OSError):
                    pass

    async def _handle_dht_store(self, conn, kind, tag, body) -> None:
        key, ts = body["key"], body.get("ts")
        if ts is None:
            # replicated records are LWW-ordered by origin ts; an
            # untimestamped REMOTE store has no place in that order and
            # could otherwise clear tombstones or overwrite newer records
            # (store()'s "local write always wins" rule is for this node's
            # own writes, not a peer omitting ts). Reject for replicated
            # prefixes; plain keys keep the legacy behavior.
            if not key.startswith(REPLICATED_PREFIXES):
                self.dht.store(key, body["value"])
            return
        # timestamped stores apply last-writer-wins, and a validator relays
        # accepted replicated records to its other validator peers — the
        # origin only reaches validators IT is connected to, so single-homed
        # workers/users still get multi-validator replication. Equal/older
        # timestamps are rejected, which terminates the relay.
        accepted = self.dht.merge({key: {"value": body["value"], "ts": float(ts)}})
        if accepted and self.role == "validator" and key.startswith(REPLICATED_PREFIXES):
            await self._fanout_validators(proto.DHT_STORE, body, exclude=conn.node_id)

    async def _handle_dht_delete(self, conn, kind, tag, body) -> None:
        key, ts = body["key"], body.get("ts")
        changed = self.dht.delete(key, ts=float(ts) if ts is not None else None)
        # relay replicated deletes exactly like stores — the tombstone makes
        # re-application a no-op, which terminates the flood
        if (
            changed and ts is not None and self.role == "validator"
            and key.startswith(REPLICATED_PREFIXES)
        ):
            await self._fanout_validators(proto.DHT_DELETE, body, exclude=conn.node_id)

    async def _handle_dht_sync(self, conn, kind, tag, body) -> None:
        """Anti-entropy: peer sent its replicated-key digest; reply with the
        records it is missing or holds stale (last-writer-wins on ts)."""
        entries = self.dht.missing_for(
            body.get("digest", {}), REPLICATED_PREFIXES
        )
        await self.respond(conn, proto.DHT_SYNC_RESP, body, {"entries": entries})

    async def sync_dht(self, conn: Connection) -> list[str]:
        """Pull replicated records this node lacks from ``conn``'s peer.
        Runs from both ends of a validator-validator connection, so one pull
        each way yields a full bidirectional sync."""
        try:
            reply = await self.request(
                conn, proto.DHT_SYNC,
                {"digest": self.dht.digest(REPLICATED_PREFIXES)},
            )
        except (ConnectionError, OSError, asyncio.TimeoutError, TimeoutError):
            return []
        accepted = self.dht.merge(reply.get("entries", {}))
        if accepted:
            self.log.info(
                "dht sync from %s: %d records", conn.node_id[:8], len(accepted)
            )
        return accepted

    async def _handle_peers(self, conn, kind, tag, body) -> None:
        peers = [
            {"id": nid, "role": self.roles.get(nid), "addr": list(self.addresses.get(nid, ()))}
            for nid in self.connections
            if self.roles.get(nid) == "validator" and nid in self.addresses
        ]
        await self.respond(conn, proto.PEERS, body, {"peers": peers})

    def validator_ids(self) -> list[str]:
        return [nid for nid, r in self.roles.items() if r == "validator" and nid in self.connections]

    async def dht_query(self, key: str, timeout: float = 3.0) -> Any:
        return await self.dht.query(key, route_pool=self.validator_ids(), timeout=timeout)

    async def dht_store_global(self, key: str, value: Any) -> None:
        """Store locally and push to connected validators, stamped with the
        origin write time so replicas and later anti-entropy syncs resolve
        conflicts last-writer-wins (the reference's replication is a TODO,
        dht.py:135-137)."""
        self.dht.store(key, value)
        await self._fanout_validators(
            proto.DHT_STORE,
            {"key": key, "value": value, "ts": self.dht.updated_at[key]},
        )

    async def dht_delete_global(self, key: str) -> None:
        """Delete locally (tombstoned) and push the delete to connected
        validators so replicas drop their copies too — without this, a
        shutdown job's record would outlive the job on every replica and be
        resurrected by the next anti-entropy sync."""
        self.dht.delete(key)
        await self._fanout_validators(
            proto.DHT_DELETE, {"key": key, "ts": self.dht.tombstones.get(key)}
        )

    # ------------------------------------------------------------------
    # bootstrap
    # ------------------------------------------------------------------
    async def bootstrap(self, seeds: list[tuple[str, int]], retries: int = 3) -> int:
        """Connect to seed validators; learn + connect to their validator
        peers (reference smart_node.py:1100-1159, retry loop
        worker_thread.py:189-197). Returns number of live connections."""
        for attempt in range(retries):
            for host, port in seeds:
                if (host, port) == (self.host, self.port):
                    continue
                try:
                    conn = await self.connect(host, port)
                    reply = await self.request(conn, proto.PEERS, {})
                    for peer in reply.get("peers", []):
                        pid, addr = peer.get("id"), peer.get("addr")
                        if pid and addr and pid != self.node_id and pid not in self.connections:
                            try:
                                await self.connect(addr[0], addr[1])
                            # tlint: disable=TL005(bootstrap keeps trying other advertised peers; the outer seed loop logs)
                            except (OSError, HandshakeError, asyncio.TimeoutError):
                                pass
                except (OSError, HandshakeError, asyncio.TimeoutError, ConnectionError) as e:
                    self.log.warning("bootstrap %s:%s failed: %s", host, port, e)
            if self.connections or not seeds:
                break
            await asyncio.sleep(1.5 * (attempt + 1))
        return len(self.connections)

    # ------------------------------------------------------------------
    # stats
    # ------------------------------------------------------------------
    def status(self) -> dict:
        return {
            "id": self.node_id,
            "role": self.role,
            "addr": [self.host, self.port],
            "peers": {
                nid[:16]: {
                    "role": self.roles.get(nid),
                    "latency_s": c.latency_s,
                    "sent": c.bytes_sent,
                    "recv": c.bytes_received,
                    "ghosts": c.ghosts,
                }
                for nid, c in self.connections.items()
            },
            "dht_keys": len(self.dht.store_map),
            "uptime_s": time.monotonic() - getattr(self, "_t0", time.monotonic()),
        }


__all__ = ["P2PNode", "HandshakeError", "hash_key"]
