"""P2P substrate — asyncio TCP mesh with authenticated peers and a DHT.

TPU-native redesign of the reference's networking layer
(p2p/smart_node.py, p2p/connection.py, p2p/dht.py, p2p/monitor.py):

- One asyncio event loop per node instead of one thread per socket.
- Length-prefixed binary frames instead of sentinel-terminated chunk scans
  (reference connection.py:67 scans for ``EOT_CHAR``).
- Single listener socket; no handshake "port swap" (reference
  smart_node.py:786-955) — asyncio multiplexes connections natively.
- This package never imports jax: the network process must stay free of
  device runtimes (same reason the reference keeps torch out of its
  networking process, nodes/nodes.py:139-147).
"""

from tensorlink_tpu.p2p.connection import Connection
from tensorlink_tpu.p2p.dht import DHT
from tensorlink_tpu.p2p.monitor import RateLimiter
from tensorlink_tpu.p2p.node import P2PNode

__all__ = ["Connection", "DHT", "RateLimiter", "P2PNode"]
