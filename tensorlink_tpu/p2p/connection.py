"""One authenticated peer link = one asyncio ``Connection``.

Replaces the reference's thread-per-socket ``Connection`` (p2p/connection.py:
recv loop scanning for a sentinel, writer threads spilling >20 MB to
``tmp/streamed_data_*`` files). Here:

- frames are length-prefixed (protocol.py) and read with ``readexactly``;
- bulk frames above ``SPILL_THRESHOLD`` stream straight to a spill file and
  are delivered as a path, never materialized in RAM;
- writes are serialized by an asyncio lock instead of a file lock;
- an idle ping fires after ``idle_ping_s`` (reference: 30 s PING health
  check, connection.py:333-353).

A received frame is delivered to the owner's ``on_frame(conn, kind, tag,
payload)`` coroutine; ``payload`` is ``bytes`` or a ``pathlib.Path`` for
spilled bulk frames.
"""

from __future__ import annotations

import asyncio
import os
import tempfile
import time
import uuid
from pathlib import Path
from typing import Awaitable, Callable

from tensorlink_tpu.core import faults
from tensorlink_tpu.core.logging import get_logger
from tensorlink_tpu.p2p import protocol as proto

log = get_logger("p2p.conn")

_IO_CHUNK = 4 << 20  # stream spill files in 4 MiB slices


class Connection:
    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        *,
        spill_dir: str | Path | None = None,
        idle_ping_s: float = 30.0,
    ):
        self.reader = reader
        self.writer = writer
        self.spill_dir = Path(spill_dir or tempfile.gettempdir()) / "tlnk_spill"
        self.idle_ping_s = idle_ping_s
        self.node_id: str | None = None  # set after handshake
        self.role: str | None = None
        self.pub_pem: bytes | None = None
        self.last_seen = time.monotonic()
        self.latency_s: float | None = None
        self.ghosts = 0  # unexpected-message counter (reference connection.py:60)
        # the write lock serializes frame writes AND the sent counter —
        # concurrent senders would interleave header/payload on the wire
        self.bytes_sent = 0  #: guarded by self._wlock
        self.bytes_received = 0
        self.closed = asyncio.Event()
        self._wlock = asyncio.Lock()
        self._pump_task: asyncio.Task | None = None
        self._ping_task: asyncio.Task | None = None
        self._ping_sent_at: float | None = None

    # -- identity ----------------------------------------------------------
    @property
    def peername(self) -> tuple[str, int]:
        peer = self.writer.get_extra_info("peername")
        return (peer[0], peer[1]) if peer else ("?", 0)

    def __repr__(self):
        nid = (self.node_id or "?")[:8]
        return f"<Connection {nid} {self.peername[0]}:{self.peername[1]}>"

    # -- sending -----------------------------------------------------------
    async def send_control(self, tag: str, body: dict) -> None:
        kind, tag, payload = proto.control(tag, body)
        await self.send_frame(kind, tag, payload)

    async def send_frame(self, kind: int, tag: str, payload: bytes) -> None:
        dup = False
        if faults.ENABLED:  # fault site "p2p.send": drop / delay / dup
            act = faults.inject("p2p.send", tag)
            if act == "drop":
                return
            if isinstance(act, tuple):
                await asyncio.sleep(act[1])
            dup = act == "dup"
        header = proto.pack_header(kind, tag, len(payload))
        async with self._wlock:
            for _ in range(2 if dup else 1):
                self.writer.write(header)
                self.writer.write(payload)
                await self.writer.drain()
                self.bytes_sent += len(header) + len(payload)

    async def send_file(self, kind: int, tag: str, path: str | Path, *, delete: bool = True) -> None:
        """Stream a file as one bulk frame without loading it (reference
        ``send_from_file``, connection.py:164-213)."""
        path = Path(path)
        size = path.stat().st_size
        header = proto.pack_header(kind, tag, size)
        async with self._wlock:
            self.writer.write(header)
            with path.open("rb") as f:
                while chunk := f.read(_IO_CHUNK):
                    self.writer.write(chunk)
                    await self.writer.drain()
            self.bytes_sent += proto.HEADER_SIZE + len(tag) + size
        if delete:
            path.unlink(missing_ok=True)

    # -- receiving ---------------------------------------------------------
    async def run(
        self, on_frame: Callable[["Connection", int, str, bytes | Path], Awaitable[None]]
    ) -> None:
        """Read frames until EOF, dispatching each to ``on_frame``."""
        self._ping_task = asyncio.ensure_future(self._idle_ping())
        try:
            while True:
                try:
                    head = await self.reader.readexactly(proto.HEADER_SIZE)
                    hdr = proto.unpack_header(head)
                    tag = (await self.reader.readexactly(hdr.tag_len)).decode("ascii")
                    payload: bytes | Path
                    if hdr.kind == proto.BULK and hdr.payload_len > proto.SPILL_THRESHOLD:
                        payload = await self._recv_to_spill(hdr.payload_len)
                    else:
                        payload = await self._recv_exact(hdr.payload_len)
                except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
                    break
                self.bytes_received += proto.HEADER_SIZE + hdr.tag_len + hdr.payload_len
                self.last_seen = time.monotonic()
                deliveries = 1
                if faults.ENABLED:  # fault site "connection.frame"
                    act = faults.inject("connection.frame", tag)
                    if act == "drop":
                        if isinstance(payload, Path):
                            # spilled frames are consumed on delivery — a
                            # dropped one must still release its temp file
                            payload.unlink(missing_ok=True)
                        continue
                    if isinstance(act, tuple):
                        await asyncio.sleep(act[1])
                    if act == "dup" and not isinstance(payload, Path):
                        # spilled frames are consumed (unlinked) on first
                        # delivery — only in-memory payloads can duplicate
                        deliveries = 2
                if tag == proto.PING:
                    await self.send_control(proto.PONG, {})
                    continue
                if tag == proto.PONG:
                    if self._ping_sent_at is not None:
                        self.latency_s = time.monotonic() - self._ping_sent_at
                        self._ping_sent_at = None
                    continue
                for _ in range(deliveries):
                    await on_frame(self, hdr.kind, tag, payload)
        except proto.ProtocolError as e:
            log.warning("protocol error from %s: %s", self.peername, e)
        finally:
            await self.close()

    async def _recv_exact(self, n: int) -> bytes:
        if n == 0:
            return b""
        return await self.reader.readexactly(n)

    async def _recv_to_spill(self, n: int) -> Path:
        self.spill_dir.mkdir(parents=True, exist_ok=True)
        path = self.spill_dir / f"rx_{uuid.uuid4().hex}.tlts"
        remaining = n
        with path.open("wb") as f:
            while remaining > 0:
                chunk = await self.reader.read(min(_IO_CHUNK, remaining))
                if not chunk:
                    raise proto.ProtocolError("EOF mid bulk frame")
                f.write(chunk)
                remaining -= len(chunk)
        return path

    # -- health ------------------------------------------------------------
    async def _idle_ping(self) -> None:
        try:
            while not self.closed.is_set():
                await asyncio.sleep(self.idle_ping_s / 2)
                idle = time.monotonic() - self.last_seen
                if idle >= self.idle_ping_s:
                    self._ping_sent_at = time.monotonic()
                    try:
                        await self.send_control(proto.PING, {})
                    except (ConnectionError, OSError):
                        break
        # tlint: disable=TL005(task cancellation is the ping loop's normal shutdown signal)
        except asyncio.CancelledError:
            pass

    async def ping(self) -> float | None:
        """Measure round-trip latency; returns seconds or None on timeout."""
        self._ping_sent_at = time.monotonic()
        await self.send_control(proto.PING, {})
        for _ in range(50):
            await asyncio.sleep(0.02)
            if self.latency_s is not None and self._ping_sent_at is None:
                return self.latency_s
        return None

    async def close(self) -> None:
        if self.closed.is_set():
            return
        self.closed.set()
        if self._ping_task:
            self._ping_task.cancel()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        # tlint: disable=TL005(closing an already-dead transport)
        except (ConnectionError, OSError):
            pass


def cleanup_spill(spill_dir: str | Path, max_age_s: float = 3600) -> int:
    """Delete stale spill files; returns count removed."""
    d = Path(spill_dir)
    if not d.is_dir():
        return 0
    now = time.time()
    n = 0
    for p in d.glob("rx_*.tlts"):
        try:
            # tlint: disable=TL004(st_mtime is epoch — wall clock is the only comparable base)
            if now - p.stat().st_mtime > max_age_s:
                p.unlink()
                n += 1
        # tlint: disable=TL005(spill sweep races the consumer unlinking its own file)
        except OSError:
            pass
    return n


def spill_write(obj_bytes: bytes, spill_dir: str | Path) -> Path:
    d = Path(spill_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"tx_{uuid.uuid4().hex}.tlts"
    with path.open("wb") as f:
        f.write(obj_bytes)
    return path


__all__ = ["Connection", "cleanup_spill", "spill_write"]


if os.name == "nt":  # pragma: no cover
    raise RuntimeError("tensorlink_tpu.p2p requires a POSIX platform")
