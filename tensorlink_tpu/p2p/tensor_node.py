"""TensorNode — array-aware protocol layer on top of :class:`P2PNode`.

Capability match for the reference's ``Torchnode`` (p2p/torch_node.py): the
wire verbs FORWARD/BACKWARD/GENERATE/MODULE/PARAMETERS/OPTIMIZER/TOKEN
(torch_node.py:119-131), tensor payloads, and module shipping. Redesigned:

- Tensor payloads are single TLTS frames (core/serialization.py) carrying an
  envelope ``{tag-meta, arrays}`` — the reference concatenates raw tensor
  bytes and JSON with fixed offsets (torch_node.py:825-836).
- Request/response correlation rides the same ``_rid`` scheme as control
  messages, so a FORWARD and its FORWARD_RESP pair up without per-module
  polling queues keyed ``(n_batch, n_micro, module_id)``
  (torch_node.py:664-718).
- Work that must reach the ML process is posted to ``self.work`` (an
  ``mp.Queue`` installed by the node runner) instead of being parked in
  shared memory for a 1 kHz poll loop (torch_node.py:838-851).

Still no jax here — arrays stay numpy until they cross into the ML process.
"""

from __future__ import annotations

import asyncio
from pathlib import Path

from tensorlink_tpu.core import serialization as ser
from tensorlink_tpu.p2p import protocol as proto
from tensorlink_tpu.p2p.connection import Connection
from tensorlink_tpu.p2p.node import P2PNode


class TensorNode(P2PNode):
    """P2PNode + tensor envelopes. Subclassed by the role servers."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.work = None  # mp.Queue installed by the runner (net -> ML)
        # roles without an ML token consumer (users — the synchronous driver
        # drains stream_buffers via next_tokens) set this False so the work
        # queue cannot grow unboundedly
        self.forward_tokens_to_ml = True
        self.stream_buffers: dict[str, asyncio.Queue] = {}  # stream_id -> tokens
        self.register(proto.TOKEN, self._handle_token)
        self.register(proto.STREAM_END, self._handle_token)

    # ------------------------------------------------------------------
    # envelopes
    # ------------------------------------------------------------------
    async def _on_frame(self, conn: Connection, kind: int, tag: str, payload) -> None:
        if kind == proto.BULK:
            if isinstance(payload, Path):
                body = ser.decode_from_file(payload)
                payload.unlink(missing_ok=True)
            else:
                body = ser.decode(payload, copy=True)
            if isinstance(body, dict) and body.get("_resp"):
                fut = self._pending.pop(body.get("_rid"), None)
                if fut is not None and not fut.done():
                    fut.set_result(body)
                return
            handler = self.handlers.get(tag)
            if handler is None:
                conn.ghosts += 1
                return
            try:
                await handler(conn, kind, tag, body)
            except Exception:
                self.log.exception("bulk handler %s failed", tag)
            return
        await super()._on_frame(conn, kind, tag, payload)

    async def send_tensor(self, conn: Connection, tag: str, body: dict) -> None:
        """Ship a dict that may contain numpy arrays as one bulk frame."""
        blob = ser.encode(body)
        await conn.send_frame(proto.BULK, tag, blob)

    async def tensor_request(
        self, conn: Connection, tag: str, body: dict, timeout: float | None = None
    ) -> dict:
        """Correlated array-carrying request; reply may be control or bulk."""
        import secrets

        rid = secrets.token_hex(8)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        self._pending_conn[rid] = conn
        try:
            await self.send_tensor(conn, tag, {**body, "_rid": rid})
            return await asyncio.wait_for(fut, timeout or self.request_timeout)
        finally:
            self._pending.pop(rid, None)
            self._pending_conn.pop(rid, None)

    async def tensor_respond(
        self, conn: Connection, tag: str, request_body: dict, body: dict
    ) -> None:
        await self.send_tensor(
            conn, tag, {**body, "_rid": request_body.get("_rid"), "_resp": True}
        )

    # ------------------------------------------------------------------
    # token streaming (reference torch_node.py:543-560,
    # validator_thread.py:211-265)
    # ------------------------------------------------------------------
    async def send_token(
        self, conn: Connection, stream_id: str, token_ids: list[int], done: bool = False
    ) -> None:
        tag = proto.STREAM_END if done else proto.TOKEN
        await conn.send_control(tag, {"stream": stream_id, "tokens": token_ids})

    async def _handle_token(self, conn, kind, tag, body) -> None:
        q = self.stream_buffers.setdefault(body["stream"], asyncio.Queue())
        await q.put((body.get("tokens", []), tag == proto.STREAM_END))
        if self.work is not None and self.forward_tokens_to_ml:
            self.post_work("token", {
                "stream": body["stream"],
                "tokens": body.get("tokens", []),
                "done": tag == proto.STREAM_END,
            })

    async def next_tokens(
        self, stream_id: str, timeout: float = 30.0
    ) -> tuple[list[int], bool]:
        """Await the next token batch for a stream; (tokens, done)."""
        q = self.stream_buffers.setdefault(stream_id, asyncio.Queue())
        return await asyncio.wait_for(q.get(), timeout)

    def drop_stream(self, stream_id: str) -> None:
        self.stream_buffers.pop(stream_id, None)

    # ------------------------------------------------------------------
    # ML-process handoff
    # ------------------------------------------------------------------
    def post_work(self, kind: str, item: dict) -> None:
        """Queue an event for the ML process (non-blocking, drops never)."""
        if self.work is not None:
            self.work.put((kind, item))


__all__ = ["TensorNode"]
