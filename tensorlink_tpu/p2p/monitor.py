"""Per-IP connection rate limiting.

Capability match for the reference's ``ConnectionMonitor`` (p2p/monitor.py:
sliding-minute attempt counter, 600 s block after 5 attempts/min,
smart_node.py:247-250).
"""

from __future__ import annotations

import time
from collections import deque


class RateLimiter:
    def __init__(self, max_per_minute: int = 5, block_s: float = 600.0):
        self.max_per_minute = max_per_minute
        self.block_s = block_s
        self._attempts: dict[str, deque[float]] = {}
        self._blocked_until: dict[str, float] = {}

    def allow(self, ip: str) -> bool:
        """Record an attempt from ``ip``; False if it is rate-limited."""
        now = time.monotonic()
        self._gc(now)
        until = self._blocked_until.get(ip)
        if until is not None:
            if now < until:
                return False
            del self._blocked_until[ip]
        dq = self._attempts.setdefault(ip, deque())
        while dq and now - dq[0] > 60.0:
            dq.popleft()
        dq.append(now)
        if len(dq) > self.max_per_minute:
            self._blocked_until[ip] = now + self.block_s
            return False
        return True

    def _gc(self, now: float) -> None:
        """Drop idle IPs so the tables don't grow with unique source count
        for the process lifetime."""
        stale = [
            ip for ip, dq in self._attempts.items() if not dq or now - dq[-1] > 120.0
        ]
        for ip in stale:
            del self._attempts[ip]
        expired = [ip for ip, t in self._blocked_until.items() if now >= t]
        for ip in expired:
            del self._blocked_until[ip]

    def is_blocked(self, ip: str) -> bool:
        until = self._blocked_until.get(ip)
        return until is not None and time.monotonic() < until

    def unblock(self, ip: str) -> None:
        self._blocked_until.pop(ip, None)
        self._attempts.pop(ip, None)


__all__ = ["RateLimiter"]
