"""Kademlia-style DHT: XOR-distance buckets, replicated store, routing.

Capability match for the reference's DHT (p2p/dht.py): 256 buckets with
exponentially growing capacity (dht.py:13-16) and local-first ``query`` that
forwards misses to the XOR-nearest *validator* peer (dht.py:110-121). Keys
are 64-hex sha256 ids (or prefixed record names like ``job:{id}``); values
are JSON-able dicts.

Where the reference leaves replication as a TODO (dht.py:135-137) —
meaning a validator death loses the job records repair depends on — stores
here carry an origin timestamp and replicate two ways: writers fan
``DHT_STORE`` out to their connected validators (p2p/node.py
``dht_store_global``), and validators anti-entropy-sync replicated key
prefixes with each other on connect (``digest``/``merge`` +
``P2PNode.sync_dht``), so records survive the storing validator and reach
validators that join later. Conflicts resolve last-writer-wins on the
origin timestamp.

Async redesign: ``query`` awaits a remote answer with timeout + reroute
(reference polls with a 3 s timeout then re-routes, smart_node.py:533-577).
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from typing import Any, Awaitable, Callable

ID_BITS = 256
# deletion markers survive this long so anti-entropy can't resurrect a
# deleted record from a replica that missed the delete; long-dead tombstones
# age out to bound memory
TOMBSTONE_TTL_S = 7 * 86400.0


def hash_key(data: bytes | str) -> str:
    if isinstance(data, str):
        data = data.encode()
    return hashlib.sha256(data).hexdigest()


def _key_int(key: str) -> int:
    """Record keys may be prefixed names (``job:{id}``) rather than 64-hex
    node ids — map them into the id space by hashing, so XOR routing works
    for any key (a raw int() would crash the first routed query for a
    prefixed key that misses locally)."""
    try:
        return int(key, 16)
    except ValueError:
        return int(hash_key(key), 16)


def xor_distance(a: str, b: str) -> int:
    return _key_int(a) ^ _key_int(b)


def bucket_index(a: str, b: str) -> int:
    d = xor_distance(a, b)
    return d.bit_length() - 1 if d else 0


class Bucket:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self.keys: list[str] = []

    def add(self, key: str) -> bool:
        if key in self.keys:
            return True
        if len(self.keys) >= self.capacity:
            return False
        self.keys.append(key)
        return True

    def remove(self, key: str) -> None:
        if key in self.keys:
            self.keys.remove(key)


class DHT:
    """Local routing table + key/value store.

    ``forward`` — async callback ``(peer_id, key) -> value | None`` used when
    a queried key is not local; the node wires it to a DHT_GET round-trip.
    """

    def __init__(
        self,
        node_id: str,
        *,
        forward: Callable[[str, str], Awaitable[Any]] | None = None,
        base_capacity: int = 2,
    ):
        self.node_id = node_id
        self.store_map: dict[str, Any] = {}
        self.updated_at: dict[str, float] = {}
        self.tombstones: dict[str, float] = {}  # key -> deletion ts
        # bucket i covers distances [2^i, 2^(i+1)); capacity grows with range
        self.buckets = [
            Bucket(base_capacity * max(1, 2 ** (i // 32))) for i in range(ID_BITS)
        ]
        self.forward = forward

    # -- routing table -----------------------------------------------------
    def add_node(self, key: str) -> bool:
        if key == self.node_id:
            return False
        return self.buckets[bucket_index(self.node_id, key)].add(key)

    def remove_node(self, key: str) -> None:
        self.buckets[bucket_index(self.node_id, key)].remove(key)

    def known_nodes(self) -> list[str]:
        return [k for b in self.buckets for k in b.keys]

    def nearest(self, key: str, candidates: list[str] | None = None, n: int = 1) -> list[str]:
        pool = candidates if candidates is not None else self.known_nodes()
        return sorted(pool, key=lambda c: xor_distance(key, c))[:n]

    # -- store -------------------------------------------------------------
    def store(self, key: str, value: Any, ts: float | None = None) -> None:
        """``ts`` is the origin write time; replicated stores pass it along
        so last-writer-wins comparisons use one clock per record. A
        timestamped store loses to BOTH a newer tombstone and a newer live
        record (e.g. a fanout write that merged while a ``query`` was
        awaiting a lagging peer's stale copy); an untimestamped store is a
        fresh local write and always wins."""
        t = time.time() if ts is None else ts
        dead = self.tombstones.get(key)
        if dead is not None:
            # tlint: disable=TL004(LWW origin timestamps are cross-node epoch stamps)
            if ts is not None and t <= dead:
                return  # the record was deleted after this write happened
            del self.tombstones[key]  # genuinely re-created
        # tlint: disable=TL004(LWW origin timestamps are cross-node epoch stamps)
        if ts is not None and self.updated_at.get(key, -1.0) > t:
            return  # a newer live record wins
        self.store_map[key] = value
        self.updated_at[key] = t

    def delete(self, key: str, ts: float | None = None) -> bool:
        """Remove a record, leaving a tombstone so replication can't bring
        it back. Returns True if local state changed (used by the relay to
        terminate the delete flood)."""
        t = time.time() if ts is None else ts
        # tlint: disable=TL004(LWW origin timestamps are cross-node epoch stamps)
        if ts is not None and self.updated_at.get(key, -1.0) > t:
            return False  # a newer write beats this replicated delete
        existed = self.store_map.pop(key, None) is not None
        self.updated_at.pop(key, None)
        prev = self.tombstones.get(key, -1.0)
        # tlint: disable=TL004(LWW origin timestamps are cross-node epoch stamps)
        if t > prev:
            self.tombstones[key] = t
        return existed or t > prev  # tlint: disable=TL004(LWW epoch stamps)

    def get_local(self, key: str) -> Any:
        return self.store_map.get(key)

    # -- replication (anti-entropy) ----------------------------------------
    def _known_ts(self, key: str) -> float:
        return max(
            self.updated_at.get(key, -1.0), self.tombstones.get(key, -1.0)
        )

    def digest(self, prefixes: tuple[str, ...]) -> dict[str, float]:
        """``key -> origin ts`` for every local record (and live tombstone)
        under ``prefixes``."""
        now = time.time()
        for k in [
            # tlint: disable=TL004(tombstone TTL compares cross-node epoch stamps)
            k for k, t in self.tombstones.items() if now - t > TOMBSTONE_TTL_S
        ]:
            del self.tombstones[k]
        d = {
            k: self.updated_at.get(k, 0.0)
            for k in self.store_map
            if k.startswith(prefixes)
        }
        for k, t in self.tombstones.items():
            if k.startswith(prefixes):
                d[k] = t
        return d

    def missing_for(
        self, their_digest: dict[str, float], prefixes: tuple[str, ...]
    ) -> dict[str, dict]:
        """Entries the peer lacks or holds stale: ``key -> {value, ts}`` for
        live records, ``{deleted: True, ts}`` for tombstones."""
        out: dict[str, dict] = {}
        for k, ts in self.digest(prefixes).items():
            if their_digest.get(k, -1.0) < ts:
                if k in self.store_map:
                    out[k] = {"value": self.store_map[k], "ts": ts}
                else:
                    out[k] = {"deleted": True, "ts": ts}
        return out

    def merge(self, entries: dict[str, dict]) -> list[str]:
        """Apply sync entries last-writer-wins; returns the keys accepted."""
        accepted = []
        for k, e in entries.items():
            ts = float(e.get("ts", 0.0))
            if self._known_ts(k) < ts:
                if e.get("deleted"):
                    self.delete(k, ts=ts)
                else:
                    self.store(k, e.get("value"), ts=ts)
                accepted.append(k)
        return accepted

    # -- query -------------------------------------------------------------
    async def query(
        self,
        key: str,
        *,
        route_pool: list[str] | None = None,
        timeout: float = 3.0,
        max_retries: int = 3,
        hops: int = 0,
    ) -> Any:
        """Local lookup, then forward to XOR-nearest peers in ``route_pool``
        (normally the connected validators), rerouting on timeout. ``hops``
        rides along on the wire so a chain of misses terminates instead of
        cycling between validators.

        ``forward`` returns ``(value, origin_ts)`` (or a bare value from
        legacy/fake forwards); remote answers cache with the ORIGIN
        timestamp so a stale copy fetched from a lagging peer can't outrank
        newer writes or resurrect a tombstoned record."""
        if key in self.store_map:
            return self.store_map[key]
        if self.forward is None or not route_pool:
            return None
        tried: set[str] = set()
        for _ in range(max_retries):
            remaining = [p for p in route_pool if p not in tried]
            if not remaining:
                return None
            peer = self.nearest(key, remaining)[0]
            tried.add(peer)
            try:
                result = await asyncio.wait_for(
                    self.forward(peer, key, hops), timeout
                )
            # tlint: disable=TL005(the continue IS the reroute — the next nearest peer is tried)
            except (asyncio.TimeoutError, ConnectionError, OSError):
                continue
            if result is None:
                continue
            value, ts = (
                result if isinstance(result, tuple) else (result, None)
            )
            if value is not None:
                if ts is not None:
                    self.store(key, value, ts=float(ts))
                    # a tombstone newer than the fetched copy rejects it
                    if key not in self.store_map:
                        return None
                else:
                    self.store(key, value)
                return value
        return None


__all__ = ["DHT", "Bucket", "hash_key", "xor_distance", "bucket_index"]
