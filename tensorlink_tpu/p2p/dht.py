"""Kademlia-style DHT: XOR-distance buckets, local store, validator routing.

Capability match for the reference's DHT (p2p/dht.py): 256 buckets with
exponentially growing capacity (dht.py:13-16), local-first ``query`` that
forwards misses to the XOR-nearest *validator* peer (dht.py:110-121), and a
local-only ``store`` (replication is the same TODO the reference carries,
dht.py:135-137). Keys are 64-hex sha256 ids; values are JSON-able dicts.

Async redesign: ``query`` awaits a remote answer with timeout + reroute
(reference polls with a 3 s timeout then re-routes, smart_node.py:533-577).
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from typing import Any, Awaitable, Callable

ID_BITS = 256


def hash_key(data: bytes | str) -> str:
    if isinstance(data, str):
        data = data.encode()
    return hashlib.sha256(data).hexdigest()


def xor_distance(a: str, b: str) -> int:
    return int(a, 16) ^ int(b, 16)


def bucket_index(a: str, b: str) -> int:
    d = xor_distance(a, b)
    return d.bit_length() - 1 if d else 0


class Bucket:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self.keys: list[str] = []

    def add(self, key: str) -> bool:
        if key in self.keys:
            return True
        if len(self.keys) >= self.capacity:
            return False
        self.keys.append(key)
        return True

    def remove(self, key: str) -> None:
        if key in self.keys:
            self.keys.remove(key)


class DHT:
    """Local routing table + key/value store.

    ``forward`` — async callback ``(peer_id, key) -> value | None`` used when
    a queried key is not local; the node wires it to a DHT_GET round-trip.
    """

    def __init__(
        self,
        node_id: str,
        *,
        forward: Callable[[str, str], Awaitable[Any]] | None = None,
        base_capacity: int = 2,
    ):
        self.node_id = node_id
        self.store_map: dict[str, Any] = {}
        self.updated_at: dict[str, float] = {}
        # bucket i covers distances [2^i, 2^(i+1)); capacity grows with range
        self.buckets = [
            Bucket(base_capacity * max(1, 2 ** (i // 32))) for i in range(ID_BITS)
        ]
        self.forward = forward

    # -- routing table -----------------------------------------------------
    def add_node(self, key: str) -> bool:
        if key == self.node_id:
            return False
        return self.buckets[bucket_index(self.node_id, key)].add(key)

    def remove_node(self, key: str) -> None:
        self.buckets[bucket_index(self.node_id, key)].remove(key)

    def known_nodes(self) -> list[str]:
        return [k for b in self.buckets for k in b.keys]

    def nearest(self, key: str, candidates: list[str] | None = None, n: int = 1) -> list[str]:
        pool = candidates if candidates is not None else self.known_nodes()
        return sorted(pool, key=lambda c: xor_distance(key, c))[:n]

    # -- store -------------------------------------------------------------
    def store(self, key: str, value: Any) -> None:
        self.store_map[key] = value
        self.updated_at[key] = time.time()

    def delete(self, key: str) -> bool:
        self.updated_at.pop(key, None)
        return self.store_map.pop(key, None) is not None

    def get_local(self, key: str) -> Any:
        return self.store_map.get(key)

    # -- query -------------------------------------------------------------
    async def query(
        self,
        key: str,
        *,
        route_pool: list[str] | None = None,
        timeout: float = 3.0,
        max_retries: int = 3,
        hops: int = 0,
    ) -> Any:
        """Local lookup, then forward to XOR-nearest peers in ``route_pool``
        (normally the connected validators), rerouting on timeout. ``hops``
        rides along on the wire so a chain of misses terminates instead of
        cycling between validators."""
        if key in self.store_map:
            return self.store_map[key]
        if self.forward is None or not route_pool:
            return None
        tried: set[str] = set()
        for _ in range(max_retries):
            remaining = [p for p in route_pool if p not in tried]
            if not remaining:
                return None
            peer = self.nearest(key, remaining)[0]
            tried.add(peer)
            try:
                value = await asyncio.wait_for(
                    self.forward(peer, key, hops), timeout
                )
            except (asyncio.TimeoutError, ConnectionError, OSError):
                continue
            if value is not None:
                self.store(key, value)
                return value
        return None


__all__ = ["DHT", "Bucket", "hash_key", "xor_distance", "bucket_index"]
