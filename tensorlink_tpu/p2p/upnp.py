"""UPnP-IGD port mapping, stdlib-only.

Public-network mode: when a node runs behind a NAT router, map its listen
port on the gateway so other peers can reach it (reference
smart_node.py:1200-1312, which uses the miniupnpc C extension — not in this
image, and the protocol is simple enough that a dependency buys nothing):

1. SSDP discovery — M-SEARCH datagram to 239.255.255.250:1900, parse the
   ``LOCATION`` header of the first InternetGatewayDevice response.
2. Fetch the device description XML; find the WANIPConnection (or
   WANPPPConnection) service's controlURL.
3. SOAP POST ``AddPortMapping`` / ``DeletePortMapping`` /
   ``GetExternalIPAddress`` to that URL.

Everything network-touching takes explicit addresses so tests can stand up
a fake IGD on 127.0.0.1 (no multicast, no real router).
"""

from __future__ import annotations

import socket
import urllib.request
from dataclasses import dataclass
from urllib.parse import urljoin, urlparse
from xml.etree import ElementTree

from tensorlink_tpu.core.logging import get_logger

log = get_logger("p2p.upnp")

SSDP_ADDR = ("239.255.255.250", 1900)
IGD_SEARCH_TARGET = "urn:schemas-upnp-org:device:InternetGatewayDevice:1"
WAN_SERVICES = (
    "urn:schemas-upnp-org:service:WANIPConnection:1",
    "urn:schemas-upnp-org:service:WANPPPConnection:1",
)


class UPnPError(Exception):
    pass


@dataclass
class Gateway:
    control_url: str
    service_type: str


def discover_location(
    timeout: float = 2.0, ssdp_addr: tuple[str, int] = SSDP_ADDR
) -> str:
    """SSDP M-SEARCH; returns the LOCATION url of the first IGD response."""
    msg = (
        "M-SEARCH * HTTP/1.1\r\n"
        f"HOST: {ssdp_addr[0]}:{ssdp_addr[1]}\r\n"
        'MAN: "ssdp:discover"\r\n'
        "MX: 2\r\n"
        f"ST: {IGD_SEARCH_TARGET}\r\n\r\n"
    ).encode()
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.settimeout(timeout)
        s.sendto(msg, ssdp_addr)
        try:
            while True:
                data, _ = s.recvfrom(65507)
                for line in data.decode(errors="replace").splitlines():
                    if line.lower().startswith("location:"):
                        return line.split(":", 1)[1].strip()
        except socket.timeout:
            raise UPnPError("no IGD responded to SSDP discovery") from None


def fetch_gateway(location: str, timeout: float = 5.0) -> Gateway:
    """Parse the IGD device description; return the WAN*Connection control
    endpoint."""
    with urllib.request.urlopen(location, timeout=timeout) as r:
        tree = ElementTree.fromstring(r.read())
    # namespace-agnostic walk: {urn:...}serviceType etc.
    for svc in tree.iter():
        if not svc.tag.endswith("service"):
            continue
        stype = curl = None
        for child in svc:
            if child.tag.endswith("serviceType"):
                stype = (child.text or "").strip()
            elif child.tag.endswith("controlURL"):
                curl = (child.text or "").strip()
        if stype in WAN_SERVICES and curl:
            return Gateway(control_url=urljoin(location, curl), service_type=stype)
    raise UPnPError(f"no WAN*Connection service in {location}")


def _soap(gw: Gateway, action: str, args: dict[str, str], timeout: float = 5.0) -> str:
    body = "".join(f"<{k}>{v}</{k}>" for k, v in args.items())
    envelope = (
        '<?xml version="1.0"?>'
        '<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/" '
        's:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">'
        f'<s:Body><u:{action} xmlns:u="{gw.service_type}">{body}</u:{action}>'
        "</s:Body></s:Envelope>"
    ).encode()
    req = urllib.request.Request(
        gw.control_url,
        data=envelope,
        headers={
            "Content-Type": 'text/xml; charset="utf-8"',
            "SOAPAction": f'"{gw.service_type}#{action}"',
        },
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.read().decode(errors="replace")
    except urllib.error.HTTPError as e:  # IGD SOAP faults are HTTP 500
        raise UPnPError(f"{action} failed: {e.read().decode(errors='replace')[:200]}")


def local_ip_towards(gateway_url: str) -> str:
    """The local interface IP the gateway routes back to (what goes in
    NewInternalClient)."""
    host = urlparse(gateway_url).hostname or "8.8.8.8"
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.connect((host, 80))
        return s.getsockname()[0]


def add_port_mapping(
    gw: Gateway,
    external_port: int,
    internal_port: int,
    internal_ip: str,
    protocol: str = "TCP",
    description: str = "tensorlink-tpu",
    lease_s: int = 0,
) -> None:
    _soap(gw, "AddPortMapping", {
        "NewRemoteHost": "",
        "NewExternalPort": str(external_port),
        "NewProtocol": protocol,
        "NewInternalPort": str(internal_port),
        "NewInternalClient": internal_ip,
        "NewEnabled": "1",
        "NewPortMappingDescription": description,
        "NewLeaseDuration": str(lease_s),
    })


def delete_port_mapping(gw: Gateway, external_port: int, protocol: str = "TCP") -> None:
    _soap(gw, "DeletePortMapping", {
        "NewRemoteHost": "",
        "NewExternalPort": str(external_port),
        "NewProtocol": protocol,
    })


def get_external_ip(gw: Gateway) -> str:
    resp = _soap(gw, "GetExternalIPAddress", {})
    tree = ElementTree.fromstring(resp)
    for el in tree.iter():
        if el.tag.endswith("NewExternalIPAddress"):
            return (el.text or "").strip()
    raise UPnPError("no NewExternalIPAddress in response")


class PortMapper:
    """Best-effort lifecycle wrapper: map on start, unmap on stop. Failure
    to find a gateway degrades to a warning — matching the reference, where
    UPnP failure doesn't kill the node (smart_node.py:1272-1286)."""

    def __init__(self, *, ssdp_addr: tuple[str, int] = SSDP_ADDR, timeout: float = 2.0):
        self.ssdp_addr = ssdp_addr
        self.timeout = timeout
        self.gateway: Gateway | None = None
        self.external_ip: str | None = None
        self.mapped: list[tuple[int, str]] = []

    def map_port(self, port: int, protocol: str = "TCP") -> str | None:
        """Map external ``port`` -> this host's ``port``. Returns the
        external IP, or None if no gateway is reachable."""
        try:
            if self.gateway is None:
                loc = discover_location(self.timeout, self.ssdp_addr)
                self.gateway = fetch_gateway(loc, self.timeout)
            ip = local_ip_towards(self.gateway.control_url)
            add_port_mapping(self.gateway, port, port, ip, protocol)
            self.mapped.append((port, protocol))
            self.external_ip = get_external_ip(self.gateway)
            log.info("upnp: mapped %s/%s -> %s:%s (external %s)",
                     port, protocol, ip, port, self.external_ip)
            return self.external_ip
        except (UPnPError, OSError, ElementTree.ParseError) as e:
            log.warning("upnp: port mapping unavailable: %s", e)
            return None

    def close(self) -> None:
        if self.gateway is None:
            return
        for port, protocol in self.mapped:
            try:
                delete_port_mapping(self.gateway, port, protocol)
            # tlint: disable=TL005(unmapping at close — the gateway may already be gone; mappings expire anyway)
            except (UPnPError, OSError):
                pass
        self.mapped.clear()


__all__ = [
    "Gateway", "PortMapper", "UPnPError", "add_port_mapping",
    "delete_port_mapping", "discover_location", "fetch_gateway",
    "get_external_ip",
]
