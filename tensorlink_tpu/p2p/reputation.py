"""Per-node reputation scoring, checked at handshake.

Capability match for the reference's handshake reputation gate
(smart_node.py:681-698, which consults on-chain validator credentials and a
local score before accepting a peer). Off-chain design: every node keeps a
local, decaying score per peer id fed by observed behavior — ghost frames,
job failures/completions, planning-spam — and refuses the handshake when a
peer's score falls below the ban threshold. Scores decay toward neutral so
a flaky-but-reformed node can return (and a griefer can't bank goodwill
forever).
"""

from __future__ import annotations

import math
import time

# event -> score delta. Magnitudes are relative to BAN_THRESHOLD: one failed
# job is forgivable, three in a half-life window are not; ghost frames only
# ban at sustained-flood volume.
# tlint: disable=TL006(read-only constant table — never mutated at runtime)
EVENT_WEIGHTS = {
    "handshake_ok": 0.5,
    "ghost": -1.0,  # unparseable/unexpected frame
    "spam": -8.0,  # rate-limit violation after authentication
    "job_completed": 5.0,
    "job_failed": -10.0,  # died mid-job / failed to deliver
    "worker_dropped": -3.0,  # liveness replacement — may be a network blip,
    # so three in a day (half-life) must NOT cross BAN_THRESHOLD the way
    # three verified job failures do
    "proof_failed": -12.0,  # PoL log that didn't verify (platform/proofs.py)
    "proposal_mismatch": -15.0,  # contract-round hash that didn't validate
}
BAN_THRESHOLD = -25.0
HALF_LIFE_S = 24 * 3600.0
MAX_SCORE = 50.0  # cap banked goodwill


class ReputationTracker:
    def __init__(
        self,
        *,
        threshold: float = BAN_THRESHOLD,
        half_life_s: float = HALF_LIFE_S,
    ):
        self.threshold = threshold
        self.half_life_s = half_life_s
        self._scores: dict[str, float] = {}
        self._at: dict[str, float] = {}

    def _decayed(self, node_id: str, now: float) -> float:
        s = self._scores.get(node_id)
        if s is None:
            return 0.0
        dt = max(now - self._at.get(node_id, now), 0.0)
        return s * math.pow(0.5, dt / self.half_life_s)

    def record(self, node_id: str, event: str, weight: float | None = None) -> float:
        """Apply an observed event; returns the new score."""
        if not node_id:
            return 0.0
        now = time.time()
        w = EVENT_WEIGHTS[event] if weight is None else weight
        s = min(self._decayed(node_id, now) + w, MAX_SCORE)
        self._scores[node_id] = s
        self._at[node_id] = now
        return s

    def score(self, node_id: str) -> float:
        return self._decayed(node_id, time.time())

    def allowed(self, node_id: str) -> bool:
        return self.score(node_id) > self.threshold

    # -- persistence (rides the keeper snapshot) ------------------------
    def to_json(self) -> dict:
        now = time.time()
        return {
            nid: {"score": round(self._decayed(nid, now), 3), "ts": now}
            for nid in self._scores
            if abs(self._decayed(nid, now)) > 0.05  # drop ~neutral entries
        }

    def load_json(self, data: dict) -> None:
        for nid, e in (data or {}).items():
            try:
                self._scores[nid] = float(e["score"])
                self._at[nid] = float(e["ts"])
            # tlint: disable=TL005(malformed persisted entry — skip it, keep the rest of the snapshot)
            except (KeyError, TypeError, ValueError):
                continue


__all__ = ["ReputationTracker", "EVENT_WEIGHTS", "BAN_THRESHOLD"]
