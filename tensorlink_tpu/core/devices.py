"""Bounded accelerator acquisition.

The reference's worker reports GPU memory straight from CUDA calls
(ml/utils.py:127 get_gpu_memory) — when the driver is wedged, its process
blocks. The TPU analogue is worse: JAX backend init against a tunneled or
dead TPU runtime can hang *indefinitely* inside ``jax.local_devices()``
(the PJRT client constructor blocks, no timeout). Production paths —
``DistributedWorker.capacity()``, ``WorkerNode.start()``, the CLI — must
never do that.

:func:`acquire_devices` probes the inherited backend in a **subprocess**
with a deadline before letting the calling process initialize JAX. If the
probe fails or times out, the calling process is switched to the CPU
backend (env + config + factory neutralization, so nothing later can hang
on the dead runtime) and a loud warning is logged. The result is cached:
one probe per process.
"""

from __future__ import annotations

import os
import subprocess
import sys
from dataclasses import dataclass, field

from .logging import get_logger

log = get_logger("core.devices")

# env var that arms a sitecustomize hook force-registering a tunneled TPU
# backend; must be scrubbed when falling back to CPU (see tests/conftest.py)
_TUNNEL_HOOK_VAR = "PALLAS_AXON_POOL_IPS"


@dataclass
class DeviceProbe:
    platform: str
    n_devices: int
    degraded: bool = False  # True when we fell back to CPU
    error: str = ""
    devices: list = field(default_factory=list)


_CACHED: DeviceProbe | None = None


def _jax_initialized() -> bool:
    """True if this process already has a live JAX backend (in which case
    device calls are safe and a probe would be wasted work)."""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge as xb

        return bool(xb._backend_lock and xb._backends)
    except Exception:
        return False


def _force_cpu_inprocess() -> None:
    """Point this process (and its future children) at the CPU backend and
    make any still-registered accelerator factory fail fast instead of
    hanging (keeps factory keys — known_platforms() derives from them)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop(_TUNNEL_HOOK_VAR, None)
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    # tlint: disable=TL005(best-effort compat shim over jax internals; failure means the version does not need it)
    except Exception:
        pass
    try:
        from jax._src import xla_bridge as xb

        def _disabled_factory(*a, **k):
            raise RuntimeError("accelerator backend disabled after failed probe")

        for name in [n for n in xb._backend_factories if n != "cpu"]:
            entry = xb._backend_factories[name]
            if callable(entry):
                xb._backend_factories[name] = _disabled_factory
            elif hasattr(entry, "factory"):
                entry.factory = _disabled_factory
    # tlint: disable=TL005(best-effort neutralization of private backend factories; absent internals = nothing to disarm)
    except Exception:
        pass


def probe_backend(deadline: float = 60.0) -> tuple[str, int] | None:
    """Initialize the inherited JAX backend in a subprocess with a deadline.

    Returns ``(platform, n_local_devices)`` or None on failure/timeout."""
    code = (
        "import jax; d = jax.local_devices(); "
        "print('PROBE=' + d[0].platform + ':' + str(len(d)))"
    )
    try:
        p = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=deadline,
        )
    except (subprocess.TimeoutExpired, OSError):
        return None
    if p.returncode != 0:
        return None
    for ln in p.stdout.splitlines():
        if ln.startswith("PROBE="):
            plat, _, n = ln[len("PROBE="):].partition(":")
            try:
                return plat, int(n)
            except ValueError:
                return None
    return None


def acquire_devices(deadline: float = 60.0) -> DeviceProbe:
    """Bounded replacement for ``jax.local_devices()`` in production paths.

    Never hangs: either the inherited backend comes up within ``deadline``
    (probed out-of-process first, so a wedged runtime can't block us), or
    the process is switched to CPU with ``degraded=True``.
    """
    global _CACHED
    if _CACHED is not None:
        return _CACHED

    env_plat = os.environ.get("JAX_PLATFORMS", "")
    if _jax_initialized():
        import jax

        devs = jax.local_devices()
        _CACHED = DeviceProbe(devs[0].platform, len(devs), devices=devs)
        return _CACHED

    if env_plat == "cpu" and not os.environ.get(_TUNNEL_HOOK_VAR):
        # CPU pinned and no tunnel hook armed — init is safe and fast.
        import jax

        devs = jax.local_devices()
        _CACHED = DeviceProbe("cpu", len(devs), devices=devs)
        return _CACHED

    res = probe_backend(deadline)
    if res is None:
        log.warning(
            "accelerator backend failed to initialize within %.0fs "
            "(JAX_PLATFORMS=%r) — falling back to CPU; this worker will "
            "advertise CPU capacity only",
            deadline,
            env_plat,
        )
        _force_cpu_inprocess()
        import jax

        devs = jax.local_devices()
        _CACHED = DeviceProbe(
            "cpu",
            len(devs),
            degraded=True,
            error=f"backend init exceeded {deadline:.0f}s deadline",
            devices=devs,
        )
        return _CACHED

    plat, _n = res
    import jax

    devs = jax.local_devices()
    _CACHED = DeviceProbe(plat, len(devs), devices=devs)
    return _CACHED


def reset_probe_cache() -> None:
    """Test hook."""
    global _CACHED
    _CACHED = None
