"""End-to-end request tracing + the engine flight recorder.

**Tracing.** The API server mints one trace id per HTTP request (echoed
as ``X-Request-Id``); the id rides the GENERATE / session-op / MIGRATE
wire frames, and every hop records *spans* — host-side timing records
(queue-wait, admission, per-prefill-chunk, first-token, decode,
freeze/export/stage/adopt) — into its process-local :class:`Tracer`.
Spans recorded on a remote worker ride its responses back (the
``trace`` field next to the serving snapshot) and are :meth:`ingested
<Tracer.ingest>` into the validator's tracer, so a stream migrated
between workers stitches spans from BOTH under one trace id, queryable
at ``GET /trace/<rid>``.

Hot-path contract (the reason this is a module and not a logging
sprinkle): spans are recorded only at boundaries the host already
synchronizes (the per-chunk boundary in the slot engine, admission, the
migration verbs). Recording is a ``time.monotonic()`` read plus a dict
append under a short lock — no device sync, no compiled programs, and
with no trace id on a request the engine skips the calls entirely
(bench-measured disabled-mode overhead).

Span timestamps: ``dur_ms`` comes from ``time.monotonic`` pairs on one
host (drift-free). ``ts`` is a wall-clock epoch anchor recorded ONCE per
span for cross-worker ordering/joining only — it is never subtracted or
compared for durations (tlint TL004 discipline).

**Flight recorder.** A bounded per-engine ring of per-step records
(occupied slots, prefill grants, tokens emitted, page occupancy,
preemptions), appended at the same per-chunk boundary, dumped on engine
error — chaos-test postmortems read data instead of print archaeology.
"""

from __future__ import annotations

import contextvars
import itertools
import secrets
import threading
import time
from collections import OrderedDict, deque

# active trace id for log joining (core/logging.py json mode): set by the
# code driving a request on the CURRENT thread (generate_api entry, the
# API handler); contextvars keep thread/task isolation for free
current_trace: contextvars.ContextVar[str] = contextvars.ContextVar(
    "tlink_trace", default=""
)


def mint_trace_id() -> str:
    """A fresh request/trace id (also the ``X-Request-Id`` echo)."""
    return secrets.token_hex(8)


class Tracer:
    """Bounded per-process span store keyed by trace id.

    One instance per process (:func:`get_tracer`); several in-process
    nodes (the test clusters run every node's ML thread in one process)
    share it, so every span carries its recording ``site`` (node id /
    engine tag) and a process-unique ``sid`` — :meth:`ingest` dedups on
    ``sid`` so a span that arrives both locally and over the wire lands
    once."""

    def __init__(self, max_traces: int = 512, max_spans: int = 256):
        self.max_traces = int(max_traces)
        self.max_spans = int(max_spans)
        self._lock = threading.Lock()
        self._traces: OrderedDict[str, list[dict]] = OrderedDict()  #: guarded by self._lock
        self._sid = itertools.count(1)
        # process-unique sid prefix: two processes ingesting each other's
        # spans must never collide on (prefix, n)
        self._tag = secrets.token_hex(4)

    # -- recording -------------------------------------------------------
    def record(
        self,
        trace_id: str,
        name: str,
        *,
        site: str = "",
        dur_s: float | None = None,
        **attrs,
    ) -> None:
        """Append one span. ``dur_s`` is a monotonic-pair duration
        measured by the caller (None = instantaneous event)."""
        if not trace_id:
            return
        span = {
            "sid": f"{self._tag}:{next(self._sid)}",
            "name": str(name),
            "site": str(site),
            # wall anchor for cross-worker ordering/log joining ONLY —
            # durations always come from the monotonic pair in dur_ms
            "ts": time.time(),
        }
        if dur_s is not None:
            span["dur_ms"] = round(float(dur_s) * 1e3, 4)
        if attrs:
            span.update(attrs)
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is None:
                spans = []
                self._traces[trace_id] = spans
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)  # LRU-ish: oldest out
            if len(spans) < self.max_spans:
                spans.append(span)

    class _SpanCtx:
        __slots__ = ("tracer", "trace_id", "name", "site", "attrs", "_t0")

        def __init__(self, tracer, trace_id, name, site, attrs):
            self.tracer = tracer
            self.trace_id = trace_id
            self.name = name
            self.site = site
            self.attrs = attrs

        def __enter__(self):
            self._t0 = time.monotonic()
            return self

        def __exit__(self, *exc):
            self.tracer.record(
                self.trace_id, self.name, site=self.site,
                dur_s=time.monotonic() - self._t0, **self.attrs,
            )
            return False

    def span(self, trace_id: str, name: str, *, site: str = "", **attrs):
        """Context manager measuring a monotonic duration around a block
        (records nothing when ``trace_id`` is empty — record() gates)."""
        return Tracer._SpanCtx(self, trace_id, name, site, attrs)

    # -- merge / query ---------------------------------------------------
    def ingest(self, trace_id: str, spans: list[dict]) -> int:
        """Merge spans that arrived over the wire (a worker's response).
        Dedups on ``sid`` — duplicated frames / in-process double-sight
        (local record + wire echo) land once. Returns spans added."""
        if not trace_id or not spans:
            return 0
        added = 0
        with self._lock:
            mine = self._traces.get(trace_id)
            if mine is None:
                mine = []
                self._traces[trace_id] = mine
                while len(self._traces) > self.max_traces:
                    self._traces.popitem(last=False)
            seen = {s.get("sid") for s in mine}
            for s in spans:
                if not isinstance(s, dict) or s.get("sid") in seen:
                    continue
                if len(mine) >= self.max_spans:
                    break
                mine.append(dict(s))
                seen.add(s.get("sid"))
                added += 1
        return added

    def collect(self, trace_id: str) -> list[dict]:
        """All spans recorded/ingested for a trace (ts-ordered copy)."""
        with self._lock:
            spans = list(self._traces.get(trace_id, ()))
        return sorted(spans, key=lambda s: s.get("ts", 0.0))

    def known(self, trace_id: str) -> bool:
        with self._lock:
            return trace_id in self._traces

    def reset(self) -> None:
        """Drop every stored trace (tests / bench isolation)."""
        with self._lock:
            self._traces.clear()


_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process-global tracer: the API server, locally-hosted engines
    and the DistributedModel ingest side all share it, which is what
    makes ``GET /trace/<rid>`` one lookup."""
    return _TRACER


class FlightRecorder:
    """Bounded ring of per-engine-step records — the postmortem buffer.

    The engine appends one record per ``step_chunk`` boundary (already a
    host sync point; the append is a deque op). On engine error the ring
    is dumped (``last_dump``) so a chaos failure ships its final N steps
    of slot/page state with the exception instead of losing them."""

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=self.capacity)  #: guarded by self._lock
        self._step = itertools.count(1)
        self.last_dump: dict | None = None  #: guarded by self._lock

    def record(self, **fields) -> None:
        rec = {"step": next(self._step), **fields}
        with self._lock:
            self._ring.append(rec)

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def dump(self, error: BaseException | None = None) -> dict:
        """Snapshot the ring (with the triggering error) and remember it
        on ``last_dump`` for tests/operators to query after teardown."""
        with self._lock:
            out = {
                "error": (
                    f"{type(error).__name__}: {error}" if error else None
                ),
                "n_records": len(self._ring),
                "records": list(self._ring),
            }
            self.last_dump = out
        return out


__all__ = [
    "FlightRecorder",
    "Tracer",
    "current_trace",
    "get_tracer",
    "mint_trace_id",
]
