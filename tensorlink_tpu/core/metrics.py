"""Unified metrics registry with Prometheus text exposition.

The serving stack's telemetry used to live in ad-hoc counter dicts
stitched through one ``/stats`` blob (``ContinuousEngine.stats``, the
scheduler's ``_ClassStats``, the batchers' loose ints). This module is
the typed replacement: every counter/gauge/histogram is registered once,
``/stats`` keys are *derived* from the registry (byte-compatible — the
test-pinned key set did not move), and the same registry renders as
Prometheus text exposition for the validator's ``GET /metrics``.

Threading contract: metric OBJECTS are cheap namespaced cells, not
synchronized abstractions. Counters follow the single-writer discipline
of the code that owns them (the engine's driver thread, or writes under
the engine lock); readers see int/float snapshots whose worst-case skew
is one increment — exactly the guarantee the old dicts gave. Histograms
take a tiny internal lock because ``observe`` and ``render`` may race
across threads (API thread vs driver).

Exposition grouping: one process may hold several registries (one per
hosted model's engine, one for the API server). :func:`render_prometheus`
merges them into a single valid exposition — HELP/TYPE emitted once per
family, per-registry constant labels (e.g. ``model="tiny"``) applied to
every sample.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Callable, Iterable, Mapping

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")
_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")

# default histogram buckets: latency seconds, log-ish spaced — wide
# enough for queue waits on an overloaded CPU host and tight enough for
# TPU-step-scale observations
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def sanitize_metric_name(raw: str) -> str:
    """Best-effort mapping of an arbitrary snapshot key to a legal
    Prometheus metric name."""
    name = _SANITIZE_RE.sub("_", str(raw))
    if not name or not _NAME_RE.match(name):
        name = "_" + name
    return name


class Counter:
    """Monotonic counter. ``inc`` only; writers follow the owner's
    single-writer/lock discipline (see module docstring)."""

    __slots__ = ("name", "help", "labels", "_value")

    def __init__(self, name: str, help: str, labels: Mapping[str, str]):
        self.name = name
        self.help = help
        self.labels = dict(labels)
        self._value = 0

    def inc(self, n: int | float = 1) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        self._value += n

    @property
    def value(self) -> int | float:
        return self._value

    # counters compare like the plain ints they replaced, so the
    # pre-registry test pins (`stats.preempted == 1`) stay byte-valid
    def __int__(self) -> int:
        return int(self._value)

    def __float__(self) -> float:
        return float(self._value)

    def __eq__(self, other):
        if isinstance(other, (int, float)):
            return self._value == other
        return NotImplemented

    def __lt__(self, other):
        return self._value < other

    def __le__(self, other):
        return self._value <= other

    def __gt__(self, other):
        return self._value > other

    def __ge__(self, other):
        return self._value >= other

    __hash__ = object.__hash__

    def samples(self) -> "list[tuple[str, dict, float]]":
        return [(self.name, self.labels, self._value)]


class Gauge:
    """Settable instantaneous value, or a callback gauge (``fn``) read at
    collection time — the shape occupancy/free-list metrics want."""

    __slots__ = ("name", "help", "labels", "_value", "_fn")

    def __init__(
        self,
        name: str,
        help: str,
        labels: Mapping[str, str],
        fn: Callable[[], float] | None = None,
    ):
        self.name = name
        self.help = help
        self.labels = dict(labels)
        self._value = 0.0
        self._fn = fn

    def set(self, v: float) -> None:
        if self._fn is not None:
            raise ValueError(f"gauge {self.name} is callback-backed")
        self._value = v

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                # a collection-time probe must never take /metrics down
                return float("nan")
        return self._value

    def samples(self) -> "list[tuple[str, dict, float]]":
        return [(self.name, self.labels, self.value)]


class Histogram:
    """Fixed-bucket cumulative histogram (Prometheus semantics:
    ``_bucket{le=...}`` counts observations <= bound, plus ``_sum`` and
    ``_count``)."""

    __slots__ = ("name", "help", "labels", "buckets", "_counts", "_sum",
                 "_count", "_lock")

    def __init__(
        self,
        name: str,
        help: str,
        labels: Mapping[str, str],
        buckets: Iterable[float] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.help = help
        self.labels = dict(labels)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")
        self._counts = [0] * len(self.buckets)  #: guarded by self._lock
        self._sum = 0.0  #: guarded by self._lock
        self._count = 0  #: guarded by self._lock
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._sum += v
            self._count += 1
            # per-bucket counts; samples() cumulates once at render time
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    break

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def samples(self) -> "list[tuple[str, dict, float]]":
        out: list[tuple[str, dict, float]] = []
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            out.append(
                (f"{self.name}_bucket", {**self.labels, "le": _fmt(b)}, cum)
            )
        out.append((f"{self.name}_bucket", {**self.labels, "le": "+Inf"}, total))
        out.append((f"{self.name}_sum", self.labels, s))
        out.append((f"{self.name}_count", self.labels, total))
        return out


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


# tlint: disable=TL006(read-only type-name table — never mutated at runtime)
_TYPES = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}


class MetricsRegistry:
    """A namespace of typed metrics. One registry per subsystem instance
    (engine, scheduler shares the engine's, API server owns its own)."""

    def __init__(self):
        self._metrics: dict[tuple[str, tuple], object] = {}  #: guarded by self._lock
        self._families: dict[str, tuple[type, str]] = {}  #: guarded by self._lock
        self._lock = threading.Lock()

    # -- registration ----------------------------------------------------
    def _register(self, cls, name: str, help: str, labels, **kw):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        labels = dict(labels or {})
        for k in labels:
            if not _LABEL_RE.match(k):
                raise ValueError(f"invalid label name {k!r}")
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            fam = self._families.get(name)
            if fam is not None and fam[0] is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as {fam[0].__name__}"
                )
            existing = self._metrics.get(key)
            if existing is not None:
                return existing
            m = cls(name, help, labels, **kw)
            self._metrics[key] = m
            self._families.setdefault(name, (cls, help))
            return m

    def counter(self, name: str, help: str, **labels) -> Counter:
        return self._register(Counter, name, help, labels)

    def gauge(
        self, name: str, help: str,
        fn: Callable[[], float] | None = None, **labels,
    ) -> Gauge:
        return self._register(Gauge, name, help, labels, fn=fn)

    def histogram(
        self, name: str, help: str,
        buckets: Iterable[float] = DEFAULT_BUCKETS, **labels,
    ) -> Histogram:
        return self._register(Histogram, name, help, labels, buckets=buckets)

    # -- collection ------------------------------------------------------
    def collect(self) -> "list[object]":
        with self._lock:
            return list(self._metrics.values())

    def family_meta(self) -> dict[str, tuple[str, str]]:
        """name -> (prom type, help)"""
        with self._lock:
            return {
                n: (_TYPES[cls], help)
                for n, (cls, help) in self._families.items()
            }

    def render(self, extra_labels: Mapping[str, str] | None = None) -> str:
        return render_prometheus([(extra_labels or {}, self)])


def snapshot_gauges(
    registry: MetricsRegistry,
    snapshot: Mapping[str, object],
    *,
    prefix: str = "tlink_snapshot_",
    help: str = "remote serving-snapshot value",
    skip: tuple = ("prefix_digest", "host_tier_digest"),
) -> None:
    """Flatten a remote engine's serving snapshot (the dict riding
    GENERATE_RESP) into gauges on ``registry`` — how /metrics exposes an
    engine whose registry lives in another process. Non-numeric leaves
    are skipped; nested dicts flatten with ``_``-joined keys. ``skip``
    names subtrees that must never become gauges — the prefix-cache
    digest's keys are CONTENT HASHES, so flattening it would mint an
    unbounded, never-collected metric family (one per chain ever seen)."""

    def walk(d: Mapping[str, object], path: str):
        for k, v in d.items():
            if k in skip:
                continue
            key = f"{path}{k}"
            if isinstance(v, Mapping):
                walk(v, f"{key}_")
            elif isinstance(v, bool):
                continue
            elif isinstance(v, (int, float)) and math.isfinite(float(v)):
                name = sanitize_metric_name(f"{prefix}{key}")
                registry.gauge(name, help).set(float(v))

    walk(snapshot, "")


def _render_labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _escape(v: object) -> str:
    return (
        str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def render_prometheus(
    groups: "Iterable[tuple[Mapping[str, str], MetricsRegistry]]",
) -> str:
    """Merge registries into one valid Prometheus text exposition.
    ``groups`` pairs per-registry constant labels (e.g. ``{"model":
    name}``) with the registry; HELP/TYPE lines are emitted once per
    family even when several registries share a family name."""
    meta: dict[str, tuple[str, str]] = {}
    by_family: dict[str, list[str]] = {}
    for labels, reg in groups:
        for name, (typ, help) in reg.family_meta().items():
            meta.setdefault(name, (typ, help))
        for metric in reg.collect():
            fam = metric.name  # family name (histogram samples suffix it)
            lines = by_family.setdefault(fam, [])
            for sample_name, sample_labels, value in metric.samples():
                merged = {**sample_labels, **dict(labels)}
                if isinstance(value, float):
                    if math.isnan(value):
                        val = "NaN"
                    elif value == int(value) and abs(value) < 1e15:
                        val = str(int(value))
                    else:
                        val = repr(value)
                else:
                    val = str(value)
                lines.append(
                    f"{sample_name}{_render_labels(merged)} {val}"
                )
    out: list[str] = []
    for fam in sorted(by_family):
        typ, help = meta.get(fam, ("untyped", ""))
        out.append(f"# HELP {fam} {_escape_help(help)}")
        out.append(f"# TYPE {fam} {typ}")
        out.extend(by_family[fam])
    return "\n".join(out) + "\n" if out else ""


def _escape_help(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_BUCKETS",
    "render_prometheus",
    "sanitize_metric_name",
    "snapshot_gauges",
]
