"""ControlJournal — the validator's durable control-plane write-ahead log.

The validator's in-memory state (hosted jobs, replica sets, in-flight
admissions, migration tickets, autopilot actions) is the last single
point of failure in the stack: every data-plane failure has a recovery
ladder, but a validator crash used to strand every live engine and drop
every in-flight stream even though the workers kept decoding. This
module is the durability half of the fix (docs/FAILURE_MODEL.md
"Control plane"):

- **Write-ahead**: intent records (`intent`/`commit`/`abort` triples)
  are fsynced BEFORE the action they describe executes, so a half-done
  rolling deploy or drain is visible at replay — resumed or rolled
  back, never forgotten. Plain records (admissions, token high-water
  marks) are fsync-BATCHED: buffered in memory and flushed when the
  batch fills or the flush window elapses, so the serving hot path
  never pays a per-token fsync.
- **Replay** (:meth:`ControlJournal.replay`) folds the record stream
  into a :class:`JournalState`: live hosted jobs with per-replica
  re-attach payloads, per-request admissions with their delivered-token
  high-water marks, and every intent that never committed. A torn final
  line (the crash landed mid-write) is tolerated and counted, never
  fatal.
- **Reconciliation contract**: the journal is authoritative for
  PLACEMENT (which job/replica/worker a stream was admitted to); the
  WORKER is authoritative for TOKENS (its live slot state survived the
  validator, so its counts can only be >= the journaled high-water
  mark). Recovery (ml/validator.py::recover) re-handshakes each worker
  and merges on that rule.

Record shape — one JSON object per line::

    {"seq": 17, "t": 1699..., "kind": "admit", "data": {...}}
    {"seq": 18, "t": ..., "kind": "mig", "phase": "intent", "iid": "..."}

The ``journal.write`` fault site (core/faults.py) fires per append:
``drop`` silently loses the record (recovery must tolerate holes),
``error`` raises out of :meth:`append` (callers keep serving — a
journal hiccup must never fail a request).
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from pathlib import Path

from tensorlink_tpu.core import faults
from tensorlink_tpu.core.logging import get_logger

# record kinds with intent -> commit/abort pairing (everything else is a
# plain single record)
INTENT_KINDS = ("host", "mig", "action")


class ControlJournal:
    """Append-only JSONL journal with batched fsync.

    Thread-safe: API handler threads journal admissions concurrently
    with the autopilot journaling action intents.
    """

    def __init__(
        self,
        path: str | Path,
        *,
        flush_every: int = 16,
        flush_s: float = 0.05,
    ):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.flush_every = max(int(flush_every), 1)
        self.flush_s = float(flush_s)
        self.log = get_logger("core.journal")
        self._lock = threading.Lock()
        self._buf: list[str] = []  #: guarded by self._lock
        self._seq = 0  #: guarded by self._lock
        self._last_flush = time.monotonic()  #: guarded by self._lock
        self._f = open(self.path, "a", encoding="utf-8")
        self._closed = False

    # -- writing ---------------------------------------------------------
    def append(self, kind: str, data: dict | None = None, *,
               phase: str | None = None, iid: str | None = None,
               flush: bool = False) -> int:
        """Append one record; returns its seq. ``flush=True`` forces the
        write-ahead fsync (intents always force). Raises
        :class:`~tensorlink_tpu.core.faults.FaultInjected` when the
        ``journal.write`` fault site fires with op="error"; a "drop"
        decision silently loses the record (the chaos suite's
        lost-record case)."""
        act = None
        if faults.ENABLED:
            act = faults.inject("journal.write", kind)
        with self._lock:
            if self._closed:
                raise RuntimeError("journal is closed")
            self._seq += 1
            seq = self._seq
            if act == "drop":
                return seq  # the record is LOST — replay sees a hole
            rec: dict = {"seq": seq, "t": time.time(), "kind": str(kind)}
            if phase:
                rec["phase"] = phase
            if iid:
                rec["iid"] = iid
            if data:
                rec["data"] = data
            self._buf.append(json.dumps(rec, separators=(",", ":")))
            now = time.monotonic()
            if (
                flush
                or len(self._buf) >= self.flush_every
                or now - self._last_flush >= self.flush_s
            ):
                self._flush_locked(now)
        return seq

    def intent(self, kind: str, data: dict | None = None) -> str:
        """Durably record that ``kind`` is ABOUT to happen (write-ahead:
        fsynced before this returns). Pair with :meth:`commit` /
        :meth:`abort`; an intent neither committed nor aborted is an
        OPEN intent at replay — recovery's resume-or-rollback input."""
        iid = uuid.uuid4().hex
        self.append(kind, data, phase="intent", iid=iid, flush=True)
        return iid

    def commit(self, iid: str, data: dict | None = None,
               *, kind: str = "") -> None:
        self.append(kind or "intent", data, phase="commit", iid=iid,
                    flush=True)

    def abort(self, iid: str, data: dict | None = None,
              *, kind: str = "") -> None:
        self.append(kind or "intent", data, phase="abort", iid=iid,
                    flush=True)

    def _flush_locked(self, now: float | None = None) -> None:  # tlint: holds-lock(self._lock)
        if self._buf:
            self._f.write("\n".join(self._buf) + "\n")
            self._buf.clear()
            self._f.flush()
            os.fsync(self._f.fileno())
        self._last_flush = time.monotonic() if now is None else now

    def flush(self) -> None:
        with self._lock:
            if not self._closed:
                self._flush_locked()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._flush_locked()
            self._closed = True
            self._f.close()

    # -- replay ----------------------------------------------------------
    @staticmethod
    def replay(path: str | Path) -> "JournalState":
        """Fold the journal file into a :class:`JournalState`. Missing
        file → empty state. A torn final line (crash mid-write) is
        skipped and counted; a torn line ANYWHERE else is also skipped
        (a dropped-record fault leaves the same shape) — replay is
        total, never raises on journal contents."""
        st = JournalState()
        p = Path(path)
        if not p.exists():
            return st
        lines = p.read_text(encoding="utf-8").splitlines()
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                st.torn += 1
                continue
            if isinstance(rec, dict):
                st._fold(rec)
        return st


@dataclass
class JournalState:
    """The replayed view of a control journal — what recovery consumes."""

    records: int = 0
    torn: int = 0  # unparseable (torn / corrupted) lines skipped
    #: name -> {"data": host-intent data, "committed": bool,
    #:          "unhosted": bool, "replicas": {rid: replica_up data}}
    jobs: dict = field(default_factory=dict)
    #: jrid -> {"data": admit data, "hwm": int, "finished": bool,
    #:          "seed": int | None, "reason": str}
    admissions: dict = field(default_factory=dict)
    #: iid -> {"kind", "data", "state": intent|commit|abort,
    #:         "close_data": dict}
    intents: dict = field(default_factory=dict)
    recovered: int = 0  # completed recovery replays recorded

    def _fold(self, rec: dict) -> None:
        self.records += 1
        kind = str(rec.get("kind", ""))
        phase = rec.get("phase")
        data = rec.get("data") or {}
        if phase:
            iid = str(rec.get("iid", ""))
            if phase == "intent":
                self.intents[iid] = {
                    "kind": kind, "data": data, "state": "intent",
                    "close_data": {},
                }
                if kind == "host" and data.get("name"):
                    self.jobs.setdefault(
                        str(data["name"]),
                        {"data": data, "committed": False,
                         "unhosted": False, "replicas": {}},
                    )["data"] = data
            else:  # commit | abort
                ent = self.intents.setdefault(
                    iid, {"kind": kind, "data": {}, "state": "intent",
                          "close_data": {}},
                )
                ent["state"] = phase
                ent["close_data"] = data
                if ent["kind"] == "host" and phase == "commit":
                    name = str(ent["data"].get("name", ""))
                    if name in self.jobs:
                        self.jobs[name]["committed"] = True
            return
        if kind == "replica_up":
            name = str(data.get("name", ""))
            job = self.jobs.setdefault(
                name, {"data": {}, "committed": False, "unhosted": False,
                       "replicas": {}},
            )
            job["replicas"][str(data.get("rid", "r0"))] = data
            job["unhosted"] = False
        elif kind == "replica_down":
            job = self.jobs.get(str(data.get("name", "")))
            if job is not None:
                job["replicas"].pop(str(data.get("rid", "")), None)
        elif kind == "unhost":
            job = self.jobs.get(str(data.get("name", "")))
            if job is not None:
                job["unhosted"] = True
                job["replicas"].clear()
        elif kind == "admit":
            jrid = str(data.get("jrid", ""))
            if jrid:
                self.admissions[jrid] = {
                    "data": data, "hwm": 0, "finished": False,
                    "seed": data.get("seed"), "reason": "",
                }
        elif kind == "place":
            # fleet dispatch resolves "router" placements to the replica
            # actually chosen (last record wins — that's the replica that
            # served it after any failover)
            adm = self.admissions.get(str(data.get("jrid", "")))
            if adm is not None and data.get("rid"):
                adm["data"]["placement"] = str(data["rid"])
        elif kind == "seed":
            adm = self.admissions.get(str(data.get("jrid", "")))
            if adm is not None:
                adm["seed"] = data.get("seed")
        elif kind == "hwm":
            adm = self.admissions.get(str(data.get("jrid", "")))
            if adm is not None:
                # monotone: a replayed out-of-order/duplicated record
                # can only raise the mark, never lower it
                adm["hwm"] = max(adm["hwm"], int(data.get("n", 0)))
        elif kind == "finish":
            adm = self.admissions.get(str(data.get("jrid", "")))
            if adm is not None:
                adm["finished"] = True
                adm["hwm"] = max(adm["hwm"], int(data.get("n", 0)))
                adm["reason"] = str(data.get("reason", ""))
        elif kind == "recovered":
            self.recovered += 1

    # -- recovery queries -------------------------------------------------
    def live_jobs(self) -> dict:
        """name -> job record for every hosted model that should exist:
        host intent seen (committed or not — a crash mid-host with
        replicas already up must still recover them), not unhosted, at
        least one replica journaled up."""
        return {
            name: job for name, job in self.jobs.items()
            if not job["unhosted"] and job["replicas"]
        }

    def open_intents(self, kind: str | None = None) -> list[tuple[str, dict]]:
        """(iid, entry) for every intent never committed nor aborted —
        the in-flight actions a crash interrupted."""
        return [
            (iid, ent) for iid, ent in self.intents.items()
            if ent["state"] == "intent"
            and (kind is None or ent["kind"] == kind)
        ]

    def orphan_admissions(self) -> list[tuple[str, dict]]:
        """(jrid, record) for admissions with no finish record — streams
        that were (possibly) still decoding when the validator died.
        The worker's live/orphan report is the authority on whether each
        still exists (worker wins for tokens)."""
        return [
            (jrid, adm) for jrid, adm in self.admissions.items()
            if not adm["finished"]
        ]

    def routed_counts(self) -> dict[str, int]:
        """placement rid -> admissions journaled there; seeds the
        recovered FleetRouter's per-replica routed counters so routing
        telemetry survives the restart instead of cold-starting."""
        out: dict[str, int] = {}
        for adm in self.admissions.values():
            rid = str(adm["data"].get("placement", "") or "")
            if rid:
                out[rid] = out.get(rid, 0) + 1
        return out


__all__ = ["ControlJournal", "JournalState", "INTENT_KINDS"]
