"""Core kit: config, logging, serialization, shared memory, identity.

Reference parity: tensorlink's layered config (nodes/nodes.py:16-77,
bin/config.json, .tensorlink.env), tagged colored logging
(p2p/smart_node.py:499-530), pickle-free tensor serialization
(ml/utils.py:569-660), and shared-memory IPC (nodes/shared_memory.py).
"""
