"""POSIX shared-memory hop between co-located processes.

Reference parity: nodes/shared_memory.py:6-38 — ``store_in_shared_memory``
returns ``(size, name)``, ``get_from_shared_memory`` reads and unlinks.
Payloads are TLTS frames (core/serialization.py), never pickle; the
reference's optional trusted-pickle path is deliberately dropped
(SURVEY §7.4).
"""

from __future__ import annotations

from multiprocessing import resource_tracker, shared_memory
from typing import Any

from . import serialization


def _unregister(shm: shared_memory.SharedMemory) -> None:
    # The producing process hands ownership to the consumer; stop the
    # resource tracker from double-unlinking at exit.
    try:  # pragma: no cover - depends on interpreter internals
        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    # tlint: disable=TL005(resource_tracker is an interpreter internal; on mismatch the tracker just warns at exit)
    except Exception:
        pass


def store(obj: Any) -> tuple[int, str]:
    """Encode ``obj`` into a fresh shared-memory segment; returns (size, name).

    The receiver owns the segment and must call :func:`load` (which unlinks)
    or :func:`unlink`.
    """
    data = serialization.encode(obj)
    shm = shared_memory.SharedMemory(create=True, size=max(len(data), 1))
    shm.buf[: len(data)] = data
    name = shm.name
    _unregister(shm)
    shm.close()
    return len(data), name


def load(size: int, name: str, *, unlink: bool = True) -> Any:
    """Read an object back; unlinks the segment by default (reference
    get_from_shared_memory reads **and unlinks**, shared_memory.py:23)."""
    shm = shared_memory.SharedMemory(name=name)
    try:
        # One memcpy of the frame, then zero-copy array views into it; views
        # must not point at the mapping itself or close() would fail with
        # exported-pointer BufferError.
        obj = serialization.decode(bytes(shm.buf[:size]), copy=False)
    finally:
        shm.close()
        if unlink:
            try:
                shm.unlink()
            # tlint: disable=TL005(consumer/producer race on unlink — either side may have won)
            except FileNotFoundError:
                pass
    return obj


def unlink(name: str) -> None:
    try:
        shm = shared_memory.SharedMemory(name=name)
        shm.close()
        shm.unlink()
    # tlint: disable=TL005(already gone is the desired end state of unlink)
    except FileNotFoundError:
        pass
