"""Layered configuration system.

Mirrors the reference's four config layers (SURVEY §5; reference
nodes/nodes.py:16-77, bin/run_node.py:213-246, .tensorlink.env,
tensorlink/config/config.json) as one coherent scheme:

1. Role config dataclasses (programmatic API) — :class:`NodeConfig` and
   subclasses.
2. Operator ``config.json`` — node type / mode / endpoint / ml caps.
3. Environment file (``.tensorlink_tpu.env``) — keys, persisted port
   assignments, chain overrides.
4. Packaged defaults — seed validators, default models, contract addresses.

Unlike the reference there is also a first-class ``MeshConfig`` describing the
TPU topology the node contributes (axis names/sizes, dtype policy) — on TPU the
unit of capacity is a slice of a device mesh, not "GPU bytes".
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

DEFAULT_ENV_FILE = ".tensorlink_tpu.env"

# Packaged defaults (reference: tensorlink/config/config.json + models.json).
# tlint: disable=TL006(read-only defaults table — EnvFile overlays copy, never mutate)
DEFAULT_CONFIG: dict[str, Any] = {
    "seed_validators": [],  # [(host, port), ...]
    "default_models": ["Qwen/Qwen3-8B"],
    "free_job_max_time": 3600.0,  # reference validator_thread.py:19
    "max_wait_time": 150.0,  # reference ml/module.py:58
    "worker_recruit_timeout": 3.0,  # reference validator_thread.py:871
    "job_request_timeout": 120.0,  # reference user_thread.py:406
    "api": {
        "max_concurrent": 100,  # reference api/node.py:537
        "stream_token_timeout": 30.0,
        "request_timeout": 300.0,
    },
}


@dataclass
class MLConfig:
    """ML-engine knobs (reference config.json "ml" block, run_node.py:228-246)."""

    max_memory_gb: float | None = None  # cap on HBM the node offers
    max_module_bytes: float | None = None  # force sharding below this size
    # ICI-slice identity this worker advertises; co-slice workers merge into
    # one planned mesh (parallel/planner.py::_merge_co_slice). Auto-detected
    # from device.slice_index on TPU when unset (and TPU_NAME identifies the
    # pod — without it the index alone would collide across pods).
    slice_id: str = ""
    # validator: enable co-slice merging at plan time. Off by default — a
    # merged plan needs a runtime where one worker process addresses the
    # whole slice's devices (see plan_sharding docstring).
    co_slice_planning: bool = False
    # multi-controller runtime (parallel/multihost.py): set on every host of
    # a slice to join one jax.distributed job; jax.devices() then spans the
    # slice. Env fallbacks: TLTPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID.
    coordinator_address: str = ""
    num_processes: int = 0
    process_id: int = -1
    trusted: bool = False  # reference: pickle mode. Here: may run user jax code
    dtype: str = "bfloat16"
    max_seq_len: int = 4096
    # TPU-specific: padding buckets to bound XLA recompilation (SURVEY §7.3.5)
    seq_buckets: tuple[int, ...] = (128, 512, 1024, 2048, 4096)
    batch_buckets: tuple[int, ...] = (1, 2, 4, 8)
    # serving: how many concurrent API requests one batched decode may
    # coalesce (ml/batching.py); bounded by the largest batch bucket
    max_serve_batch: int = 8
    # continuous batching over the paged KV cache (engine/continuous.py,
    # docs/SERVING.md): requests join the RUNNING slot batch at decode-chunk
    # boundaries and finished rows free their KV pages immediately, instead
    # of window-coalescing into run-to-completion static batches. Single-
    # stage jobs decode on the worker's slot engine; pipelined jobs run
    # slot admission through the session path (ml/batching.py). Models the
    # paged engine can't serve (sliding-window attention) fall back to the
    # windowed batcher automatically; int8-KV models ("int8+kv") serve
    # CONTINUOUS — the paged cache stores int8 pages natively (kv_quant).
    continuous_batching: bool = True
    cont_max_slots: int = 8  # concurrent requests per model (B of the slot batch)
    cont_page_size: int = 16  # KV positions per page
    cont_chunk_steps: int = 8  # decode steps between admission boundaries
    # chunked prefill (engine/continuous.py): an admitted prompt prefills
    # in fixed-shape chunks of this many tokens interleaved with decode
    # chunks, so a long admission never stalls co-resident decodes for
    # more than one chunk (flat TTFT under mixed traffic). 0 = legacy
    # monolithic admission (whole-prompt dense prefill; disables the
    # prefix cache, which needs offset-carrying suffix prefill).
    prefill_chunk: int = 128
    # automatic prefix caching over the paged KV cache (docs/SERVING.md):
    # full KV pages are kept resident keyed by their exact token chain
    # from position 0; admission maps the longest cached prefix into the
    # new slot's block table (zero prefill compute for the hit region),
    # the first divergent page is copy-on-write, and unreferenced pages
    # evict LRU when the allocator runs dry. Hits are bitwise the KV the
    # slot would have computed — streams are identical cache on or off.
    prefix_cache: bool = True
    # tiered prefix cache (engine/kvtier.py, docs/SERVING.md "Tiered
    # prefix cache"): > 0 arms a host-RAM tier of this many pages —
    # refcount-0 prefix pages DEMOTE to host numpy at eviction instead
    # of being destroyed, and admission promotes host residents back
    # into HBM bitwise (device_put, zero new compiled programs). The
    # tier also feeds the fleet digest map so siblings can pull
    # prefixes cross-replica on a local miss. 0 keeps seed behavior
    # (evicted pages die).
    cont_host_tier_pages: int = 0
    # paged KV cache storage dtype (engine/paged.py, docs/SERVING.md
    # "Quantized KV"): "int8" stores KV pages int8 with per-(page,
    # position, head) symmetric scales, quantized at the one page-write
    # path and dequantized in-kernel at the page fetch — KV bytes halve,
    # so ~2x serving slots and ~2x prefix-cache residency at fixed HBM.
    # "int4" packs two values per byte at the same scale granularity:
    # ~4x at a byte-matched budget (vs bf16), with a looser but still
    # context-length-independent divergence bound. Streams stay
    # bit-identical to each other across every lifecycle path
    # (solo/co-batched/recovered/preempted, cache on/off); only the
    # fp-vs-quantized comparison differs, bounded in tests. Default
    # int8 (the PR 7 one-release opt-in window has elapsed); "none" is
    # the explicit opt-out. Models served with quant="int8+kv" force
    # quantized pages.
    kv_quant: str = "int8"  # "none" | "int8" | "int4"
    # -- multi-tenant co-hosting (docs/SERVING.md "Co-hosting multiple
    # models"): one physical KV page pool shared by every co-hosted
    # model with matching page geometry (the many-small-fine-tunes
    # shape), under per-model page quotas with cross-model preemption
    # by scheduler rank. 0 keeps today's private pool per engine.
    cont_pool_pages: int = 0  # TOTAL shared pool pages (0 = private pools)
    # default per-model page quota on the shared pool (0 = uncapped —
    # bounded by the pool alone); a model spec's "page_quota" overrides
    cont_pool_quota: int = 0
    # EQuARX-style quantized collectives (parallel/ring.py): ring-attention
    # K/V hops move int8 chunks + scales over ICI with a deterministic f32
    # reduction — ~half the hop bytes at a bounded, test-pinned divergence.
    # Applied via ModelConfig.collective_quant at SERVING stage load only
    # (the quantize round() has a zero gradient — training keeps exact
    # collectives). GSPMD tensor-parallel collectives are XLA-inserted and
    # unaffected; ring.quantized_psum/quantized_all_gather are the
    # building blocks for explicit shard_map paths.
    collective_quant: bool = False
    # -- explicit tensor parallelism (docs/SHARDING.md): shard the paged
    # serving hot path over a tp mesh axis — attention heads and MLP
    # columns as weight shards, KV pages by kv head, every control-state
    # array replicated, per-chunk activation gathers in a fixed order so
    # streams stay bit-identical to tp=1. The whole mesh is ONE
    # placement unit to the fleet router. 1 = single-device (today's
    # path, byte-identical programs). Models the specs can't shard
    # (MoE, indivisible head counts) fall back to the static batcher
    # through the worker's normal refusal seam.
    tensor_parallel: int = 1
    # -- disaggregated prefill/decode pools (docs/SERVING.md
    # "Disaggregated prefill/decode"): the serving role this worker
    # advertises. "prefill" workers take new continuous admissions, fill
    # their pages through the normal ragged grants, then freeze each
    # slot at the prefill→decode boundary and ship it to a decode-pool
    # worker through the migration export/stage/adopt path — so an
    # interactive stream's inter-token latency never shares a step with
    # a neighbor's long prompt. "decode" workers are excluded from
    # placement and serve as handoff destinations. "mixed" (default)
    # keeps the single-pool behavior. Placement and the decode-pool push
    # are the validator's job (ml/validator.py); a prefill worker with
    # no reachable decode pool degrades to mixed behavior per slot
    # (abort_handoff — never a dropped or slower stream).
    worker_role: str = "mixed"  # "prefill" | "decode" | "mixed"
    # speculative decoding inside the unified ragged step (engine/
    # continuous.py, docs/SERVING.md "Speculative decoding"): an opted-in
    # request ({"speculative": true}) packs a host-drafted prompt-lookup
    # block as extra valid rows of its decode slot and the one compiled
    # step verifies all of them in-program — multi-token decode per pass
    # on repetitive/extractive text, bit-identical streams always, with
    # a per-request acceptance-rate kill switch so a bad draft mix can
    # never make it a slowdown. Default ON (the PR 11 one-release
    # opt-in window has elapsed, mirroring the kv_quant flip): the
    # engine capability is armed everywhere, requests still opt in
    # per-call; spec_decode=False is the explicit opt-out.
    spec_decode: bool = True
    # max draft tokens per verify pass (extra ragged rows per
    # speculating slot; capped by prefill_chunk - 1)
    spec_draft: int = 8
    # optional TOTAL draft tokens per step shared round-robin-fair
    # across speculating slots (0 = each gets a full draft) — bounds the
    # extra verify compute per step like prefill_budget bounds prefill
    spec_budget: int = 0
    # -- SLO-aware request scheduling (engine/scheduler.py) --------------
    # priority class a request gets when the API body carries none:
    # "interactive" | "batch" | "best_effort". Classes order admission
    # (aging keeps low classes starvation-free) and bound preemption —
    # see docs/SERVING.md "Scheduling".
    default_priority: str = "interactive"
    # per-class queued-request cap: past it submissions fail fast (the
    # API layer turns the rejection into 429 + Retry-After) instead of
    # queueing until the client times out
    sched_queue_cap: int = 64
    # starvation-free aging: a queued request's effective class improves
    # by one rank every this-many admission rounds (one round = one
    # engine chunk), so sustained interactive load delays batch work but
    # never parks it forever
    sched_aging_ticks: int = 32
    # cache-backed preemption: a higher-class request that would miss
    # admission may evict the lowest-class / most-recently-admitted slot
    # through the prefix-cache promotion path and re-queue it — the
    # resumed stream is bit-identical to an uninterrupted run
    sched_preemption: bool = True
    # "slo" (priority + aging + preemption) or "fcfs" (PR-2 behavior:
    # strict arrival order, no preemption) — the bench's baseline knob
    sched_policy: str = "slo"
    # backpressure: reject admission when the estimated queue wait for
    # the request's class exceeds this many seconds (0 disables the
    # wait check; the queue cap still applies)
    sched_max_wait_s: float = 60.0
    # -- fleet serving (tensorlink_tpu/fleet, docs/SERVING.md "Fleet
    # serving"): N replicas of each hosted model behind a cache- and
    # SLO-aware router. host_model plans this many independent replica
    # jobs (fewer when capacity runs out — the fleet degrades, the host
    # never fails for lack of spares) and routes each request by
    # prefix-cache affinity + per-class load; 1 keeps today's
    # single-replica path byte-identical.
    fleet_replicas: int = 1
    # start the FleetAutopilot control loop per hosted fleet: rebalance
    # hot replicas, scale the decode pool, run rolling deploys — every
    # action through the drain/migration path (zero dropped tokens)
    fleet_autopilot: bool = False
    fleet_autopilot_interval_s: float = 2.0
    # router telemetry refresh cadence (seconds between replica-view
    # pulls; route() also refreshes lazily at this cadence)
    fleet_refresh_s: float = 0.5
    # streamed requests: >0 runs the decode as fully-compiled on-device
    # chunks of this many steps (one host round trip per chunk instead of
    # per token — engine/generate.py::generate_chunked); 0 keeps the
    # per-token host loop (lowest time-to-first-delta on local devices).
    # Set 16-64 when the chip is reached over a high-latency tunnel; a
    # stop-sequence cancel still cuts the stream at the exact token (only
    # device compute, not emission, runs to the chunk end).
    stream_chunk_steps: int = 0
    # pre-compile the serving engine at host time for this many decode
    # tokens (engine.warmup) — 0 skips; when set, "ready" means every batch
    # bucket's smallest-prompt prefill + this token budget's decode loop is
    # compiled (other prompt/budget buckets still compile on first use)
    warmup_tokens: int = 0
    # validator: host DEFAULT_CONFIG["default_models"] at startup (reference
    # auto-loads popular/default models, ml/validator.py:169-365); off by
    # default so local tests never pull multi-GB checkpoints
    autoload_default_models: bool = False
    # -- control-plane crash safety (core/journal.py, docs/FAILURE_MODEL.md
    # "Control plane"): the validator's write-ahead journal of hosting,
    # admissions, delivered-token high-water marks, migration tickets and
    # autopilot intents. Restart + DistributedValidator.recover() replays
    # it, re-attaches live replicas and expires stranded tickets.
    journal: bool = True
    # plain (non-intent) records are fsync-batched: flush when this many
    # buffered or when the window elapses, whichever first. Intents always
    # fsync write-ahead regardless.
    journal_flush_every: int = 16
    journal_flush_s: float = 0.05
    # delivered-token high-water marks are journaled every N streamed
    # tokens per request (chunk granularity — the journal is an audit
    # floor; the worker's live count is authoritative at recovery)
    journal_hwm_every: int = 16
    # workers: finished orphaned streams (client/validator gone before the
    # final response was delivered) are kept for re-attach up to this many
    # entries / this long, whichever trips first. Live orphans aren't
    # bounded here — allocator pressure sheds them via preemption as usual.
    orphan_keep: int = 64
    orphan_ttl_s: float = 180.0


@dataclass
class MeshConfig:
    """Shape of the device mesh a node runs over.

    Axis names follow the scaling-book convention: data / fsdp / tensor /
    expert / sequence / stage. ``axis_sizes`` of -1 means "all remaining local
    devices".
    """

    axes: tuple[str, ...] = ("data", "tensor")
    axis_sizes: tuple[int, ...] = (1, -1)
    platform: str | None = None  # None = jax default; "cpu" for tests

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = dict(zip(self.axes, self.axis_sizes))
        rem = n_devices
        wildcard = None
        for ax, s in sizes.items():
            if s == -1:
                if wildcard is not None:
                    raise ValueError("only one mesh axis may be -1")
                wildcard = ax
            else:
                if rem % s != 0:
                    raise ValueError(
                        f"axis {ax}={s} does not divide device count {rem}"
                    )
                rem //= s
        if wildcard is not None:
            sizes[wildcard] = rem
        elif rem != 1:
            raise ValueError(
                f"mesh {sizes} does not use all {n_devices} devices"
            )
        return sizes


@dataclass
class NodeConfig:
    """Base node configuration (reference BaseNodeConfig, nodes/nodes.py:16-45)."""

    role: str = "node"
    host: str = "0.0.0.0"
    port: int | None = None  # None = ephemeral / persisted in env file
    debug: bool = True
    debug_level: int = 20  # logging level; 5 = VERBOSE
    # structured logging (core/logging.py): one JSON object per line
    # carrying ts/level/tag/msg and the active trace_id when a request
    # span is live — joinable against /trace. Default keeps the colored
    # human format.
    json_logs: bool = False
    local_test: bool = False  # force 127.0.0.1, no UPnP (reference smart_node.py:230)
    upnp: bool = False
    off_chain: bool = True  # reference: on_chain flag inverted; off-chain default
    endpoint: bool = False  # serve the HTTP API (validators)
    endpoint_host: str = "127.0.0.1"
    endpoint_port: int = 64747  # reference test endpoint port
    seed_validators: list[tuple[str, int]] = field(default_factory=list)
    key_dir: str = "keys"
    log_dir: str = "logs"
    env_file: str = DEFAULT_ENV_FILE
    ml: MLConfig = field(default_factory=MLConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    utilization: bool = True  # offer capacity (workers)
    duplicate: str = ""  # role suffix for same-host multi-node tests
    # native shm message ring for the ML↔net bridge (core/ring.py); falls
    # back to mp.Queue when the C++ toolchain / platform can't build it
    native_ipc: bool = True
    # platform-service cadences (reference: keeper write every 300 s,
    # JobMonitor 30 s cycle — validator_thread.py:978-1011, job_monitor.py:104)
    keeper_interval: float = 300.0
    monitor_interval: float = 30.0
    proposal_interval: float = 3600.0  # contract round cadence (0 = manual)
    # seconds a job's worker may be unreachable before the monitor recruits
    # a replacement (platform/job_monitor.py)
    offline_grace: float = 5.0
    # deterministic fault-injection plan (core/faults.py): {} disables the
    # layer entirely — no fault-site code runs on the hot paths. A non-empty
    # plan is installed in BOTH halves of the node: the spawned network
    # process (p2p.send / connection.frame sites) and the ML executor
    # (worker.session_step / worker.train_step sites).
    faults: dict = field(default_factory=dict)

    def effective_host(self) -> str:
        return "127.0.0.1" if self.local_test else self.host


@dataclass
class WorkerConfig(NodeConfig):
    role: str = "worker"
    mining: bool = False  # reference: miner subprocess mgmt (run_node.py:135-194)


@dataclass
class ValidatorConfig(NodeConfig):
    role: str = "validator"
    endpoint: bool = True


@dataclass
class UserConfig(NodeConfig):
    role: str = "user"


def _coerce(cls, data: dict[str, Any]):
    """Build a dataclass from a dict, recursing into nested dataclass fields
    and ignoring unknown keys (operator config files may carry extras)."""
    import typing

    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        v = data[f.name]
        ftype = hints.get(f.name, f.type)
        if dataclasses.is_dataclass(ftype) and isinstance(v, dict):
            v = _coerce(ftype, v)
        elif f.name == "seed_validators":
            v = [tuple(x) for x in v]
        kwargs[f.name] = v
    return cls(**kwargs)


# tlint: disable=TL006(read-only constant table — never mutated at runtime)
ROLE_CONFIGS = {
    "worker": WorkerConfig,
    "validator": ValidatorConfig,
    "user": UserConfig,
}


def load_config(path: str | Path) -> NodeConfig:
    """Load an operator config.json (reference bin/run_node.py:213-246)."""
    raw = json.loads(Path(path).read_text())
    role = raw.get("role", raw.get("node", {}).get("type", "worker"))
    cls = ROLE_CONFIGS.get(role, NodeConfig)
    flat = dict(raw)
    flat.update(raw.get("node", {}))
    flat["role"] = role
    # Reference mode mapping (run_node.py:60-76): local / upnp / on_chain
    mode = flat.pop("mode", None)
    if mode == "local":
        flat.update(local_test=True, upnp=False, off_chain=True)
    elif mode == "upnp":
        flat.update(local_test=False, upnp=True, off_chain=True)
    elif mode == "on_chain":
        flat.update(local_test=False, upnp=True, off_chain=False)
    return _coerce(cls, flat)


class EnvFile:
    """Tiny KEY=VALUE env file with persisted port assignments keyed by node
    id (reference .tensorlink.env, smart_node.py:84,1166-1198)."""

    def __init__(self, path: str | Path = DEFAULT_ENV_FILE):
        self.path = Path(path)

    def read(self) -> dict[str, str]:
        out: dict[str, str] = {}
        if self.path.exists():
            for line in self.path.read_text().splitlines():
                line = line.strip()
                if line and not line.startswith("#") and "=" in line:
                    k, _, v = line.partition("=")
                    out[k.strip()] = v.strip()
        return out

    def get(self, key: str, default: str | None = None) -> str | None:
        return self.read().get(key, os.environ.get(key, default))

    def set(self, key: str, value: str) -> None:
        data = self.read()
        data[key] = value
        self.path.write_text(
            "".join(f"{k}={v}\n" for k, v in sorted(data.items()))
        )

    def port_for(self, node_id: str, default: int | None = None) -> int | None:
        v = self.get(f"PORT_{node_id[:16]}")
        return int(v) if v is not None else default

    def save_port(self, node_id: str, port: int) -> None:
        self.set(f"PORT_{node_id[:16]}", str(port))
