"""Tagged, colored logging with a VERBOSE level and rotating file output.

Reference parity: smart_node.py:47,119-125,499-530 — colored tag-prefixed
``debug_print`` with custom VERBOSE=5 level and a TimedRotatingFileHandler to
``logs/runtime.log`` with 7-day retention. Re-specified on top of stdlib
logging rather than hand-rolled prints.

**Structured JSON mode** (``NodeConfig.json_logs`` →
:func:`set_json_logs`): every line becomes one JSON object carrying
``ts`` (epoch seconds), ``level``, ``tag``, ``msg`` — and ``trace_id``
when a distributed-trace span is active on the emitting thread
(core/trace.py ``current_trace``), so cluster log aggregates join
directly against ``GET /trace/<rid>``. Plain colored mode stays the
default.
"""

from __future__ import annotations

import json
import logging
import logging.handlers
import sys
from pathlib import Path

# process-wide log-mode switch, flipped once at node start (BaseNode reads
# NodeConfig.json_logs before any executor thread spawns); a dict cell so
# formatters see updates without module-global rebinding
# tlint: disable=TL006(process-wide log-mode flag — set once at node start, reset via set_json_logs(False) in tests)
_MODE = {"json": False}


def set_json_logs(enabled: bool) -> None:
    """Switch every tensorlink logger (stream and file handlers alike)
    to/from one-JSON-object-per-line output."""
    _MODE["json"] = bool(enabled)


def json_logs_enabled() -> bool:
    return _MODE["json"]

VERBOSE = 5
logging.addLevelName(VERBOSE, "VERBOSE")

# tlint: disable=TL006(read-only constant table — never mutated at runtime)
_COLORS = {
    "VERBOSE": "\033[90m",
    "DEBUG": "\033[36m",
    "INFO": "\033[32m",
    "WARNING": "\033[33m",
    "ERROR": "\033[31m",
    "CRITICAL": "\033[41m",
}
_RESET = "\033[0m"


class _TagFormatter(logging.Formatter):
    def __init__(self, color: bool):
        super().__init__()
        self.color = color

    def format(self, record: logging.LogRecord) -> str:
        tag = getattr(record, "tag", record.name.rsplit(".", 1)[-1])
        if _MODE["json"]:
            out = {
                # record.created is the stdlib's epoch stamp — a genuine
                # wall-clock timestamp for log joining, never used for
                # durations
                "ts": round(record.created, 6),
                "level": record.levelname,
                "tag": tag,
                "msg": record.getMessage(),
            }
            from tensorlink_tpu.core.trace import current_trace

            tid = current_trace.get()
            if tid:
                out["trace_id"] = tid
            if record.exc_info:
                out["exc"] = self.formatException(record.exc_info)
            return json.dumps(out, default=str)
        base = f"[{self.formatTime(record, '%H:%M:%S')}] [{tag}] {record.getMessage()}"
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        if self.color:
            c = _COLORS.get(record.levelname, "")
            return f"{c}{base}{_RESET}" if c else base
        return base


class NodeLogger(logging.LoggerAdapter):
    """Logger bound to a node tag, e.g. ``[worker:ab12cd]``."""

    def process(self, msg, kwargs):
        extra = kwargs.setdefault("extra", {})
        extra.setdefault("tag", self.extra["tag"])
        return msg, kwargs

    def verbose(self, msg, *args, **kwargs):
        self.log(VERBOSE, msg, *args, **kwargs)


def get_logger(
    tag: str,
    level: int = logging.INFO,
    log_dir: str | Path | None = None,
    color: bool = True,
) -> NodeLogger:
    logger = logging.getLogger(f"tensorlink_tpu.{tag}")
    logger.setLevel(min(level, VERBOSE))
    stream_handlers = [
        h for h in logger.handlers if isinstance(h, logging.StreamHandler)
        and not isinstance(h, logging.FileHandler)
    ]
    if not stream_handlers:
        sh = logging.StreamHandler(sys.stderr)
        sh.setFormatter(_TagFormatter(color=color and sys.stderr.isatty()))
        sh.setLevel(level)
        logger.addHandler(sh)
    else:
        # Later calls may lower the level (e.g. enable VERBOSE after import).
        for h in stream_handlers:
            h.setLevel(min(h.level, level))
    if log_dir is not None and not any(
        isinstance(h, logging.FileHandler) for h in logger.handlers
    ):
        Path(log_dir).mkdir(parents=True, exist_ok=True)
        fh = logging.handlers.TimedRotatingFileHandler(
            Path(log_dir) / "runtime.log",
            when="D",
            backupCount=7,  # 7-day retention, reference smart_node.py:119-125
        )
        fh.setFormatter(_TagFormatter(color=False))
        fh.setLevel(VERBOSE)
        logger.addHandler(fh)
    logger.propagate = False
    return NodeLogger(logger, {"tag": tag})
