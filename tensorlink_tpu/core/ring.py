"""RingChannel — queue-shaped Python wrapper over the native shm ring.

Drop-in for the ``mp.Queue`` trio in nodes/ipc.py: ``put(obj)`` /
``get(timeout)`` with ``queue.Empty`` on timeout. Objects serialize through
TLTS (core/serialization.py — arrays are raw buffers, never pickled);
messages bigger than half the ring spill to a TLTS temp file and ship as a
path marker (the reference spills >20 MB frames the same way,
p2p/connection.py:110-122).

Pickling a RingChannel transfers only ``(name, capacity)`` — the spawned
process attaches to the same shm segment (creator side unlinks on close).
"""

from __future__ import annotations

import os
import queue as queue_mod
import secrets
import tempfile
import threading
from pathlib import Path

from tensorlink_tpu.core import serialization as ser

_FILE_MARKER = b"TLF1"
DEFAULT_CAPACITY = 64 << 20


class RingChannel:
    def __init__(self, capacity: int = DEFAULT_CAPACITY, *, _name: str | None = None):
        from tensorlink_tpu.native import load_tlring

        self._lib = load_tlring()
        if self._lib is None:
            raise RuntimeError("native tlring unavailable")
        self.capacity = capacity
        self._wlock = threading.Lock()
        self._rlock = threading.Lock()
        if _name is None:
            sweep_orphans()  # SIGKILLed owners can't unlink; reap them here
            self.name = f"/tlring-{os.getpid()}-{secrets.token_hex(6)}"
            self._h = self._lib.tlring_create(self.name.encode(), capacity)
            self.owner = True
        else:
            self.name = _name
            self._h = self._lib.tlring_attach(self.name.encode())
            self.owner = False
        if not self._h:
            raise RuntimeError(f"tlring setup failed for {self.name}")

    # -- pickling: child attaches ---------------------------------------
    def __reduce__(self):
        return (_attach, (self.name, self.capacity))

    # -- queue interface -------------------------------------------------
    def put(self, obj, timeout: float = 120.0) -> None:
        import ctypes

        blob = ser.encode(obj)
        if len(blob) + 8 > self.capacity // 2:
            # oversized → spill the ALREADY-BUILT frame + tiny marker
            # message (re-encoding here would pay the whole frame assembly
            # twice on exactly the large-payload path)
            fd, path = tempfile.mkstemp(prefix="tlring-", suffix=".tlts")
            os.close(fd)
            with open(path, "wb") as f:
                f.write(blob)
            blob = _FILE_MARKER + path.encode()
        if isinstance(blob, bytes):
            carg = blob
        else:
            # write straight from encode()'s buffer — no bytes() copy on
            # the hot IPC path (tlring_write takes c_void_p)
            carg = (ctypes.c_char * len(blob)).from_buffer(blob)
        with self._wlock:
            if self._h is None:
                raise OSError(f"ring {self.name} released")
            rc = self._lib.tlring_write(self._h, carg, len(blob), timeout)
        if rc == -1:
            raise queue_mod.Full(f"ring {self.name} full after {timeout}s")
        if rc == -2:
            raise OSError(f"ring {self.name} closed")
        if rc != 0:
            raise OSError(f"ring write failed rc={rc}")

    def get(self, timeout: float | None = None):
        t = 3600.0 if timeout is None else float(timeout)
        with self._rlock:
            if self._h is None:
                raise EOFError(f"ring {self.name} released")
            size = self._lib.tlring_next_size(self._h, t)
            if size == -1:
                raise queue_mod.Empty
            if size == -2:
                raise EOFError(f"ring {self.name} closed")
            if size < 0:
                raise OSError(f"ring read failed rc={size}")
            import ctypes

            cbuf = ctypes.create_string_buffer(size)
            n = self._lib.tlring_read(self._h, cbuf, size)
            if n != size:
                raise OSError(f"ring read short: {n} != {size}")
            buf = cbuf.raw
        if buf[:4] == _FILE_MARKER:
            path = Path(buf[4:].decode())
            obj = ser.decode_from_file(path)
            path.unlink(missing_ok=True)
            return obj
        return ser.decode(buf, copy=True)

    # -- lifecycle --------------------------------------------------------
    def close(self) -> None:
        if self._h:
            self._lib.tlring_close(self._h)

    def release(self) -> None:
        """Detach (and unlink when owner). Thread-safe against concurrent
        put/get: close() first wakes any thread blocked inside the C calls
        (they return closed), then the detach waits for both user locks so
        the munmap can never pull memory out from under a live call."""
        if self._h is None:
            return
        self._lib.tlring_close(self._h)
        with self._wlock, self._rlock:
            if self._h is None:
                return
            self._lib.tlring_detach(self._h)
            self._h = None
        if self.owner:
            self._lib.tlring_unlink(self.name.encode())

    def __del__(self):  # best-effort; explicit release preferred
        try:
            self.release()
        # tlint: disable=TL005(__del__ must never raise; explicit release() is the loud path)
        except Exception:
            pass


def _attach(name: str, capacity: int) -> RingChannel:
    return RingChannel(capacity, _name=name)


def sweep_orphans() -> int:
    """Unlink shm segments whose creating process is gone. Ring names embed
    the creator pid (``tlring-<pid>-<token>``); a SIGKILLed node can never
    unlink its segments, and a long-lived host would otherwise exhaust
    /dev/shm. Attachers of a dead creator are orphaned regardless, so
    reaping by creator-liveness is safe. Returns segments removed."""
    import re

    n = 0
    shm = Path("/dev/shm")
    if not shm.is_dir():
        return 0
    for p in shm.glob("tlring-*"):
        m = re.match(r"tlring-(\d+)-", p.name)
        if not m:
            continue
        try:
            os.kill(int(m.group(1)), 0)
        except ProcessLookupError:
            try:
                p.unlink()
                n += 1
            # tlint: disable=TL005(stale-segment sweep races other processes unlinking the same file)
            except OSError:
                pass
        # tlint: disable=TL005(pid exists under another uid — its segment is not ours to sweep)
        except PermissionError:
            pass  # pid exists under another uid — leave it
    return n


def ring_supported() -> bool:
    from tensorlink_tpu.native import load_tlring

    return load_tlring() is not None
