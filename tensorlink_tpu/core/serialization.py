"""Pickle-free structured array serialization.

Re-specification of the reference's safe tensor wire format
(ml/utils.py:569-660: JSON structure skeleton + safetensors blob, handling
Tensor/dict/list/tuple/DynamicCache/ModelOutput with *no pickle*), designed
for JAX arrays and a single contiguous frame:

    MAGIC "TLTS" | version u8 | header_len u32le | header JSON | payload

The header carries the container tree with ``{"__arr__": i}`` placeholders and
an array table (dtype, shape, offset, nbytes). The payload is the raw
little-endian array bytes, 64-byte aligned so a receiver can map them
zero-copy into jax/numpy. bfloat16 and fp8 ride on ``ml_dtypes``.

Custom structured objects (KV caches, model outputs) register with
:func:`register_struct` — symmetric named encode/decode, never code execution.
"""

from __future__ import annotations

import json
from typing import Any, Callable

import numpy as np

try:  # ml_dtypes ships with jax; gives numpy bfloat16/fp8 dtypes
    import ml_dtypes

    _EXTRA_DTYPES = {
        "bfloat16": np.dtype(ml_dtypes.bfloat16),
        "float8_e4m3fn": np.dtype(ml_dtypes.float8_e4m3fn),
        "float8_e5m2": np.dtype(ml_dtypes.float8_e5m2),
    }
except ImportError:  # pragma: no cover
    _EXTRA_DTYPES = {}

MAGIC = b"TLTS"
VERSION = 1
_ALIGN = 64

# name -> (to_tree, from_tree); to_tree returns a JSON-able tree possibly
# containing arrays, from_tree reconstructs the object.
# tlint: disable=TL006(codec registry — populated once at import by register_struct, read-only after)
_STRUCTS: dict[str, tuple[Callable[[Any], Any], Callable[[Any], Any]]] = {}
# tlint: disable=TL006(codec registry — populated once at import by register_struct, read-only after)
_STRUCT_TYPES: dict[type, str] = {}


def register_struct(name: str, cls: type, to_tree, from_tree) -> None:
    _STRUCTS[name] = (to_tree, from_tree)
    _STRUCT_TYPES[cls] = name


def _dtype_name(dt: np.dtype) -> str:
    for name, d in _EXTRA_DTYPES.items():
        if dt == d:
            return name
    return dt.name


def _dtype_from_name(name: str) -> np.dtype:
    if name in _EXTRA_DTYPES:
        return _EXTRA_DTYPES[name]
    return np.dtype(name)


def _is_array(x: Any) -> bool:
    if isinstance(x, np.ndarray):
        return True
    # jax.Array without importing jax at module load (network proc must not
    # import jax — same reason the reference keeps torch out of its network
    # process, SURVEY §1).
    return type(x).__module__.startswith("jax") and hasattr(x, "__array__")


def encode(obj: Any) -> memoryview:
    """Serialize a nested container of arrays/scalars into one frame —
    returned as a bytes-compatible ``memoryview`` built in place with ONE
    copy per array (``bytes(encode(x))`` where a true ``bytes`` is
    required, e.g. ctypes ``c_char_p``)."""
    arrays: list[np.ndarray] = []
    table: list[dict[str, Any]] = []

    def walk(x: Any) -> Any:
        if _is_array(x):
            a = np.asarray(x)
            if not a.flags.c_contiguous:
                a = np.ascontiguousarray(a)
            idx = len(arrays)
            arrays.append(a)
            table.append({"dtype": _dtype_name(a.dtype), "shape": list(a.shape)})
            return {"__arr__": idx}
        if isinstance(x, (np.generic,)):
            return walk(np.asarray(x))
        if isinstance(x, bytes):
            return {"__bytes__": x.hex()}
        if isinstance(x, dict):
            return {"__dict__": [[walk(k), walk(v)] for k, v in x.items()]}
        if isinstance(x, tuple):
            return {"__tuple__": [walk(v) for v in x]}
        if isinstance(x, list):
            return [walk(v) for v in x]
        if x is None or isinstance(x, (bool, int, str)):
            return x
        if isinstance(x, float):
            return x
        name = _STRUCT_TYPES.get(type(x))
        if name is not None:
            return {"__struct__": name, "tree": walk(_STRUCTS[name][0](x))}
        raise TypeError(
            f"cannot serialize {type(x).__name__} without register_struct()"
        )

    tree = walk(obj)
    offset = 0
    for a, meta in zip(arrays, table):
        offset = (offset + _ALIGN - 1) // _ALIGN * _ALIGN
        meta["offset"] = offset
        meta["nbytes"] = a.nbytes
        offset += a.nbytes

    header = json.dumps({"tree": tree, "arrays": table}).encode()
    # single-copy, single-touch assembly: np.empty (no zero-fill — a
    # bytearray would pay a full memory write just being created) and
    # np.copyto each array straight into place; only the alignment gaps are
    # explicitly zeroed so no uninitialized heap bytes ever leave the
    # process. tobytes()+join paid TWO full copies per array — a 256 MB
    # activation framed in ~700 ms on this box vs ~60 ms here. Returns the
    # buffer's memoryview (bytes-compatible for socket/file/shm writes).
    prefix = 9 + len(header)
    buf = np.empty(prefix + offset, np.uint8)
    mv = memoryview(buf)
    mv[0:4] = MAGIC
    mv[4] = VERSION
    mv[5:9] = len(header).to_bytes(4, "little")
    mv[9:prefix] = header
    pos = 0
    for a, meta in zip(arrays, table):
        if meta["offset"] != pos:  # zero the alignment gap
            buf[prefix + pos : prefix + meta["offset"]] = 0
        n = meta["nbytes"]
        if n:
            np.copyto(
                buf[prefix + meta["offset"] : prefix + meta["offset"] + n],
                a.reshape(-1).view(np.uint8),
            )
        pos = meta["offset"] + n
    return mv


def decode(data: bytes | memoryview, *, copy: bool = False) -> Any:
    """Inverse of :func:`encode`. Arrays come back as numpy views over the
    input buffer (zero-copy) unless ``copy=True``."""
    mv = memoryview(data)
    if len(mv) < 9:
        raise ValueError(f"truncated TLTS frame: {len(mv)} bytes")
    if bytes(mv[:4]) != MAGIC:
        raise ValueError("bad magic: not a TLTS frame")
    if mv[4] != VERSION:
        raise ValueError(f"unsupported TLTS version {mv[4]}")
    hlen = int.from_bytes(mv[5:9], "little")
    if 9 + hlen > len(mv):
        raise ValueError("truncated TLTS frame: header exceeds buffer")
    header = json.loads(bytes(mv[9 : 9 + hlen]).decode())
    payload = mv[9 + hlen :]

    def get_array(i: int) -> np.ndarray:
        meta = header["arrays"][i]
        dt = _dtype_from_name(meta["dtype"])
        if meta["offset"] + meta["nbytes"] > len(payload):
            raise ValueError(
                f"truncated TLTS frame: array {i} needs bytes up to "
                f"{meta['offset'] + meta['nbytes']}, payload has {len(payload)}"
            )
        raw = payload[meta["offset"] : meta["offset"] + meta["nbytes"]]
        a = np.frombuffer(raw, dtype=dt).reshape(meta["shape"])
        return a.copy() if copy else a

    def walk(x: Any) -> Any:
        if isinstance(x, dict):
            if "__arr__" in x:
                return get_array(x["__arr__"])
            if "__bytes__" in x:
                return bytes.fromhex(x["__bytes__"])
            if "__dict__" in x:
                return {walk(k): walk(v) for k, v in x["__dict__"]}
            if "__tuple__" in x:
                return tuple(walk(v) for v in x["__tuple__"])
            if "__struct__" in x:
                name = x["__struct__"]
                if name not in _STRUCTS:
                    raise ValueError(f"unknown struct {name!r}")
                return _STRUCTS[name][1](walk(x["tree"]))
            raise ValueError(f"malformed node: {list(x)[:3]}")
        if isinstance(x, list):
            return [walk(v) for v in x]
        return x

    return walk(header["tree"])


def content_digest(obj: Any) -> str:
    """Stable sha256 over an object's TLTS encoding — an integrity tag for
    payloads that cross the wire AND a process boundary (migration blobs:
    the importer recomputes the digest before adopting KV bytes, so a
    corrupted or reordered-and-reassembled transfer fails loudly into the
    re-prefill fallback instead of decoding from garbage pages)."""
    import hashlib

    return hashlib.sha256(bytes(encode(obj))).hexdigest()


def encode_to_file(obj: Any, path) -> int:
    """Spill large frames to disk (reference connection.py:110-128 spills
    >20 MB buffers to tmp files). Returns bytes written."""
    data = encode(obj)
    with open(path, "wb") as f:
        f.write(data)
    return len(data)


def decode_from_file(path) -> Any:
    with open(path, "rb") as f:
        return decode(f.read(), copy=True)
