"""Deterministic fault injection for the P2P/ML stack.

A :class:`FaultPlan` is a *seeded* list of rules keyed on named sites.
Each site is a point in the stack where a real deployment fails — a frame
on the wire, a decode-session step on a worker, an optimizer step mid
fine-tune — and each rule says *what* goes wrong there (drop / delay /
duplicate / crash / error) and *when* (the nth matching call, or a seeded
coin flip). Given the same seed and the same call sequence, a plan makes
identical decisions every run, so a chaos test that kills a worker on the
4th decode step kills it on the 4th decode step forever.

Wired sites:

- ``p2p.send``        — every outbound frame (p2p/connection.py::send_frame);
  supports drop / delay / dup.
- ``connection.frame`` — every inbound frame (p2p/connection.py::run);
  supports drop / delay / dup.
- ``worker.session_step`` — every session-carrying FORWARD a worker applies
  (ml/worker.py::_forward); supports error / crash.
- ``worker.train_step``   — every optimizer step (ml/worker.py::_optimizer);
  supports error / crash.
- ``worker.cont_step``    — every continuous-batching decode chunk over the
  worker's slot engine (ml/worker.py::_cont_step); supports error / crash.
- ``worker.drain``        — a DRAIN verb arriving at a worker
  (ml/worker.py::_drain); supports error / crash (a worker that dies the
  moment it is asked to shed its slots).
- ``migrate.export``      — per live slot a drain tries to freeze+export
  (ml/worker.py::_drain_engine); supports error / crash.
- ``migrate.wire``        — the MIGRATE page-transfer send on the source
  (ml/worker.py::_ship_migration); supports drop / delay / dup / crash —
  dup really sends the staging frame twice (idempotency is the
  destination's job), drop skips the send (the fallback ladder's trigger).
- ``migrate.import``      — a MIGRATE staging arriving at the destination
  (ml/worker.py::_migrate_in); supports error / crash (the
  kill-the-destination-mid-migration case).
- ``worker.handoff``      — per prefill-completed slot a prefill-pool
  worker tries to ship to its decode pool (ml/worker.py::_run_handoffs);
  supports error (the slot takes the re-prefill redirect rung) / crash
  (a prefill worker dying at the prefill→decode boundary). The wire
  transfer itself shares ``migrate.wire`` with the drain path.
- ``validator.crash``     — the validator control plane dying at a chosen
  point (ml/validator.py admission / recovery paths, tools/soak.py crash
  schedule); supports crash / error. The soak harness keys this site on
  the epoch so a seeded schedule kills the control plane at the same
  instant every run.
- ``control.frame``       — a validator control verb crossing the net
  process (nodes/roles.py: drain_worker / create_job / set_replica_set /
  set_handoff_pool / expire_migrations); supports error / delay / crash
  (drop is mapped to error: a request/reply verb that vanishes surfaces
  to the caller as a loud failure, not a silent hang).
- ``journal.write``       — a control-journal append
  (core/journal.py::ControlJournal.append); supports drop (the record is
  silently lost — replay-tolerance case) / error / delay.
- ``kvtier.demote``       — a refcount-0 prefix page demoting to the
  host-RAM tier (engine/continuous.py::_demote_page); supports error
  (the page is destroyed instead — seed behavior for that page) / crash.
- ``kvtier.fetch``        — a host-tier promote or fleet prefix pull at
  admission (engine/continuous.py promote/pull rungs); supports error
  (the rung degrades to the next: fleet pull, then re-prefill) / crash
  (a worker dying mid-pull — the chaos suite's tiered-cache kill case).

Site names are REGISTERED (:data:`SITES`): a rule naming an unknown site
fails loudly at plan construction instead of silently never firing — a
chaos config can't typo a site into a no-op.

Zero overhead when disabled: the network process guards every site with
``if faults.ENABLED:`` (a module bool that is False unless a plan was
installed), and the ML worker holds ``self.faults = None`` unless its
NodeConfig carries a plan — the default configuration executes no
fault-site code on the hot decode path beyond one predicate.

Plans are plain dicts so they ride ``NodeConfig.faults`` through the
spawn-pickled network process and the ML executor alike::

    WorkerConfig(faults={
        "seed": 7,
        "rules": [
            {"site": "worker.session_step", "op": "crash", "nth": 4},
            {"site": "p2p.send", "op": "dup", "prob": 1.0,
             "key_substr": "fwd", "max_fires": None},
        ],
    })
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

OPS = ("drop", "delay", "dup", "crash", "error")

# The registered fault-site names — every site wired in the stack. A rule
# naming anything else raises at construction (FaultRule.__post_init__),
# so a typo'd chaos config fails the test that installs it instead of
# silently injecting nothing.
SITES = (
    "p2p.send",
    "connection.frame",
    "worker.session_step",
    "worker.train_step",
    "worker.cont_step",
    "worker.drain",
    "migrate.export",
    "migrate.wire",
    "migrate.import",
    "worker.handoff",
    "validator.crash",
    "control.frame",
    "journal.write",
    "kvtier.demote",
    "kvtier.fetch",
)


class FaultInjected(RuntimeError):
    """An injected *recoverable* failure (op="error")."""


class FaultCrash(BaseException):
    """An injected node death (op="crash"). Derives from BaseException so
    generic ``except Exception`` error-reply paths cannot swallow it — the
    run loop that catches it must take the node down, not answer the
    request."""


@dataclass
class FaultRule:
    site: str
    op: str  # drop | delay | dup | crash | error
    nth: int | None = None  # fire on exactly the nth MATCHING call (1-based)
    prob: float = 0.0  # else: fire with this seeded probability
    delay_s: float = 0.05
    key_substr: str = ""  # only calls whose key contains this substring
    max_fires: int | None = 1  # None = unlimited
    # mutable per-run state
    seen: int = 0
    fires: int = 0

    def __post_init__(self):
        if self.op not in OPS:
            raise ValueError(f"unknown fault op {self.op!r} (want one of {OPS})")
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r} — registered sites: "
                f"{', '.join(SITES)} (a typo here would make the rule a "
                "silent no-op)"
            )


@dataclass
class FaultPlan:
    seed: int = 0
    rules: list[FaultRule] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        rules = []
        for r in d.get("rules", []):
            r = dict(r)
            rules.append(
                FaultRule(
                    site=r["site"],
                    op=r["op"],
                    nth=r.get("nth"),
                    prob=float(r.get("prob", 0.0)),
                    delay_s=float(r.get("delay_s", 0.05)),
                    key_substr=str(r.get("key_substr", "")),
                    max_fires=r.get("max_fires", 1),
                )
            )
        return cls(seed=int(d.get("seed", 0)), rules=rules)

    def _coin(self, site: str, n: int) -> float:
        """Deterministic uniform in [0, 1) for the nth call at a site —
        a hash, not an RNG stream, so interleaved sites never perturb each
        other's draws."""
        h = hashlib.sha256(f"{self.seed}:{site}:{n}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2**64

    def inject(self, site: str, key: str = ""):
        """Decide this call's fate. Returns ``None`` (proceed), ``"drop"``,
        ``"dup"``, or ``("delay", seconds)``; raises :class:`FaultInjected`
        (op="error") or :class:`FaultCrash` (op="crash").

        Every matching rule counts every call (so interleaved rules keep
        deterministic nth semantics); the FIRST rule that fires decides the
        action."""
        decided: FaultRule | None = None
        for r in self.rules:
            if r.site != site:
                continue
            if r.key_substr and r.key_substr not in key:
                continue
            r.seen += 1
            if decided is not None:
                continue  # shadowed by an earlier rule, but still counted
            if r.max_fires is not None and r.fires >= r.max_fires:
                continue
            if r.nth is not None:
                fire = r.seen == r.nth
            else:
                fire = self._coin(site, r.seen) < r.prob
            if not fire:
                continue
            r.fires += 1
            decided = r
        if decided is None:
            return None
        if decided.op == "error":
            raise FaultInjected(
                f"injected fault at {site} (call {decided.seen}, key={key!r})"
            )
        if decided.op == "crash":
            raise FaultCrash(
                f"injected crash at {site} (call {decided.seen}, key={key!r})"
            )
        if decided.op == "delay":
            return ("delay", decided.delay_s)
        return decided.op  # drop | dup


# ---------------------------------------------------------------------------
# Process-global plan (network process sites). The ML executor holds its own
# per-node instance instead (ml/worker.py) so several in-process worker nodes
# in a test never share fault state.
# ---------------------------------------------------------------------------

ENABLED = False
_PLAN: FaultPlan | None = None


def install(plan: FaultPlan) -> None:
    global ENABLED, _PLAN
    _PLAN = plan
    ENABLED = True


def uninstall() -> None:
    global ENABLED, _PLAN
    _PLAN = None
    ENABLED = False


def inject(site: str, key: str = ""):
    """Module-level dispatch for sites guarded by ``if faults.ENABLED:``."""
    if _PLAN is None:
        return None
    return _PLAN.inject(site, key)


__all__ = [
    "SITES",
    "FaultPlan",
    "FaultRule",
    "FaultInjected",
    "FaultCrash",
    "install",
    "uninstall",
    "inject",
    "ENABLED",
]
