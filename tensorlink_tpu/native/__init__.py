"""Native (C++) runtime components with build-on-demand + pure-Python
fallback.

The reference has no native code of its own (SURVEY §2: 100% Python, all
native perf from dependencies); here the runtime hot paths are C++ where it
pays: the ML↔network shared-memory message ring (tlring.cpp). The library
compiles on first use with g++ into a per-user cache; import never fails —
``load_tlring()`` returns None when the toolchain or platform can't build,
and callers fall back to mp.Queue transports.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
from pathlib import Path

_SRC = Path(__file__).parent / "tlring.cpp"
_lib = None
_tried = False


def _cache_dir() -> Path:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return Path(base) / "tensorlink_tpu"


def _build() -> Path | None:
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src + sys.version.encode()).hexdigest()[:16]
    out = _cache_dir() / f"libtlring-{tag}.so"
    if out.exists():
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    tmp = out.with_suffix(f".{os.getpid()}.tmp.so")
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        "-o", str(tmp), str(_SRC), "-lpthread", "-lrt",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError, OSError):
        tmp.unlink(missing_ok=True)
        return None
    tmp.replace(out)
    return out


def load_tlring():
    """ctypes handle to the ring library, or None (fallback mode)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not sys.platform.startswith("linux"):
        return None
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError:
        return None
    lib.tlring_create.restype = ctypes.c_void_p
    lib.tlring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.tlring_attach.restype = ctypes.c_void_p
    lib.tlring_attach.argtypes = [ctypes.c_char_p]
    lib.tlring_write.restype = ctypes.c_int
    # payload as c_void_p: accepts bytes AND writable buffers
    # ((c_char * n).from_buffer(...)) so callers can write straight from a
    # serialization buffer without a bytes() copy (core/ring.py::put)
    lib.tlring_write.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_double,
    ]
    lib.tlring_next_size.restype = ctypes.c_int64
    lib.tlring_next_size.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.tlring_read.restype = ctypes.c_int64
    lib.tlring_read.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
    ]
    lib.tlring_close.argtypes = [ctypes.c_void_p]
    lib.tlring_detach.argtypes = [ctypes.c_void_p]
    lib.tlring_unlink.restype = ctypes.c_int
    lib.tlring_unlink.argtypes = [ctypes.c_char_p]
    _lib = lib
    return _lib
