// tlring — shared-memory message ring for the ML↔network process bridge.
//
// The reference parks tensors in POSIX shared memory and polls queues at
// 1 kHz under a global lock (nodes/shared_memory.py, torch_node.py:838-851,
// nodes/nodes.py:201-235). This is the native replacement: a byte-message
// ring over shm_open+mmap with process-shared pthread mutex/condvars —
// blocking reads (no polling), one copy per side, no pickling.
//
// Layout: [Header][data bytes (capacity)]
// Messages are u64 length-prefixed and wrap around the ring. Single
// logical producer / single logical consumer per ring (the Python wrapper
// serializes same-process producers with a lock).
//
// Build: g++ -O2 -shared -fPIC -o libtlring.so tlring.cpp -lpthread -lrt

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t MAGIC = 0x544c52494e470001ULL;  // "TLRING" v1

struct Header {
  uint64_t magic;
  uint64_t capacity;        // data area size in bytes
  uint64_t head;            // monotonic write offset (guarded by mu)
  uint64_t tail;            // monotonic read offset (guarded by mu)
  uint32_t closed;
  uint32_t _pad;
  pthread_mutex_t mu;
  pthread_cond_t nonempty;
  pthread_cond_t nonfull;
};

struct Ring {
  Header* hdr;
  uint8_t* data;
  uint64_t map_len;
  int owner;  // created (1) vs attached (0)
};

void abstime_in(double seconds, timespec* ts) {
  clock_gettime(CLOCK_REALTIME, ts);
  time_t sec = static_cast<time_t>(seconds);
  long nsec = static_cast<long>((seconds - static_cast<double>(sec)) * 1e9);
  ts->tv_sec += sec;
  ts->tv_nsec += nsec;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

uint64_t used(const Header* h) { return h->head - h->tail; }

void copy_in(Ring* r, uint64_t pos, const uint8_t* src, uint64_t len) {
  const uint64_t cap = r->hdr->capacity;
  const uint64_t off = pos % cap;
  const uint64_t first = (off + len <= cap) ? len : cap - off;
  memcpy(r->data + off, src, first);
  if (first < len) memcpy(r->data, src + first, len - first);
}

void copy_out(Ring* r, uint64_t pos, uint8_t* dst, uint64_t len) {
  const uint64_t cap = r->hdr->capacity;
  const uint64_t off = pos % cap;
  const uint64_t first = (off + len <= cap) ? len : cap - off;
  memcpy(dst, r->data + off, first);
  if (first < len) memcpy(dst + first, r->data, len - first);
}

}  // namespace

extern "C" {

// Returns opaque handle or nullptr.
void* tlring_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = sizeof(Header) + capacity;
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* h = static_cast<Header*>(mem);
  memset(h, 0, sizeof(Header));
  h->capacity = capacity;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_mutexattr_destroy(&ma);

  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->nonempty, &ca);
  pthread_cond_init(&h->nonfull, &ca);
  pthread_condattr_destroy(&ca);

  h->magic = MAGIC;  // last: attachers spin on it
  Ring* r = new Ring{h, reinterpret_cast<uint8_t*>(mem) + sizeof(Header),
                     total, 1};
  return r;
}

void* tlring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(sizeof(Header))) {
    close(fd);
    return nullptr;
  }
  void* mem =
      mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ | PROT_WRITE,
           MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* h = static_cast<Header*>(mem);
  if (h->magic != MAGIC) {
    munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  Ring* r = new Ring{h, reinterpret_cast<uint8_t*>(mem) + sizeof(Header),
                     static_cast<uint64_t>(st.st_size), 0};
  return r;
}

static int lock_mu(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {  // peer died holding the lock; state is a byte
    pthread_mutex_consistent(&h->mu);  // ring — counters stay coherent
    return 0;
  }
  return rc;
}

// 0 ok, -1 timeout, -2 closed, -3 message larger than capacity, -4 error
int tlring_write(void* rp, const uint8_t* buf, uint64_t len, double timeout_s) {
  Ring* r = static_cast<Ring*>(rp);
  Header* h = r->hdr;
  const uint64_t need = len + 8;
  if (need > h->capacity) return -3;
  timespec deadline;
  abstime_in(timeout_s, &deadline);
  if (lock_mu(h) != 0) return -4;
  while (h->capacity - used(h) < need && !h->closed) {
    int rc = pthread_cond_timedwait(&h->nonfull, &h->mu, &deadline);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
    if (rc == EOWNERDEAD) {  // peer died holding the lock mid-wait
      pthread_mutex_consistent(&h->mu);
      continue;
    }
    if (rc != 0) {  // persistent error: don't spin
      pthread_mutex_unlock(&h->mu);
      return -4;
    }
  }
  if (h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -2;
  }
  uint64_t le_len = len;  // little-endian hosts only (x86/ARM/TPU VMs)
  copy_in(r, h->head, reinterpret_cast<uint8_t*>(&le_len), 8);
  copy_in(r, h->head + 8, buf, len);
  h->head += need;
  pthread_cond_signal(&h->nonempty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// >=0: size of next message (kept in ring); -1 timeout, -2 closed+drained, -4 err
int64_t tlring_next_size(void* rp, double timeout_s) {
  Ring* r = static_cast<Ring*>(rp);
  Header* h = r->hdr;
  timespec deadline;
  abstime_in(timeout_s, &deadline);
  if (lock_mu(h) != 0) return -4;
  while (used(h) == 0) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    int rc = pthread_cond_timedwait(&h->nonempty, &h->mu, &deadline);
    if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
    if (rc == EOWNERDEAD) {  // peer died holding the lock mid-wait
      pthread_mutex_consistent(&h->mu);
      continue;
    }
    if (rc != 0) {  // persistent error: don't spin
      pthread_mutex_unlock(&h->mu);
      return -4;
    }
  }
  uint64_t len = 0;
  copy_out(r, h->tail, reinterpret_cast<uint8_t*>(&len), 8);
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(len);
}

// Copies the next message into buf (must be >= its size) and advances.
// Returns message size, or -4 on usage error.
int64_t tlring_read(void* rp, uint8_t* buf, uint64_t buflen) {
  Ring* r = static_cast<Ring*>(rp);
  Header* h = r->hdr;
  if (lock_mu(h) != 0) return -4;
  if (used(h) == 0) {
    pthread_mutex_unlock(&h->mu);
    return -4;
  }
  uint64_t len = 0;
  copy_out(r, h->tail, reinterpret_cast<uint8_t*>(&len), 8);
  if (len > buflen) {
    pthread_mutex_unlock(&h->mu);
    return -4;
  }
  copy_out(r, h->tail + 8, buf, len);
  h->tail += len + 8;
  pthread_cond_signal(&h->nonfull);
  pthread_mutex_unlock(&h->mu);
  return static_cast<int64_t>(len);
}

void tlring_close(void* rp) {
  Ring* r = static_cast<Ring*>(rp);
  Header* h = r->hdr;
  if (lock_mu(h) == 0) {
    h->closed = 1;
    pthread_cond_broadcast(&h->nonempty);
    pthread_cond_broadcast(&h->nonfull);
    pthread_mutex_unlock(&h->mu);
  }
}

void tlring_detach(void* rp) {
  Ring* r = static_cast<Ring*>(rp);
  munmap(r->hdr, r->map_len);
  delete r;
}

int tlring_unlink(const char* name) { return shm_unlink(name); }

}  // extern "C"
