"""Node runners — the user-facing processes (reference nodes/nodes.py).

``WorkerNode()`` / ``ValidatorNode()`` / ``UserNode()`` spawn their network
process (role server, never imports jax) and run the ML side in the calling
process: an event-driven executor thread for workers/validators, nothing for
users (the DistributedModel drives synchronously through ``send_request``).

Reference mapping: BaseNode/Worker/Validator/User (nodes/nodes.py:106-414)
with ``send_request`` (nodes.py:201-235) — minus the global mpc_lock, which
the per-request-future bridge (nodes/ipc.py) makes unnecessary.
"""

from __future__ import annotations

import multiprocessing as mp
import threading
from typing import Any

from tensorlink_tpu.core.config import (
    NodeConfig,
    UserConfig,
    ValidatorConfig,
    WorkerConfig,
)
from tensorlink_tpu.core.logging import get_logger
from tensorlink_tpu.nodes.ipc import BridgeQueues, MLBridge
from tensorlink_tpu.nodes.roles import run_server


def _spawn_ctx():
    # spawn, not fork: the ML process holds jax/TPU state that must never be
    # inherited by the network process (reference nodes.py:103 does the same
    # for CUDA).
    return mp.get_context("spawn")


class BaseNode:
    CONFIG = NodeConfig

    def __init__(self, config: NodeConfig | None = None, **overrides: Any):
        if config is None:
            config = self.CONFIG(**overrides)
        elif overrides:
            from dataclasses import replace

            config = replace(config, **overrides)
        self.config = config
        self.role = config.role
        if config.json_logs:
            # flip BEFORE the first logger so every line of this process
            # (and the executor threads it spawns) is one JSON object
            from tensorlink_tpu.core.logging import set_json_logs

            set_json_logs(True)
        self.log = get_logger(f"node.{self.role}{config.duplicate}")
        self.queues = self._make_queues()
        self.bridge = MLBridge(self.queues)
        self._proc: mp.process.BaseProcess | None = None
        self._ml_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.node_id: str | None = None
        self.port: int | None = None

    def _make_queues(self) -> BridgeQueues:
        """Native shm message ring when available (C++ tlring — blocking
        reads, TLTS payloads, no pickling); mp.Queue otherwise."""
        if self.config.native_ipc:
            try:
                from tensorlink_tpu.core.ring import RingChannel, ring_supported

                if ring_supported():
                    return BridgeQueues(
                        cmd=RingChannel(), resp=RingChannel(), work=RingChannel()
                    )
            except Exception as e:
                self.log.warning("native ipc unavailable (%s); using mp.Queue", e)
        ctx = _spawn_ctx()
        return BridgeQueues(cmd=ctx.Queue(), resp=ctx.Queue(), work=ctx.Queue())

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "BaseNode":
        if self._proc is not None:
            return self
        ctx = _spawn_ctx()
        self._proc = ctx.Process(
            target=run_server,
            args=(self.role, self.config, self.queues),
            name=f"net-{self.role}",
            daemon=True,
        )
        self._proc.start()
        rid, ok, info = self.queues.resp.get(timeout=60)
        if rid != -1 or not ok:
            raise RuntimeError(f"network process failed to start: {info}")
        self.node_id, self.port = info["id"], info["port"]
        self.bridge.start()
        if self.config.seed_validators:
            self.send_request("bootstrap", {})
        self._start_ml()
        self.log.info("up id=%s port=%s", self.node_id[:12], self.port)
        return self

    def _start_ml(self) -> None:  # overridden by roles with an ML executor
        pass

    def stop(self) -> None:
        import queue as queue_mod

        self._stop.set()
        if self._ml_thread is not None:
            try:
                self.queues.work.put(("_stop", None))
            # tlint: disable=TL005(ring closed by a dead peer / full — the join below is the real stop)
            except (OSError, EOFError, queue_mod.Full):
                pass  # ring closed by a dead peer / full — join regardless
            self._ml_thread.join(timeout=10)
            self._ml_thread = None
        if self._proc is not None:
            try:
                self.queues.cmd.put((0, "_stop", None))
            # tlint: disable=TL005(network process already gone — the join below is the real stop)
            except (OSError, EOFError, queue_mod.Full):
                pass
            self._proc.join(timeout=10)
            if self._proc.is_alive():
                self._proc.terminate()
            self._proc = None
        self.bridge.close()
        for q in (self.queues.cmd, self.queues.resp, self.queues.work):
            release = getattr(q, "release", None)
            if release is not None:
                try:
                    release()
                # tlint: disable=TL005(teardown of shm rings whose peer may have released first)
                except Exception:
                    pass

    def crash(self) -> None:
        """Abrupt node death (fault injection, core/faults.py): kill the
        network process with no shutdown courtesy so peers observe a dropped
        connection — exactly what a real worker loss looks like. Unlike
        :meth:`stop`, nothing is flushed and the ML loop is expected to be
        the caller (it returns right after). ``stop()`` stays safe to call
        afterwards."""
        self._stop.set()
        proc, self._proc = self._proc, None
        if proc is not None:
            proc.kill()
            proc.join(timeout=5)
        self._ml_thread = None  # the calling ML thread is exiting itself
        self.bridge.close()

    def __enter__(self) -> "BaseNode":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- RPC into the network process ----------------------------------
    def send_request(self, verb: str, payload: Any = None, timeout: float = 30.0):
        return self.bridge.request(verb, payload, timeout=timeout)

    def status(self) -> dict:
        return self.send_request("status")

    def connect_to(self, host: str, port: int) -> str:
        return self.send_request("connect", {"host": host, "port": port})

    @property
    def address(self) -> tuple[str, int]:
        return (self.config.effective_host(), self.port or 0)


class WorkerNode(BaseNode):
    """Offers device capacity; runs the DistributedWorker executor
    (reference Worker, nodes/nodes.py:256-301)."""

    CONFIG = WorkerConfig

    def _start_ml(self) -> None:
        from tensorlink_tpu.ml.worker import DistributedWorker

        self.executor = DistributedWorker(self)
        self.send_request("set_capacity", self.executor.capacity())
        self._ml_thread = threading.Thread(
            target=self.executor.run, name="ml-worker", daemon=True
        )
        self._ml_thread.start()


class ValidatorNode(BaseNode):
    """Plans jobs, tracks workers, serves the HTTP API (reference Validator,
    nodes.py:304-377 + TensorlinkAPI, api/node.py:523-541)."""

    CONFIG = ValidatorConfig

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.api = None

    def _start_ml(self) -> None:
        from tensorlink_tpu.ml.validator import DistributedValidator

        self.executor = DistributedValidator(self)
        self._ml_thread = threading.Thread(
            target=self.executor.run, name="ml-validator", daemon=True
        )
        self._ml_thread.start()
        if self.config.endpoint:
            from tensorlink_tpu.api.server import TensorlinkAPI

            self.api = TensorlinkAPI(
                self,
                self.executor,
                host=self.config.endpoint_host,
                port=self.config.endpoint_port,
            ).start()

    def stop(self) -> None:
        if self.api is not None:
            self.api.stop()
            self.api = None
        super().stop()


class UserNode(BaseNode):
    """Requests models; the DistributedModel drives the job from the calling
    thread (reference User, nodes.py:380-414)."""

    CONFIG = UserConfig
