"""Node runners and role servers.

The reference splits every node into a networking process and an ML process
bridged by ``mp.Queue`` pairs + a global lock polled at 1 kHz
(nodes/nodes.py:139-147, ml/worker.py:1349). The split survives here — the
network process must never import jax, exactly as the reference keeps torch
out of it — but the bridge is event-driven: per-request futures instead of a
global ``mpc_lock``, blocking queue gets instead of poll loops.
"""

from tensorlink_tpu.nodes.runners import UserNode, ValidatorNode, WorkerNode

__all__ = ["UserNode", "ValidatorNode", "WorkerNode"]
