"""ML↔network process bridge.

Reference equivalent: ``BaseNode.send_request`` — a blocking round-trip
through two ``mp.Queue``s under one global ``mpc_lock`` (nodes/nodes.py:
201-235), answered by a 1 ms poll loop (p2p/torch_node.py:932-935). That
lock serializes *all* ML↔net traffic; here each request carries its own id
and resolves its own future, so any number of ML threads can have requests
in flight, and the network side executes each command as its own asyncio
task (a slow ``tensor_request`` does not block a ``status`` call).

Three queues:

- ``cmd``   ML → net: ``(rid, verb, payload)`` — commands for the net loop.
- ``resp``  net → ML: ``(rid, ok, result)`` — command results.
- ``work``  net → ML: ``(kind, item)`` — events the ML executor consumes
  with a *blocking* get (no polling; the reference's main_loop polls five
  queues per module per tick, ml/worker.py:1386-1435).

Payloads may contain numpy arrays (pickled efficiently by mp via buffer
protocol). jax arrays must be converted to numpy before crossing.
"""

from __future__ import annotations

import asyncio
import itertools
import multiprocessing as mp
import queue as queue_mod
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable


class RemoteError(RuntimeError):
    """A command failed in the network process; carries its traceback."""


@dataclass
class BridgeQueues:
    """The picklable bundle handed to the spawned network process."""

    cmd: mp.Queue = field(default_factory=mp.Queue)
    resp: mp.Queue = field(default_factory=mp.Queue)
    work: mp.Queue = field(default_factory=mp.Queue)


class MLBridge:
    """ML-process side: issue commands, consume work events."""

    def __init__(self, queues: BridgeQueues):
        self.q = queues
        self._pending: dict[int, queue_mod.Queue] = {}
        self._lock = threading.Lock()
        self._rid = itertools.count(1)
        self._dispatcher: threading.Thread | None = None
        self._closed = threading.Event()

    def start(self) -> None:
        if self._dispatcher:
            return
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="ipc-dispatch", daemon=True
        )
        self._dispatcher.start()

    def _dispatch_loop(self) -> None:
        while not self._closed.is_set():
            try:
                rid, ok, result = self.q.resp.get(timeout=0.5)
            # tlint: disable=TL005(the poll timeout IS the loop cadence — Empty means check the stop flag)
            except queue_mod.Empty:
                continue
            except (EOFError, OSError):
                break
            with self._lock:
                slot = self._pending.pop(rid, None)
            if slot is not None:
                slot.put((ok, result))

    def request(self, verb: str, payload: Any = None, timeout: float = 30.0) -> Any:
        """Blocking command round-trip; safe from any ML thread."""
        rid = next(self._rid)
        slot: queue_mod.Queue = queue_mod.Queue(1)
        with self._lock:
            self._pending[rid] = slot
        self.q.cmd.put((rid, verb, payload))
        try:
            ok, result = slot.get(timeout=timeout)
        except queue_mod.Empty:
            with self._lock:
                self._pending.pop(rid, None)
            raise TimeoutError(f"ipc command {verb!r} timed out after {timeout}s")
        if not ok:
            raise RemoteError(f"{verb}: {result}")
        return result

    def notify(self, verb: str, payload: Any = None) -> None:
        """Fire-and-forget command (no reply expected)."""
        self.q.cmd.put((0, verb, payload))

    def get_work(self, timeout: float | None = None):
        """Blocking get of the next work event; None on timeout."""
        try:
            return self.q.work.get(timeout=timeout)
        except queue_mod.Empty:
            return None

    def close(self) -> None:
        self._closed.set()


class NetBridge:
    """Network-process side: executes commands against the role server.

    Queue writes from the event loop go through an executor thread — the
    native ring's put blocks when the consumer lags, and a blocked event
    loop would stall all networking (heartbeats, every connection)."""

    def __init__(self, queues: BridgeQueues):
        self.q = queues
        self._task: asyncio.Task | None = None

    def post_work(self, kind: str, item: Any) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is not None:
            loop.run_in_executor(None, self._safe_put, self.q.work, (kind, item))
        else:
            self._safe_put(self.q.work, (kind, item))

    @staticmethod
    def _safe_put(q, item) -> None:
        try:
            q.put(item)
        # tlint: disable=TL005(_safe_put's contract: consumer gone at shutdown means nothing to deliver to)
        except Exception:
            pass  # consumer gone (shutdown) — nothing to deliver to

    async def serve(self, dispatch: Callable[[str, Any], Any]) -> None:
        """Pump the cmd queue; run each command as its own task.

        ``dispatch(verb, payload)`` is an async callable on the role server.
        """
        loop = asyncio.get_running_loop()
        while True:
            item = await loop.run_in_executor(None, self._blocking_get)
            if item is None:
                continue
            rid, verb, payload = item
            if verb == "_stop":
                break
            asyncio.ensure_future(self._run_cmd(dispatch, rid, verb, payload))

    def _blocking_get(self):
        try:
            return self.q.cmd.get(timeout=0.5)
        except queue_mod.Empty:
            return None
        except (EOFError, OSError):
            return (0, "_stop", None)

    async def _run_cmd(self, dispatch, rid: int, verb: str, payload: Any) -> None:
        try:
            result = await dispatch(verb, payload)
            ok = True
        except Exception:
            result = traceback.format_exc(limit=20)
            ok = False
        if rid:  # rid 0 = notify, no reply wanted
            await asyncio.get_running_loop().run_in_executor(
                None, self._safe_put, self.q.resp, (rid, ok, result)
            )
