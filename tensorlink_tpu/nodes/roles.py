"""Role servers — the network-process half of each node.

Reference equivalents: WorkerThread / ValidatorThread / UserThread
(nodes/worker_thread.py, validator_thread.py, user_thread.py) running inside
the spawned networking process. Redesigned around asyncio + the IPC bridge:
wire handlers post work events; the ML process answers with commands; no
shared-memory parking lots or poll loops.

Job lifecycle (asyncio version of SURVEY §3.2):

1. user ML → ``request_job`` cmd → UserServer sends JOB_REQ to a validator.
2. ValidatorServer posts ``job_req`` work → DistributedValidator plans
   (sharding planner) → ``recruit`` cmd → ValidatorServer asks each chosen
   worker JOB_REQ (3 s accept window, reference validator_thread.py:845-887);
   workers reserve capacity and accept.
3. Validator replies to the user's JOB_REQ with the plan + worker addresses
   and stores the job in the DHT.
4. The user connects to each worker and ships MODULE (plan slice + model
   config + checkpoint ref — never code; reference ships serialized modules,
   torch_node.py:879-924). Worker ML loads and the MODULE request resolves
   with MODULE_LOADED.
5. FORWARD / BACKWARD / GENERATE are correlated tensor requests straight to
   the owning worker.

No jax imports in this module.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from pathlib import Path
from typing import Any

from tensorlink_tpu.core.config import NodeConfig
from tensorlink_tpu.nodes.ipc import BridgeQueues, NetBridge
from tensorlink_tpu.p2p import protocol as proto
from tensorlink_tpu.p2p.connection import Connection
from tensorlink_tpu.p2p.tensor_node import TensorNode

RECRUIT_TIMEOUT = 3.0  # reference validator_thread.py:871
JOB_REQS_PER_MINUTE = 30  # reference validator_thread.py:508-516
JOB_REQ_TIMEOUT = 120.0  # reference user_thread.py:406
MODULE_LOAD_TIMEOUT = 150.0  # reference MAX_WAIT_TIME ml/module.py:58


class RoleServer(TensorNode):
    """TensorNode + IPC command surface shared by all roles."""

    def __init__(self, cfg: NodeConfig, queues: BridgeQueues):
        if getattr(cfg, "faults", None):
            # deterministic fault injection (core/faults.py): install the
            # plan process-globally HERE — this network process is one OS
            # process per node, so the global cannot leak across nodes
            from tensorlink_tpu.core import faults

            faults.install(faults.FaultPlan.from_dict(cfg.faults))
        super().__init__(
            cfg.role,
            host=cfg.effective_host(),
            port=cfg.port or 0,
            key_dir=cfg.key_dir,
            local_test=cfg.local_test,
            identity_name=cfg.role + cfg.duplicate,
        )
        self.cfg = cfg
        self.bridge = NetBridge(queues)
        self.work = queues.work  # TensorNode.post_work target
        self.capacity: dict[str, Any] = {
            "hbm_bytes": 0.0,
            "n_devices": 0,
            "slice_id": "",
            "role": cfg.role,
            "training": True,
        }
        self.reserved: dict[str, float] = {}  # job_id -> reserved bytes
        self.register(proto.STATS_REQUEST, self._handle_stats_request)

    def post_work(self, kind: str, item: Any) -> None:
        # executor-offloaded put: the ring transport blocks when full and
        # must never stall the event loop (see NetBridge.post_work)
        self.bridge.post_work(kind, item)

    # -- entrypoint (net process main) ----------------------------------
    def main(self) -> None:
        self.start()  # event loop thread + listener
        self.port_mapper = None
        if self.cfg.upnp and not self.cfg.local_test:
            # public-network mode: map the listen port on the NAT gateway
            # (reference smart_node.py:1200-1312; best-effort — a missing
            # gateway degrades to a warning, not a dead node)
            from tensorlink_tpu.p2p.upnp import PortMapper

            self.port_mapper = PortMapper()
            ext_ip = self.port_mapper.map_port(self.port)
            if ext_ip:
                self.capacity["external_addr"] = [ext_ip, self.port]
        info = {"port": self.port, "id": self.node_id, "role": self.role}
        self.bridge.q.resp.put((-1, True, info))
        self.on_started()
        fut = asyncio.run_coroutine_threadsafe(
            self.bridge.serve(self.dispatch), self._loop
        )
        try:
            fut.result()  # blocks until _stop
        finally:
            try:
                self.on_shutdown()
            except Exception:
                self.log.exception("shutdown hook failed")
            if self.port_mapper is not None:
                self.port_mapper.close()
            self.stop()

    def on_started(self) -> None:
        """Role hook: schedule background tasks after the listener is up."""

    def on_shutdown(self) -> None:
        """Role hook: flush state before the event loop stops."""

    # -- command dispatch ----------------------------------------------
    async def dispatch(self, verb: str, payload: Any) -> Any:
        fn = getattr(self, f"cmd_{verb}", None)
        if fn is None:
            raise ValueError(f"unknown ipc verb {verb!r}")
        return await fn(payload or {})

    def _conn(self, peer: str) -> Connection:
        conn = self.connections.get(peer)
        if conn is None:
            raise ConnectionError(f"no connection to {peer[:12]}")
        return conn

    async def _control_fault(self, verb: str) -> None:
        """``control.frame`` fault site (core/faults.py): fires at the top
        of the control verbs that mutate fleet state (drain / recruit /
        pool- and replica-set pushes / ticket expiry). "drop" maps to a
        raised error — a control frame that vanishes must surface to the
        caller as a loud failure, never a silent hang; "crash"
        (FaultCrash) propagates so the run loop takes the node down."""
        from tensorlink_tpu.core import faults

        if not faults.ENABLED:
            return
        act = faults.inject("control.frame", verb)
        if act == "drop":
            raise faults.FaultInjected(
                f"injected control-frame drop at {verb}"
            )
        if isinstance(act, tuple) and act[0] == "delay":
            await asyncio.sleep(act[1])

    async def cmd_status(self, p) -> dict:
        return self.status()

    async def cmd_validators(self, p) -> list[str]:
        return self.validator_ids()

    async def cmd_peers(self, p) -> list[str]:
        """Full node ids of live connections (``status`` truncates ids for
        display; session recovery needs exact membership to tell which
        stage workers died)."""
        return list(self.connections)

    async def cmd_bootstrap(self, p) -> int:
        seeds = [tuple(s) for s in p.get("seeds", self.cfg.seed_validators)]
        return await self.bootstrap(seeds, retries=p.get("retries", 3))

    async def cmd_connect(self, p) -> str:
        conn = await self.connect(p["host"], p["port"])
        return conn.node_id

    async def cmd_disconnect(self, p) -> bool:
        """Close the connection to a peer by id (or unique id prefix) —
        ops/testing surface for pruning a mesh link. An ambiguous prefix
        matches nothing rather than severing an arbitrary peer."""
        pid = p.get("peer", "")
        if not pid:
            return False
        matches = [c for nid, c in self.connections.items()
                   if nid.startswith(pid)]
        if len(matches) != 1:
            return False
        await matches[0].close()
        return True

    async def cmd_dht_get(self, p):
        return await self.dht_query(p["key"])

    async def cmd_dht_store(self, p) -> bool:
        await self.dht_store_global(p["key"], p["value"])
        return True

    async def cmd_set_capacity(self, p) -> bool:
        self.capacity.update(p)
        return True

    async def cmd_tensor_request(self, p) -> dict:
        """Generic correlated array-carrying request to a peer."""
        reply = await self.tensor_request(
            self._conn(p["peer"]), p["tag"], p.get("body", {}),
            timeout=p.get("timeout"),
        )
        reply.pop("_rid", None)
        reply.pop("_resp", None)
        return reply

    async def cmd_send_tensor(self, p) -> bool:
        await self.send_tensor(self._conn(p["peer"]), p["tag"], p.get("body", {}))
        return True

    async def cmd_chain_send(self, p) -> bool:
        """Forward a chained-stage frame to the NEXT stage's worker by
        address, dialing on demand (ml/worker.py::_finish_fwd — worker-to-
        worker pipelined forward; connect() dedupes by address)."""
        addr = p["addr"]
        conn = await self.connect(addr[0], int(addr[1]))
        await self.send_tensor(conn, p["tag"], p.get("body", {}))
        return True

    async def cmd_respond(self, p) -> bool:
        """Resolve an earlier inbound tensor request (ML finished the work)."""
        await self.tensor_respond(
            self._conn(p["peer"]), p["tag"], {"_rid": p["rid"]}, p.get("body", {})
        )
        return True

    async def cmd_send_control(self, p) -> bool:
        """Generic fire-and-forget control frame to a peer."""
        await self._conn(p["peer"]).send_control(p["tag"], p.get("body", {}))
        return True

    async def cmd_control_request(self, p) -> dict:
        """Generic correlated control-frame request to a peer."""
        reply = await self.request(
            self._conn(p["peer"]), p["tag"], p.get("body", {}),
            timeout=p.get("timeout"),
        )
        reply.pop("_rid", None)
        reply.pop("_resp", None)
        return reply

    async def cmd_send_token(self, p) -> bool:
        await self.send_token(
            self._conn(p["peer"]), p["stream"], p.get("tokens", []),
            done=p.get("done", False),
        )
        return True

    async def cmd_next_tokens(self, p):
        try:
            tokens, done = await self.next_tokens(
                p["stream"], timeout=p.get("timeout", 30.0)
            )
            if done:
                self.drop_stream(p["stream"])
            return {"tokens": tokens, "done": done}
        except asyncio.TimeoutError:
            return {"tokens": [], "done": False, "timeout": True}

    async def cmd_drop_stream(self, p) -> bool:
        """Release a stream buffer without draining it to the done marker
        (stop-sequence cancel stops forwarding early; the generation's
        trailing tokens would otherwise sit in the buffer forever)."""
        self.drop_stream(p["stream"])
        return True

    # -- stats ----------------------------------------------------------
    async def _handle_stats_request(self, conn, kind, tag, body) -> None:
        free = self.capacity["hbm_bytes"] - sum(self.reserved.values())
        await self.respond(
            conn, proto.STATS_RESPONSE, body,
            {**self.capacity, "free_bytes": max(free, 0.0), "id": self.node_id},
        )


class WorkerServer(RoleServer):
    """Accepts jobs when capacity allows; relays tensor work to the ML
    process (reference WorkerThread, nodes/worker_thread.py:14)."""

    def __init__(self, cfg: NodeConfig, queues: BridgeQueues):
        super().__init__(cfg, queues)
        self.jobs: dict[str, dict] = {}
        # stream id -> cancelled row indices (STREAM_CANCEL pushes from the
        # driving user); the ML generate loop polls these at chunk
        # boundaries via cmd_poll_cancel so a confirmed stop-sequence match
        # ends the compiled decode within one chunk
        self.stream_cancels: dict[str, set] = {}
        self.register(proto.JOB_REQ, self._handle_job_req)
        self.register(proto.JOB_SHUTDOWN, self._handle_job_shutdown)
        self.register(proto.MODULE, self._handle_module)
        self.register(proto.STREAM_CANCEL, self._handle_stream_cancel)
        for tag in (
            proto.FORWARD, proto.BACKWARD, proto.GENERATE,
            proto.PARAMS_REQ, proto.OPTIMIZER, proto.TRAIN_MODE,
            proto.CHECKPOINT, proto.PROOF_REQ,
            # live slot migration: DRAIN from a validator, MIGRATE
            # (probe / page transfer) worker-to-worker; HANDOFF pushes
            # the decode-pool membership a prefill worker ships to;
            # REPLICA_SET pushes the sibling-replica membership a fleet
            # entry worker may drain onto (docs/SERVING.md "Fleet
            # serving")
            proto.DRAIN, proto.MIGRATE, proto.HANDOFF, proto.REPLICA_SET,
        ):
            self.register(tag, self._relay_to_ml)

    async def _handle_job_req(self, conn, kind, tag, body) -> None:
        """Validator recruiting (reference worker_thread.py:128-166):
        accept iff free capacity covers the stage estimate."""
        est = float(body.get("est_bytes", 0.0))
        free = self.capacity["hbm_bytes"] - sum(self.reserved.values())
        job_id = body.get("job_id", "")
        if est and est > free:
            await self.respond(conn, proto.JOB_DECLINE, body, {"job_id": job_id})
            return
        self.reserved[job_id] = est
        self.jobs[job_id] = {"stage": body.get("stage"), "t0": time.time()}
        await self.respond(
            conn, proto.JOB_ACCEPT, body,
            {"job_id": job_id, "id": self.node_id,
             "addr": [self.host, self.port]},
        )

    async def _handle_job_shutdown(self, conn, kind, tag, body) -> None:
        job_id = body.get("job_id", "")
        self.reserved.pop(job_id, None)
        self.jobs.pop(job_id, None)
        self.post_work("shutdown_job", {"job_id": job_id})

    async def _handle_module(self, conn, kind, tag, body) -> None:
        """A stage assignment arrives (plan + model config + ckpt ref).
        ML loads it and resolves the request via the ``respond`` cmd."""
        self.post_work(
            "load_stage",
            {**{k: v for k, v in body.items() if k not in ("_rid",)},
             "peer": conn.node_id, "rid": body.get("_rid")},
        )

    async def _relay_to_ml(self, conn, kind, tag, body) -> None:
        rid = body.pop("_rid", None)
        body.pop("_resp", None)
        self.post_work(tag, {**body, "peer": conn.node_id, "rid": rid})

    async def _handle_stream_cancel(self, conn, kind, tag, body) -> None:
        """Record confirmed stop-sequence cancels for a streamed generate.
        Kept server-side (not relayed through the work queue): the ML run
        loop is busy inside the generate and polls via cmd_poll_cancel."""
        rows = self.stream_cancels.setdefault(str(body.get("stream", "")), set())
        rows.update(int(r) for r in body.get("rows", []))
        if len(self.stream_cancels) > 1024:  # stale-stream bound
            self.stream_cancels.pop(next(iter(self.stream_cancels)))

    async def cmd_poll_cancel(self, p) -> list[int]:
        return sorted(self.stream_cancels.get(p.get("stream", ""), ()))

    async def cmd_clear_cancels(self, p) -> bool:
        self.stream_cancels.pop(p.get("stream", ""), None)
        return True


class ValidatorServer(RoleServer):
    """Job orchestration (reference ValidatorThread,
    nodes/validator_thread.py:22). Plans come from the validator ML process;
    this side recruits workers and answers users."""

    def __init__(self, cfg: NodeConfig, queues: BridgeQueues):
        super().__init__(cfg, queues)
        from tensorlink_tpu.platform.contract import ContractManager
        from tensorlink_tpu.platform.job_monitor import JobMonitor
        from tensorlink_tpu.platform.keeper import Keeper

        self.jobs: dict[str, dict] = {}
        self._job_requests: dict[str, tuple[Connection, dict]] = {}
        self.keeper = Keeper(Path(cfg.log_dir) / "dht_state.json")
        self.monitor = JobMonitor(self)
        chain = None
        if not cfg.off_chain:
            # on-chain mode: EVM submission via the stdlib chain client
            # (reference builds web3 contracts at startup,
            # smart_node.py:292-315; missing credentials degrade off-chain)
            from tensorlink_tpu.core.config import EnvFile
            from tensorlink_tpu.platform.chain import from_env

            chain = from_env(EnvFile(cfg.env_file))
            if chain is not None:
                # Sybil gate: a fresh key starts clean with LOCAL reputation,
                # so on-chain mode also requires peers claiming validator/
                # worker roles to be chain-registered before the handshake
                # completes (reference smart_node.py:708-739)
                from tensorlink_tpu.platform.chain import make_credential_check

                self.credential_check = make_credential_check(chain.client)
        self.contract = ContractManager(self.node_id, chain=chain)
        self.worker_capacity_total = 0.0
        # workers seen disconnecting since the last proposal round —
        # keeper.clean_node prunes addresses/roles, so the proposal's
        # offline list must come from its own record
        self.offline_workers: dict[str, float] = {}
        from tensorlink_tpu.p2p.monitor import RateLimiter

        # per-IP JOB_REQ rate limiting: a connected (authenticated) peer must
        # not be able to spam planning work — each request costs the ML
        # process a full plan_sharding pass (reference
        # validator_thread.py:508-516; r2 gap — only connection attempts
        # were limited)
        self.job_req_limiter = RateLimiter(
            max_per_minute=JOB_REQS_PER_MINUTE, block_s=600.0
        )
        self._restore_state()
        self.register(proto.JOB_REQ, self._handle_job_req)
        self.register(proto.JOB_SHUTDOWN, self._handle_job_shutdown)
        self.register(proto.JOB_REPAIR, self._handle_job_repair)
        self.register(proto.PROPOSAL, self._handle_proposal)
        self.register(proto.REQUEST_WORKERS, self._handle_request_workers)
        # workers advertised by OTHER validators (id -> [host, port]) so a
        # plan can place stages on them; connections are made lazily at
        # recruit time (reference REQUEST-WORKERS, validator_thread.py:889-928)
        self.remote_workers: dict[str, list] = {}

    def _restore_state(self) -> None:
        """Reload persisted DHT entries + stats (reference keeper restore at
        validator startup, validator_thread.py:135-137)."""
        state = self.keeper.load_previous_state()
        for k, ts in state.get("dht_tombstones", {}).items():
            try:
                self.dht.delete(k, ts=float(ts))
            # tlint: disable=TL005(malformed persisted tombstone — skip it, keep restoring the rest)
            except (TypeError, ValueError):
                continue
        for k, v in state.get("dht", {}).items():
            # restore with the ORIGIN ts — an untimestamped store would
            # stamp restart-time and beat every write/delete that happened
            # while this validator was down (stale-resurrection)
            try:
                ts = float(v.get("ts"))
            except (TypeError, ValueError):
                ts = None
            self.dht.store(k, v.get("value"), ts=ts)
        self.reputation.load_json(state.get("reputation", {}))
        now = time.time()
        for jid, j in state.get("jobs", {}).items():
            j.setdefault("t0_restored", now)  # don't credit downtime
            self.jobs.setdefault(jid, j)

    def on_started(self) -> None:
        asyncio.run_coroutine_threadsafe(self._platform_loop(), self._loop)

    def on_shutdown(self) -> None:
        self.keeper.write_state(self)

    def _on_disconnect(self, conn) -> None:
        if conn.node_id and self.roles.get(conn.node_id) == "worker":
            self.offline_workers[conn.node_id] = time.time()
        super()._on_disconnect(conn)

    async def _platform_loop(self) -> None:
        """Keeper writes, job monitoring, stats, contract rounds — the
        validator run loop's periodic duties (validator_thread.py:978-1011)."""
        last_keeper = last_round = time.monotonic()
        interval = max(min(self.cfg.monitor_interval, self.cfg.keeper_interval), 0.5)
        while not self.terminate.is_set():
            await asyncio.sleep(min(interval, self.cfg.monitor_interval))
            try:
                await self.monitor.check_jobs()
                self.keeper.update_statistics(self)
                self.keeper.clean_node(self)
                now = time.monotonic()
                if now - last_keeper >= self.cfg.keeper_interval:
                    self.keeper.write_state(self)
                    last_keeper = now
                if (
                    self.cfg.proposal_interval
                    and now - last_round >= self.cfg.proposal_interval
                ):
                    await self._run_proposal_round()
                    last_round = now
            except Exception:
                self.log.exception("platform loop iteration failed")

    # -- worker replacement (net-new working path; reference stubs it,
    # job_monitor.py:293-328) -------------------------------------------
    async def replace_worker(self, job_id: str, dead_wid: str) -> dict | None:
        """Recruit a spare worker for a dead stage; rewrite plan + DHT and
        push JOB_UPDATE to the user. Returns the update dict or None."""
        job = self.jobs.get(job_id)
        if job is None:
            # failover: the validator that created the job may be gone, but
            # its record replicated (dht_store_global + validator sync) —
            # adopt it and become the monitoring validator
            record = self.dht.get_local(f"job:{job_id}") or await self.dht_query(
                f"job:{job_id}"
            )
            if not isinstance(record, dict) or "plan" not in record:
                return None
            job = dict(record)
            job["t0_restored"] = time.time()
            self.jobs[job_id] = job
            self.log.info("job %s: adopted from replicated DHT record", job_id[:8])
        stages = [
            s for s in job.get("plan", {}).get("stages", [])
            if s["worker_id"] == dead_wid
        ]
        if not stages:
            return None
        current = set(job.get("workers", {}))
        candidates = [
            nid for nid in self.connections
            if self.roles.get(nid) == "worker" and nid not in current
        ]
        est = float(job.get("stage_bytes", {}).get(dead_wid, 0.0))
        for cand in candidates:
            try:
                reply = await self.request(
                    self._conn(cand), proto.JOB_REQ,
                    {"job_id": job_id, "stage": stages[0], "est_bytes": est},
                    timeout=RECRUIT_TIMEOUT,
                )
            # tlint: disable=TL005(recruit probe — a dead/slow candidate just means try the next one)
            except (TimeoutError, asyncio.TimeoutError, ConnectionError):
                continue
            if "addr" not in reply:
                continue
            host, _ = self.addresses.get(cand, (None, None))
            addr = [host or reply["addr"][0], reply["addr"][1]]
            for s in stages:
                s["worker_id"] = cand
            job["workers"].pop(dead_wid, None)
            job["workers"][cand] = addr
            job["stage_bytes"][cand] = job.get("stage_bytes", {}).pop(dead_wid, est)
            await self.dht_store_global(f"job:{job_id}", _json_safe(job))
            update = {
                "job_id": job_id,
                "old_worker": dead_wid,
                "worker": {"id": cand, "addr": addr},
                "stages": [s["layer_lo"] for s in stages],
            }
            user_conn = self.connections.get(job.get("user_id", ""))
            if user_conn is not None:
                try:
                    await user_conn.send_control(proto.JOB_UPDATE, update)
                except (ConnectionError, OSError) as e:
                    # the user will pull the replacement via JOB_REPAIR
                    self.log.warning(
                        "job %s: JOB_UPDATE push to user failed (%s)",
                        job_id[:8], e,
                    )
            self.reputation.record(dead_wid, "worker_dropped")
            self.log.info(
                "job %s: replaced worker %s -> %s", job_id[:8],
                dead_wid[:8], cand[:8],
            )
            return update
        self.log.warning("job %s: no replacement for %s", job_id[:8], dead_wid[:8])
        return None

    async def _handle_job_repair(self, conn, kind, tag, body) -> None:
        """User pulls a replacement synchronously after a failed request."""
        update = await self.replace_worker(
            body.get("job_id", ""), body.get("worker_id", "")
        )
        await self.respond(
            conn, proto.JOB_UPDATE, body,
            update or {"error": "no replacement available"},
        )

    async def _handle_job_shutdown(self, conn, kind, tag, body) -> None:
        """User ends a job: drop validator state + DHT record and make sure
        the workers released it (idempotent on their side)."""
        job = self.jobs.get(body.get("job_id", ""))
        if job is not None:
            self.contract.record_job(job)
        await self.cmd_shutdown_job({"job_id": body.get("job_id", "")})

    # -- contract / stats commands --------------------------------------
    async def _run_proposal_round(self) -> dict:
        """Create → collect validator votes → execute one reward round
        (reference proposal_creator flow, contract_manager.py:317-683):
        the full proposal body goes to every connected validator, each
        recomputes the hash and votes; quorum over validators + self."""
        offline = [
            nid for nid in self.offline_workers if nid not in self.connections
        ]
        self.offline_workers.clear()
        prop = self.contract.create_proposal(offline)
        h = prop.hash()
        await self.dht_store_global(f"proposal:{h}", prop.to_json())
        self.contract.vote(h, self.node_id, True)
        for vid in self.validator_ids():
            try:
                reply = await self.request(
                    self._conn(vid), proto.PROPOSAL,
                    {"proposal": prop.to_json(), "hash": h},
                    timeout=10.0,
                )
                self.contract.vote(h, vid, bool(reply.get("approve")))
            # tlint: disable=TL005(a validator missing a vote round is normal liveness; quorum math tolerates it)
            except (TimeoutError, asyncio.TimeoutError, ConnectionError):
                continue
        n_validators = len(self.validator_ids()) + 1
        executed = self.contract.try_execute(h, n_validators)
        record = prop.to_json()
        self.keeper.proposals.append(record)
        self.log.info("proposal round %d: executed=%s", prop.round, executed)
        return record

    async def _handle_proposal(self, conn, kind, tag, body) -> None:
        """Another validator asks for our vote: recompute the hash from the
        full body (reference proposal_validator, contract_manager.py:45-242)."""
        ok = False
        try:
            ok = self.contract.validate_proposal(
                body.get("proposal", {}), body.get("hash", "")
            )
        except Exception:
            self.log.exception("proposal validation failed")
        if not ok:
            self.reputation.record(conn.node_id or "", "proposal_mismatch")
        await self.respond(conn, proto.PROPOSAL_VOTE, body, {"approve": ok})

    # -- proof of learning (monitor pull path; reference job_monitor.py
    # PoL hooks are commented out, :193-207 — here they enforce) ----------
    async def collect_job_proofs(self, job_id: str) -> dict:
        """Pull + verify each worker's PoL log for a job; failed
        verification flags the job record and dings worker reputation."""
        from tensorlink_tpu.platform.proofs import verify_proof_log

        job = self.jobs.get(job_id)
        if job is None:
            return {"error": "unknown job"}

        async def pull(wid: str) -> tuple[str, dict] | None:
            conn = self.connections.get(wid)
            if conn is None:
                return None  # liveness is the monitor's concern, not PoL's
            try:
                reply = await self.request(
                    conn, proto.PROOF_REQ, {"job_id": job_id}, timeout=10.0
                )
            except (TimeoutError, asyncio.TimeoutError, ConnectionError):
                return wid, {"ok": False, "reason": "unreachable"}
            if "log" not in reply:
                # worker-side error (e.g. job released in a shutdown race) —
                # not a passing verdict, but not evidence of faked work
                return wid, {
                    "ok": False, "reason": "no-log",
                    "error": str(reply.get("error", ""))[:200],
                }
            log = reply.get("log", [])
            total = int(reply.get("total_steps", 0) or 0)
            ok, detail = verify_proof_log(log)
            if ok and total > 0 and not log:
                # claiming optimizer steps while returning no entries is the
                # trivial bypass of an "empty log passes" rule — flag it
                ok, detail = False, {"reason": "empty-log-with-steps"}
            return wid, {"ok": ok, **detail, "total_steps": total}

        results = await asyncio.gather(
            *(pull(w) for w in list(job.get("workers", {})))
        )
        verdicts = dict(r for r in results if r is not None)
        # SOFT_REASONS are liveness matters (busy worker timing out a pull,
        # shutdown-race error replies), not evidence of faked work — but a
        # worker that NEVER verifiably answers is opting out of PoL, so
        # persistent softness escalates to one penalty per streak. Hard
        # verification failures are rate-limited per worker instead of
        # keyed by chain position (position keys either collide forever —
        # the empty-log faker pays once — or churn every pull as the window
        # slides): one glitch costs one ding that decays, while a
        # persistent cheat re-dings every cooldown and reaches the ban
        # threshold in ~3 cooldowns.
        SOFT_REASONS = ("unreachable", "no-log")
        SOFT_STREAK_LIMIT = 5
        PENALTY_COOLDOWN_S = 600.0
        dinged = job.setdefault("pol_dinged", {})  # wid -> last penalty ts
        misses = job.setdefault("pol_misses", {})  # wid -> consecutive softs
        now = time.time()
        for wid, v in verdicts.items():
            if v["ok"]:
                misses.pop(wid, None)
                continue
            if v.get("reason") in SOFT_REASONS:
                misses[wid] = misses.get(wid, 0) + 1
                if misses[wid] >= SOFT_STREAK_LIMIT:
                    self.reputation.record(wid, "proof_failed")
                    misses[wid] = 0
            else:
                misses.pop(wid, None)
                # tlint: disable=TL004(dinged stamps ride the persisted job record — epoch by design)
                if now - dinged.get(wid, 0.0) > PENALTY_COOLDOWN_S:
                    self.reputation.record(wid, "proof_failed")
                    dinged[wid] = now
            self.log.warning(
                "job %s: PoL verification failed for %s: %s",
                job_id[:8], wid[:8], v,
            )
        job["pol"] = {"ts": time.time(), "verdicts": verdicts}
        return job["pol"]

    async def cmd_job_proofs(self, p) -> dict:
        return await self.collect_job_proofs(p["job_id"])

    async def cmd_run_proposal_round(self, p) -> dict:
        return await self._run_proposal_round()

    async def cmd_proposal_history(self, p) -> list[dict]:
        return list(self.keeper.proposals)

    async def cmd_claim_info(self, p) -> dict:
        for h, prop in reversed(list(self.contract.proposals.items())):
            claim = self.contract.claim_data(h, p["worker_id"])
            if claim is not None:
                return claim
        return {"error": "no executed proposal covers this worker"}

    async def cmd_network_history(self, p) -> dict:
        return self.keeper.get_network_status(self)

    async def _handle_job_req(self, conn, kind, tag, body) -> None:
        """A user asks for a model (reference validator_thread.py:583-609).
        Hand the spec to the validator ML process for planning."""
        # key on the socket peer address (untainted), not the advertised
        # handshake address a peer could rotate to evade the limit
        try:
            ip = conn.peername[0]
        except Exception:
            ip = (self.addresses.get(conn.node_id) or ("?",))[0]
        if not self.job_req_limiter.allow(str(ip)):
            self.log.warning("rate-limiting job requests from %s", ip)
            self.reputation.record(conn.node_id or "", "spam")
            await self.respond(
                conn, proto.JOB_DECLINE, body,
                {"error": "job request rate limit exceeded"},
            )
            return
        req_id = uuid.uuid4().hex
        self._job_requests[req_id] = (conn, body)
        self.post_work(
            "job_req",
            {"spec": body.get("spec", {}), "user_id": conn.node_id,
             "req_id": req_id},
        )

    async def _own_worker_stats(self) -> list[dict]:
        """Fan STATS_REQUEST out to this validator's connected workers
        CONCURRENTLY (one slow worker must not serialize the sweep — the
        peer validator asking via REQUEST-WORKERS waits on the total),
        tagging each with its reachable listen address."""

        async def one(nid: str) -> dict | None:
            try:
                reply = await self.request(
                    self._conn(nid), proto.STATS_REQUEST, {}, timeout=5.0
                )
            except (TimeoutError, asyncio.TimeoutError, ConnectionError):
                return None
            stat = {k: v for k, v in reply.items()
                    if k not in ("_rid", "_resp")}
            addr = self.addresses.get(nid)
            if addr:
                stat["addr"] = list(addr)
            return stat

        wids = [nid for nid in list(self.connections)
                if self.roles.get(nid) == "worker"]
        replies = await asyncio.gather(*(one(n) for n in wids))
        return [s for s in replies if s is not None]

    async def cmd_stats_workers(self, p) -> list[dict]:
        """Worker pool for planning: this validator's own workers PLUS the
        pools of its validator peers (reference REQUEST-WORKERS,
        validator_thread.py:889-928) — so a job can be placed on a worker
        known only to another validator. Own stats win on id collision (a
        worker connected to several validators)."""
        out = await self._own_worker_stats()
        seen = {s.get("id") for s in out}

        async def ask(nid: str) -> list[dict]:
            try:
                reply = await self.request(
                    self._conn(nid), proto.REQUEST_WORKERS, {}, timeout=7.0
                )
            except (TimeoutError, asyncio.TimeoutError, ConnectionError):
                return []
            return list(reply.get("workers", []))

        vids = [nid for nid in list(self.connections)
                if self.roles.get(nid) == "validator"]
        peer_pools = await asyncio.gather(*(ask(n) for n in vids))
        advertised: dict[str, list] = {}
        for pool in peer_pools:
            for stat in pool:
                wid = stat.get("id")
                if not wid or wid in seen:
                    continue
                seen.add(wid)
                if stat.get("addr"):
                    advertised[wid] = list(stat["addr"])
                out.append(stat)
        # rebuilt wholesale each sweep so departed workers' addresses are
        # pruned rather than accumulating for the process lifetime
        self.remote_workers = advertised
        self.worker_capacity_total = sum(
            float(s.get("hbm_bytes", 0.0)) for s in out
        )
        return out

    def _resolve_worker(self, prefix: str) -> str | None:
        """Unique connected worker whose id starts with ``prefix`` (ops
        surfaces pass truncated ids); ambiguity matches nothing."""
        matches = [
            nid for nid in self.connections
            if self.roles.get(nid) == "worker" and nid.startswith(prefix)
        ]
        return matches[0] if len(matches) == 1 else None

    async def cmd_drain_worker(self, p) -> dict:
        """Operator surface for live slot migration (docs/SERVING.md
        "Draining a worker"): tell ``worker`` to shed every live serving
        slot onto ``dest`` — page-shipping migration with the
        crash-recovery re-prefill as the fallback rung, zero dropped
        streams. ``dest`` defaults to the connected worker with the most
        free capacity; the DRAIN body carries the destination's id and
        LISTEN address so the source can dial it worker-to-worker."""
        await self._control_fault("drain_worker")
        src = self._resolve_worker(str(p.get("worker", "")))
        if src is None:
            return {"ok": False, "error": "unknown or ambiguous worker"}
        dest = None
        if p.get("dest"):
            dest = self._resolve_worker(str(p["dest"]))
            if dest is None or dest == src or dest not in self.addresses:
                # an EXPLICITLY named destination that doesn't resolve
                # stays a loud error — silently draining onto a fallback
                # the operator never chose is worse than refusing
                return {"ok": False, "error": "no usable destination worker"}
        else:
            # destination choice: most free capacity among the OTHER
            # connected workers with a known listen address
            stats = await self._own_worker_stats()
            ranked = sorted(
                (s for s in stats
                 if s.get("id") != src and s.get("id") in self.addresses),
                key=lambda s: -float(
                    s.get("free_bytes", s.get("hbm_bytes", 0.0))
                ),
            )
            dest = ranked[0]["id"] if ranked else None
        if dest is not None and (dest == src or dest not in self.addresses):
            dest = None
        if dest is None:
            # no candidate from here — still send the DRAIN: a fleet
            # entry worker holds a REPLICA_SET push and can drain onto
            # its sibling replica itself (docs/SERVING.md "Fleet
            # serving"); a worker with neither answers with the error
            body = {}
        else:
            body = {"dest": {"id": dest, "addr": list(self.addresses[dest])}}
        reply = await self.request(
            self._conn(src), proto.DRAIN,
            body,
            # generous default: a drain to a COLD destination ships the
            # whole stage (up to ~130s) before the per-slot transfers
            # (60s each) — a shorter operator timeout would report a
            # still-succeeding drain as failed and lose its summary
            timeout=float(p.get("timeout", 600.0)),
        )
        reply.pop("_rid", None)
        reply.pop("_resp", None)
        return {**reply, "dest": dest}

    async def _handle_request_workers(self, conn, kind, tag, body) -> None:
        """A validator peer asks for this validator's spare workers. Answer
        with OWN workers only — never relayed ones — so a two-validator
        cycle cannot amplify into a request storm. The stats sweep runs as
        a task: handlers are awaited inline on the connection's read loop
        (p2p/node.py::_on_frame), and a multi-second fan-out must not
        head-of-line-block every other frame on this link."""
        if self.roles.get(conn.node_id) != "validator":
            await self.respond(conn, proto.WORKERS, body, {"workers": []})
            return

        async def answer() -> None:
            stats = await self._own_worker_stats()
            try:
                await self.respond(conn, proto.WORKERS, body, {"workers": stats})
            # tlint: disable=TL005(the asking validator hung up while we gathered stats — nobody to answer)
            except (ConnectionError, OSError):
                pass

        t = asyncio.ensure_future(answer())
        self._conn_tasks.add(t)
        t.add_done_callback(self._conn_tasks.discard)

    async def _worker_conn(self, wid: str) -> Connection:
        """Connection to a worker, dialing out lazily when the worker is
        known only via another validator's REQUEST-WORKERS advertisement."""
        conn = self.connections.get(wid)
        if conn is not None:
            return conn
        addr = self.remote_workers.get(wid)
        if not addr:
            raise ConnectionError(f"no connection to {wid[:12]}")
        conn = await self.connect(addr[0], int(addr[1]))
        if conn.node_id != wid:
            raise ConnectionError(
                f"worker at {addr[0]}:{addr[1]} is {conn.node_id[:12]}, "
                f"not {wid[:12]}"
            )
        return conn

    async def cmd_create_job(self, p) -> dict:
        """Recruit the planned workers, store the job, answer the user.

        ``p`` = {req_id, job: {job_id, model, plan}} from the validator ML.
        Recruiting = JOB_REQ to each stage's worker with a 3 s accept window
        (reference recruit_worker, validator_thread.py:845-887).
        """
        await self._control_fault("create_job")
        job = p["job"]
        job_id = job["job_id"]
        plan = job["plan"]
        accepted: dict[str, list] = {}
        declined: list[str] = []
        for stage in plan["stages"]:
            wid = stage["worker_id"]
            # co-slice members share the stage's reservation — each must
            # accept (and reserve its share) or the whole recruit fails
            members = [wid] + [
                c for c in stage.get("coworkers", []) if c not in accepted
            ]
            est = job.get("stage_bytes", {}).get(wid, 0.0) / max(len(members), 1)
            for member in members:
                if member in accepted:
                    continue
                try:
                    reply = await self.request(
                        await self._worker_conn(member), proto.JOB_REQ,
                        {"job_id": job_id, "stage": stage, "est_bytes": est},
                        timeout=RECRUIT_TIMEOUT,
                    )
                except (TimeoutError, asyncio.TimeoutError, ConnectionError):
                    declined.append(member)
                    continue
                if "addr" not in reply:  # decline replies carry no address
                    declined.append(member)
                else:
                    # the worker reports its *bind* host (may be 0.0.0.0);
                    # the routable address is the one this validator observed
                    # at handshake (P2PNode.addresses) + the advertised
                    # listen port
                    host, _ = self.addresses.get(member, (None, None))
                    accepted[member] = [
                        host or reply["addr"][0], reply["addr"][1]
                    ]

        ok = not declined
        if not ok:
            # release reservations on the workers that already accepted —
            # otherwise every failed recruit permanently shrinks their
            # advertised free capacity
            for wid in accepted:
                try:
                    await self._conn(wid).send_control(
                        proto.JOB_SHUTDOWN, {"job_id": job_id}
                    )
                # tlint: disable=TL005(best-effort reservation release — a dead worker frees it by dying)
                except (ConnectionError, OSError):
                    pass
        result = {
            "job_id": job_id,
            "accepted": ok,
            "workers": accepted,
            "declined": declined,
            "model": job.get("model"),
            "plan": plan,
        }
        if ok:
            self.jobs[job_id] = {
                "job_id": job_id, "plan": plan, "workers": accepted,
                "user_id": p.get("user_id"), "t0": time.time(),
                "model": job.get("model", {}).get("name", ""),
                "stage_bytes": dict(job.get("stage_bytes", {})),
                "status": "active",
            }
            await self.dht_store_global(f"job:{job_id}", _json_safe(self.jobs[job_id]))

        if ok:
            # disaggregated prefill/decode: the validator ML's plan named
            # which recruited workers serve the prefill pool and which
            # decode workers they should hand completed prefills to —
            # push the membership now (fire-and-forget; a worker that
            # never hears it simply serves mixed, never a failed job)
            for wid, pool in (job.get("handoff_push") or {}).items():
                if wid not in accepted:
                    continue
                try:
                    await (await self._worker_conn(wid)).send_control(
                        proto.HANDOFF, {"job_id": job_id, "pool": pool}
                    )
                # tlint: disable=TL005(best-effort pool push — an unreached prefill worker degrades to mixed serving)
                except Exception as e:
                    # truly fire-and-forget: a re-dial here can also raise
                    # asyncio.TimeoutError / HandshakeError, and NONE of
                    # them may abort cmd_create_job — the job is already
                    # recruited and the JOB_ACCEPT below must still send
                    self.log.warning(
                        "job %s: handoff-pool push to %s failed: %s",
                        job_id[:8], wid[:8], e,
                    )
        req = self._job_requests.pop(p.get("req_id", ""), None)
        if req is not None:
            conn, body = req
            await self.respond(conn, proto.JOB_ACCEPT if ok else proto.JOB_DECLINE,
                               body, result)
        return result

    async def cmd_set_handoff_pool(self, p) -> dict:
        """Operator surface for disaggregated serving (docs/SERVING.md
        "Disaggregated prefill/decode"): push a decode-pool membership to
        ``worker`` (a prefill-pool worker). ``pool`` defaults to every
        connected worker advertising ``serving_role == "decode"`` — the
        refresh an operator runs after decode workers join or leave, the
        same information recruit-time pushes carry automatically."""
        await self._control_fault("set_handoff_pool")
        wid = self._resolve_worker(str(p.get("worker", "")))
        if wid is None:
            return {"ok": False, "error": "unknown or ambiguous worker"}
        pool = p.get("pool")
        if pool is None:
            stats = await self._own_worker_stats()
            pool = [
                {"id": s["id"], "addr": list(s["addr"])}
                for s in stats
                if str(s.get("serving_role") or "mixed") == "decode"
                and s.get("addr") and s["id"] != wid
            ]
        await self._conn(wid).send_control(proto.HANDOFF, {"pool": pool})
        return {"ok": True, "pool": [str(x.get("id", ""))[:16] for x in pool]}

    async def cmd_set_replica_set(self, p) -> dict:
        """Fleet serving (docs/SERVING.md "Fleet serving"): push a
        sibling-replica membership to ``worker`` — the entry worker of
        one replica of a hosted fleet. Mirrors the HANDOFF pool push:
        fire-and-forget wire state the worker uses when a DRAIN arrives
        with no explicit destination (the autopilot's rolling deploy
        drains a replica onto a sibling), scoped to the replica's own
        ``job_id``. ``peers`` is ``[{id, addr, job_id}, ...]`` naming the
        OTHER replicas' entry workers."""
        await self._control_fault("set_replica_set")
        wid = self._resolve_worker(str(p.get("worker", "")))
        if wid is None:
            return {"ok": False, "error": "unknown or ambiguous worker"}
        peers = []
        for e in p.get("peers") or []:
            pid = self._resolve_worker(str(e.get("id", "")))
            if pid is None:
                continue
            # the ML process knows worker IDS, not transports — fill each
            # sibling's LISTEN address here, where the net process keeps
            # them (the same table the DRAIN destination uses)
            addr = list(e.get("addr") or self.addresses.get(pid) or [])
            if not addr:
                continue
            peers.append({
                "id": pid, "addr": addr,
                "job_id": str(e.get("job_id", "")),
            })
        await self._conn(wid).send_control(
            proto.REPLICA_SET,
            {"job_id": str(p.get("job_id", "")), "peers": peers},
        )
        return {"ok": True, "peers": [e["id"][:16] for e in peers]}

    async def cmd_expire_migrations(self, p) -> dict:
        """Control-plane recovery (docs/FAILURE_MODEL.md "Control
        plane"): tell ``worker`` to drop its STAGED — exported but never
        committed — migration tickets for ``job_id``, the deterministic
        expiry a restarted validator runs for every journal "mig" intent
        the crash left open. The worker re-checks page conservation after
        dropping; a worker with nothing staged answers ``expired: 0``.
        ``mig`` narrows the expiry to one ticket id."""
        await self._control_fault("expire_migrations")
        wid = self._resolve_worker(str(p.get("worker", "")))
        if wid is None:
            return {"ok": False, "error": "unknown or ambiguous worker"}
        body = {"op": "expire", "job_id": str(p.get("job_id", ""))}
        if p.get("mig"):
            body["mig"] = str(p["mig"])
        reply = await self.request(
            self._conn(wid), proto.MIGRATE, body,
            timeout=float(p.get("timeout", 30.0)),
        )
        reply.pop("_rid", None)
        reply.pop("_resp", None)
        return reply

    async def cmd_decline_job(self, p) -> bool:
        """Planning failed (no capacity / unknown model)."""
        req = self._job_requests.pop(p.get("req_id", ""), None)
        if req is not None:
            conn, body = req
            await self.respond(conn, proto.JOB_DECLINE, body,
                               {"error": p.get("error", "declined")})
        return True

    async def cmd_shutdown_job(self, p) -> bool:
        job = self.jobs.pop(p["job_id"], None)
        if job:
            for wid in job.get("workers", {}):
                self.reputation.record(wid, "job_completed")
                try:
                    await self._conn(wid).send_control(
                        proto.JOB_SHUTDOWN, {"job_id": p["job_id"]}
                    )
                # tlint: disable=TL005(best-effort release — a worker already gone freed its reservation by dying)
                except (ConnectionError, OSError):
                    pass
            await self.dht_delete_global(f"job:{p['job_id']}")
        return True


class UserServer(RoleServer):
    """User-side networking (reference UserThread, nodes/user_thread.py:13).
    The DistributedModel drives everything through generic commands; the only
    role-specific verb is the job request."""

    def __init__(self, cfg: NodeConfig, queues: BridgeQueues):
        super().__init__(cfg, queues)
        self.forward_tokens_to_ml = False  # drained via cmd_next_tokens
        self.job_updates: list[dict] = []  # JOB_UPDATE pushes from validators
        self.register(proto.JOB_UPDATE, self._handle_job_update)

    async def _handle_job_update(self, conn, kind, tag, body) -> None:
        """A validator replaced one of our workers (monitor push path)."""
        body.pop("_rid", None)
        body.pop("_resp", None)
        self.job_updates.append(body)

    async def cmd_job_updates(self, p) -> list[dict]:
        out, self.job_updates = self.job_updates, []
        return out

    async def cmd_request_job(self, p) -> dict:
        """Send JOB_REQ to a connected validator and await the decision
        (reference user_thread.py:242-415, 120 s timeout)."""
        validators = self.validator_ids()
        if not validators:
            raise ConnectionError("no validator connections (bootstrap first)")
        reply = await self.request(
            self._conn(validators[0]), proto.JOB_REQ, {"spec": p.get("spec", {})},
            timeout=p.get("timeout", JOB_REQ_TIMEOUT),
        )
        reply.pop("_rid", None)
        reply.pop("_resp", None)
        return reply


def _json_safe(obj: Any) -> Any:
    return json.loads(json.dumps(obj, default=str))


# tlint: disable=TL006(read-only constant table — never mutated at runtime)
SERVERS = {
    "worker": WorkerServer,
    "validator": ValidatorServer,
    "user": UserServer,
}


def run_server(role: str, cfg: NodeConfig, queues: BridgeQueues) -> None:
    """Entry point for the spawned network process."""
    if cfg.json_logs:
        # the network half logs too — both processes of a node must agree
        # on the structured format for cluster log aggregation
        from tensorlink_tpu.core.logging import set_json_logs

        set_json_logs(True)
    SERVERS[role](cfg, queues).main()
