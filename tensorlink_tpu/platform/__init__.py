"""Platform services: state persistence, job monitoring, reward accounting,
proof-of-learning primitives (reference nodes/keeper.py, job_monitor.py,
contract_manager.py, ml/proofs.py)."""
