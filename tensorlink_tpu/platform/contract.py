"""Reward accounting — merkle proposals and worker claims, off-chain first.

Reference: nodes/contract_manager.py:12 (1037 LoC): the round's proposal
creator aggregates completed jobs into per-worker byte-hour capacities,
builds a merkle tree of ``(worker, capacity)`` leaves (:785-836), stores the
full proposal in the DHT keyed by its hash, submits the hash on-chain, and
other validators recompute + vote; workers later claim rewards with merkle
proofs (get_worker_claim_data:911).

Here the same consensus artifacts are produced off-chain (sha256 in place of
keccak, DHT in place of the EVM): proposals, deterministic hashes, votes,
and verifiable claim proofs. With ``off_chain=False`` the lifecycle also
submits to the EVM through :mod:`tensorlink_tpu.platform.chain` (stdlib
keccak/RLP/secp256k1 + JSON-RPC — web3 is absent from the TPU image):
proposal hashes at creation, votes at validation, execution at quorum,
each guarded so a flaky RPC degrades to off-chain instead of killing the
validator.
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass, field


def _h(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def leaf_hash(worker_id: str, capacity: int) -> bytes:
    return _h(f"{worker_id}:{capacity}".encode())


def build_merkle(leaves: list[bytes]) -> tuple[bytes, list[list[bytes]]]:
    """Returns (root, levels) — levels[0] = leaves, last = [root]. Odd nodes
    promote unchanged (reference pairs-with-duplicate is a detail, not a
    contract — this tree is self-consistent with its own proofs)."""
    if not leaves:
        return _h(b""), [[]]
    levels = [list(leaves)]
    while len(levels[-1]) > 1:
        cur = levels[-1]
        nxt = []
        for i in range(0, len(cur) - 1, 2):
            nxt.append(_h(cur[i] + cur[i + 1]))
        if len(cur) % 2:
            nxt.append(cur[-1])
        levels.append(nxt)
    return levels[-1][0], levels


def merkle_proof(levels: list[list[bytes]], index: int) -> list[tuple[str, bytes]]:
    """Sibling path for ``leaves[index]``; entries are (side, hash) with
    side "L"/"R" = sibling position."""
    proof = []
    for level in levels[:-1]:
        sib = index ^ 1
        if sib < len(level):
            proof.append(("L" if sib < index else "R", level[sib]))
        index //= 2
    return proof


def verify_proof(leaf: bytes, proof: list[tuple[str, bytes]], root: bytes) -> bool:
    h = leaf
    for side, sib in proof:
        h = _h(sib + h) if side == "L" else _h(h + sib)
    return h == root


@dataclass
class Proposal:
    round: int
    creator: str
    capacities: dict[str, int]  # worker_id -> byte-seconds served
    offline: list[str] = field(default_factory=list)
    ts: float = field(default_factory=time.time)
    votes: dict[str, bool] = field(default_factory=dict)
    executed: bool = False

    def ordered(self) -> list[tuple[str, int]]:
        return sorted(self.capacities.items())

    def merkle(self):
        leaves = [leaf_hash(w, c) for w, c in self.ordered()]
        return build_merkle(leaves)

    def hash(self) -> str:
        root, _ = self.merkle()
        body = json.dumps(
            {"round": self.round, "creator": self.creator,
             "root": root.hex(), "offline": sorted(self.offline)},
            sort_keys=True,
        )
        return _h(body.encode()).hex()

    def to_json(self) -> dict:
        return {
            "round": self.round, "creator": self.creator,
            "capacities": self.capacities, "offline": self.offline,
            "ts": self.ts, "votes": self.votes, "executed": self.executed,
            "hash": self.hash(),
        }

    @classmethod
    def from_json(cls, d: dict) -> "Proposal":
        return cls(
            round=d["round"], creator=d["creator"],
            capacities=dict(d["capacities"]), offline=list(d.get("offline", [])),
            ts=d.get("ts", 0.0), votes=dict(d.get("votes", {})),
            executed=bool(d.get("executed", False)),
        )


class ContractManager:
    """Round-based proposal lifecycle over completed-job accounting."""

    def __init__(self, node_id: str, *, quorum: float = 0.5, chain=None):
        self.node_id = node_id
        self.quorum = quorum
        self.chain = chain  # ChainSubmitter | None (platform/chain.py)
        self.round = 0
        self.usage: dict[str, float] = {}  # worker -> accumulated byte·s
        self.proposals: dict[str, Proposal] = {}  # hash -> proposal

    # -- accounting -----------------------------------------------------
    def record_job(self, job: dict, *, ended: float | None = None) -> None:
        """Fold a completed/expired job into per-worker byte-seconds
        (reference capacity aggregation, contract_manager.py:283-315).
        Jobs restored after a validator restart carry ``t0_restored`` so
        downtime is never credited as served capacity."""
        t0 = float(job.get("t0_restored") or job.get("t0", time.time()))
        # tlint: disable=TL004(job t0 is persisted/replicated — epoch is the record's clock)
        dt = max((ended or time.time()) - t0, 0.0)
        stage_bytes = job.get("stage_bytes", {})
        for s in job.get("plan", {}).get("stages", []):
            wid = s["worker_id"]
            self.usage[wid] = self.usage.get(wid, 0.0) + dt * float(
                stage_bytes.get(wid, 0.0)
            )

    # -- proposal lifecycle --------------------------------------------
    def create_proposal(self, offline: list[str] = ()) -> Proposal:
        self.round += 1
        prop = Proposal(
            round=self.round,
            creator=self.node_id,
            capacities={w: int(c) for w, c in self.usage.items()},
            offline=list(offline),
        )
        h = prop.hash()
        self.proposals[h] = prop
        if self.chain is not None:  # reference createProposal, :534
            self.chain.submit_proposal(h, prop.round)
        return prop

    def validate_proposal(self, data: dict, claimed_hash: str) -> bool:
        """Recompute the hash from the full proposal body (reference
        proposal_validator, contract_manager.py:45-242)."""
        ok = Proposal.from_json(data).hash() == claimed_hash
        if self.chain is not None:  # reference voteForProposal, :208-242
            self.chain.submit_vote(claimed_hash, ok)
        return ok

    def vote(self, prop_hash: str, voter: str, approve: bool = True) -> None:
        prop = self.proposals.get(prop_hash)
        if prop is not None:
            prop.votes[voter] = approve

    def try_execute(self, prop_hash: str, n_validators: int) -> bool:
        prop = self.proposals.get(prop_hash)
        if prop is None or prop.executed:
            return False
        yes = sum(1 for v in prop.votes.values() if v)
        if yes / max(n_validators, 1) > self.quorum:
            prop.executed = True
            self.usage = {}  # rewarded usage resets for the next round
            if self.chain is not None:  # reference executeProposal, :683
                self.chain.execute_proposal(prop.round)
            return True
        return False

    # -- worker claims (reference get_worker_claim_data:911) ------------
    def claim_data(self, prop_hash: str, worker_id: str) -> dict | None:
        prop = self.proposals.get(prop_hash)
        if prop is None or not prop.executed:
            return None
        ordered = prop.ordered()
        ids = [w for w, _ in ordered]
        if worker_id not in ids:
            return None
        idx = ids.index(worker_id)
        root, levels = prop.merkle()
        proof = merkle_proof(levels, idx)
        return {
            "worker": worker_id,
            "capacity": ordered[idx][1],
            "index": idx,  # leaf position — the on-chain fold derives
            # sibling sides from it (chain.py submit_claim)
            "root": root.hex(),
            "round": prop.round,
            "proof": [(s, h.hex()) for s, h in proof],
        }

    def submit_claim(self, prop_hash: str, worker_id: str) -> str | None:
        """On-chain reward claim for a worker's share of an executed
        proposal (reference get_worker_claim_data + claim submission,
        contract_manager.py:911-1000). Returns the tx hash, or None when
        there is nothing to claim / no chain configured / RPC failed."""
        claim = self.claim_data(prop_hash, worker_id)
        if claim is None or self.chain is None:
            return None
        return self.chain.submit_claim(claim["round"], claim)

    @staticmethod
    def verify_claim(claim: dict) -> bool:
        return verify_proof(
            leaf_hash(claim["worker"], claim["capacity"]),
            [(s, bytes.fromhex(h)) for s, h in claim["proof"]],
            bytes.fromhex(claim["root"]),
        )
