"""Keeper — network-state persistence and statistics.

Reference: nodes/keeper.py:165 (855 LoC): persists DHT entity state to
``logs/dht_state.json`` (write_state:616), restores with age filters — 7 d
for jobs/users, 30 d for others (load_previous_state:658) — and maintains
daily→weekly network statistics with gap filling and chart-shaped API
output (get_network_status:502). Same capability, pure functions + one
class, no thread; the role server schedules ``tick()`` on its event loop.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any

JOB_MAX_AGE = 7 * 86400  # reference keeper.py:658 age filters
NODE_MAX_AGE = 30 * 86400
WEEKLY_ARCHIVE_DAYS = 7


def _day(ts: float) -> str:
    return time.strftime("%Y-%m-%d", time.gmtime(ts))


class Keeper:
    def __init__(self, state_path: str | Path):
        self.path = Path(state_path)
        self.daily: dict[str, dict] = {}  # day -> counters
        self.weekly: list[dict] = []
        self.proposals: list[dict] = []  # archived proposals (contract layer)
        self._last_write = 0.0

    # -- persistence ----------------------------------------------------
    def write_state(self, node) -> dict:
        """Snapshot the node's live state (peers, DHT, jobs, stats)."""
        now = time.time()
        jobs = getattr(node, "jobs", {})
        state = {
            "ts": now,
            "node_id": node.node_id,
            "peers": {
                nid: {
                    "role": node.roles.get(nid),
                    "addr": list(node.addresses.get(nid, ())),
                    "ts": now,
                }
                for nid in node.connections
            },
            # per-key ORIGIN timestamps (not snapshot time): a restored
            # record must not outrank writes/deletes that happened while
            # this validator was down; tombstones persist for the same
            # reason (a restart must not resurrect deleted records)
            "dht": {
                k: {"value": v,
                    "ts": getattr(node.dht, "updated_at", {}).get(k, now)}
                for k, v in node.dht.store_map.items()
                if _json_safe_check(v)
            },
            "dht_tombstones": dict(getattr(node.dht, "tombstones", {})),
            "jobs": {jid: {**j, "ts": j.get("t0", now)} for jid, j in jobs.items()},
            "reputation": (
                node.reputation.to_json()
                if getattr(node, "reputation", None) is not None else {}
            ),
            "daily": self.daily,
            "weekly": self.weekly,
            "proposals": self.proposals[-200:],
        }
        self.path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.path.with_suffix(".tmp")
        tmp.write_text(json.dumps(state, default=str))
        tmp.replace(self.path)
        self._last_write = now
        return state

    def load_previous_state(self) -> dict:
        """Restore with freshness filters (reference keeper.py:658-700)."""
        if not self.path.exists():
            return {}
        try:
            state = json.loads(self.path.read_text())
        except (json.JSONDecodeError, OSError):
            return {}
        now = time.time()
        state["peers"] = {
            k: v for k, v in state.get("peers", {}).items()
            # tlint: disable=TL004(restored-state freshness vs persisted epoch stamps)
            if now - float(v.get("ts", 0)) < NODE_MAX_AGE
        }
        state["jobs"] = {
            k: v for k, v in state.get("jobs", {}).items()
            # tlint: disable=TL004(restored-state freshness vs persisted epoch stamps)
            if now - float(v.get("ts", 0)) < JOB_MAX_AGE
        }
        self.daily = state.get("daily", {})
        self.weekly = state.get("weekly", [])
        self.proposals = state.get("proposals", [])
        return state

    # -- statistics (reference keeper.py:341-572) -----------------------
    def update_statistics(self, node) -> None:
        now = time.time()
        day = _day(now)
        roles = [node.roles.get(nid) for nid in node.connections]
        cap = getattr(node, "worker_capacity_total", 0.0)
        entry = self.daily.setdefault(
            day,
            {"workers": 0, "validators": 0, "users": 0, "jobs": 0,
             "capacity_bytes": 0.0},
        )
        entry["workers"] = max(entry["workers"], roles.count("worker"))
        entry["validators"] = max(entry["validators"], roles.count("validator") + 1)
        entry["users"] = max(entry["users"], roles.count("user"))
        entry["jobs"] = max(entry["jobs"], len(getattr(node, "jobs", {})))
        entry["capacity_bytes"] = max(entry["capacity_bytes"], cap)
        self._archive_old_days(day)

    def _archive_old_days(self, today: str) -> None:
        """Days older than a week fold into weekly aggregates (reference
        daily→weekly archival, keeper.py:341-420)."""
        old = sorted(d for d in self.daily if d != today)[:-WEEKLY_ARCHIVE_DAYS]
        if not old:
            return
        for day in old:
            e = self.daily.pop(day)
            wk = f"{day[:4]}-W{time.strftime('%W', time.strptime(day, '%Y-%m-%d'))}"
            slot = next((w for w in self.weekly if w["week"] == wk), None)
            if slot is None:
                slot = {"week": wk,
                        **{k: (0.0 if isinstance(v, float) else 0)
                           for k, v in e.items()}}
                self.weekly.append(slot)
            for k, v in e.items():
                slot[k] = max(slot.get(k, 0), v)

    def get_network_status(self, node) -> dict:
        """Chart-ready output for /network-history. Day labels are
        contiguous: days with no recorded sample (node offline) appear as
        zero entries so charts show the outage instead of splicing it out
        (reference gap filling, keeper.py:341-420)."""
        days = _fill_day_gaps(sorted(self.daily))
        zero = {"workers": 0, "validators": 0, "users": 0, "jobs": 0,
                "capacity_bytes": 0.0}

        def series(key):
            return [self.daily.get(d, zero)[key] for d in days]

        return {
            "current": {
                "peers": len(node.connections),
                "jobs": len(getattr(node, "jobs", {})),
            },
            "daily": {
                "labels": days,
                "workers": series("workers"),
                "validators": series("validators"),
                "users": series("users"),
                "jobs": series("jobs"),
                "capacity_bytes": series("capacity_bytes"),
            },
            "weekly": self.weekly,
        }

    # -- pruning (reference clean_node, keeper.py:702-733) --------------
    @staticmethod
    def clean_node(node) -> int:
        """Drop dead connections' bookkeeping; returns number pruned."""
        dead = [
            nid for nid in list(node.addresses)
            if nid not in node.connections
        ]
        for nid in dead:
            node.addresses.pop(nid, None)
            node.roles.pop(nid, None)
        return len(dead)


MAX_CHART_DAYS = 30


def _fill_day_gaps(days: list[str]) -> list[str]:
    """Contiguous YYYY-MM-DD labels from the first to the last recorded day,
    capped to the most recent :data:`MAX_CHART_DAYS` — a sporadically-online
    node can retain recorded days months apart (archival keeps the newest 7
    by count, not calendar age), and an unbounded fill would zero-pad the
    whole span into the API payload."""
    if not days:
        return []
    import datetime as dt

    d0 = dt.date.fromisoformat(days[0])
    d1 = dt.date.fromisoformat(days[-1])
    span = [(d0 + dt.timedelta(n)).isoformat() for n in range((d1 - d0).days + 1)]
    return span[-MAX_CHART_DAYS:]


def _json_safe_check(v: Any) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False
