"""EVM submission layer, stdlib-only — the on-chain half of the contract
manager.

Reference: nodes/contract_manager.py submits proposal hashes, votes, and
executions to a Smartnodes contract via web3 (createProposal:534,
voteForProposal:208-242, executeProposal:683) with keys from
``.tensorlink.env``. web3/eth-account are not in this image, so the pieces
web3 would provide are implemented here directly:

- ``keccak256`` — Keccak-f[1600] (Ethereum's pre-standard padding; NOT
  hashlib's sha3_256, which pads differently and yields different digests).
- ``rlp_encode`` — recursive length prefix for legacy transactions.
- secp256k1 ECDSA with RFC-6979 deterministic nonces and EIP-2 low-s
  normalization; EIP-155 replay-protected ``v``.
- 4-byte ABI selectors + static-type argument encoding.
- A urllib JSON-RPC client (eth_chainId / nonce / gasPrice / estimateGas /
  sendRawTransaction / call).

``ChainClient`` composes them: build → sign → submit a legacy transaction.
Submission is *guarded*: every entry point raises :class:`ChainError` on
RPC failure, and the contract manager treats that as "stay off-chain this
round" rather than dying (the reference behaves the same when its RPC is
flaky).

**Validation status**: the wire artifacts (keccak vectors, RLP round-trips,
EIP-155 signature recovery, ABI word layout incl. dynamic types) are pinned
against known vectors and a local fake JSON-RPC node (tests/test_chain.py).
No transaction has been attempted against a live testnet from this
environment (zero egress) — treat the layer as stub-tested until one has.
"""

from __future__ import annotations

import asyncio
import hmac
import hashlib
import http.client
import json
import threading
import urllib.request
from typing import Any, Sequence

from tensorlink_tpu.core.logging import get_logger

log = get_logger("platform.chain")


class ChainError(Exception):
    pass


# ---------------------------------------------------------------------------
# keccak-256 (Ethereum variant)
# ---------------------------------------------------------------------------

_M64 = (1 << 64) - 1
# tlint: disable=TL006(Keccak round constants — read-only)
_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
# rotation offsets r[x][y]
# tlint: disable=TL006(Keccak rotation offsets — read-only)
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
_RATE = 136  # 1088-bit rate for 256-bit output


def _rol(v: int, n: int) -> int:
    return ((v << n) | (v >> (64 - n))) & _M64 if n else v


def _keccak_f(a: list[list[int]]) -> None:
    for rc in _RC:
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rol(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rol(a[x][y], _ROT[x][y])
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y])
        a[0][0] ^= rc


def keccak256(data: bytes) -> bytes:
    a = [[0] * 5 for _ in range(5)]
    # pad: 0x01 ... 0x80 (Keccak padding, not SHA-3's 0x06)
    padded = bytearray(data)
    padded.append(0x01)
    while len(padded) % _RATE:
        padded.append(0x00)
    padded[-1] |= 0x80
    for off in range(0, len(padded), _RATE):
        block = padded[off : off + _RATE]
        for i in range(_RATE // 8):
            lane = int.from_bytes(block[8 * i : 8 * i + 8], "little")
            a[i % 5][i // 5] ^= lane
        _keccak_f(a)
    out = bytearray()
    for i in range(4):  # 32 bytes = 4 lanes
        out += a[i % 5][i // 5].to_bytes(8, "little")
    return bytes(out)


# ---------------------------------------------------------------------------
# RLP
# ---------------------------------------------------------------------------


def _rlp_int(v: int) -> bytes:
    return b"" if v == 0 else v.to_bytes((v.bit_length() + 7) // 8, "big")


def rlp_encode(item: Any) -> bytes:
    if isinstance(item, int):
        item = _rlp_int(item)
    if isinstance(item, (bytes, bytearray)):
        item = bytes(item)
        if len(item) == 1 and item[0] < 0x80:
            return item
        return _rlp_len(len(item), 0x80) + item
    if isinstance(item, (list, tuple)):
        body = b"".join(rlp_encode(x) for x in item)
        return _rlp_len(len(body), 0xC0) + body
    raise TypeError(f"cannot RLP-encode {type(item)}")


def _rlp_len(n: int, offset: int) -> bytes:
    if n < 56:
        return bytes([offset + n])
    nb = _rlp_int(n)
    return bytes([offset + 55 + len(nb)]) + nb


def rlp_decode(data: bytes) -> Any:
    item, rest = _rlp_decode_one(data)
    if rest:
        raise ValueError("trailing RLP bytes")
    return item


def _rlp_decode_one(d: bytes) -> tuple[Any, bytes]:
    if not d:
        raise ValueError("empty RLP")
    b0 = d[0]
    if b0 < 0x80:
        return d[:1], d[1:]
    if b0 < 0xB8:
        n = b0 - 0x80
        return d[1 : 1 + n], d[1 + n :]
    if b0 < 0xC0:
        ln = b0 - 0xB7
        n = int.from_bytes(d[1 : 1 + ln], "big")
        return d[1 + ln : 1 + ln + n], d[1 + ln + n :]
    if b0 < 0xF8:
        n = b0 - 0xC0
        body, rest = d[1 : 1 + n], d[1 + n :]
    else:
        ln = b0 - 0xF7
        n = int.from_bytes(d[1 : 1 + ln], "big")
        body, rest = d[1 + ln : 1 + ln + n], d[1 + ln + n :]
    items = []
    while body:
        item, body = _rlp_decode_one(body)
        items.append(item)
    return items, rest


# ---------------------------------------------------------------------------
# secp256k1 (sign + verify; RFC-6979 nonces)
# ---------------------------------------------------------------------------

_P = 2**256 - 2**32 - 977
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
_G = (_GX, _GY)


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


def _ec_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and (y1 + y2) % _P == 0:
        return None
    if p1 == p2:
        lam = (3 * x1 * x1) * _inv(2 * y1, _P) % _P
    else:
        lam = (y2 - y1) * _inv(x2 - x1, _P) % _P
    x3 = (lam * lam - x1 - x2) % _P
    return x3, (lam * (x1 - x3) - y1) % _P


def _ec_mul(k: int, p):
    r = None
    while k:
        if k & 1:
            r = _ec_add(r, p)
        p = _ec_add(p, p)
        k >>= 1
    return r


def pubkey(priv: int) -> tuple[int, int]:
    return _ec_mul(priv, _G)


def priv_to_address(priv: int) -> str:
    x, y = pubkey(priv)
    raw = x.to_bytes(32, "big") + y.to_bytes(32, "big")
    return "0x" + keccak256(raw)[12:].hex()


def _rfc6979_k(z: int, priv: int) -> int:
    """Deterministic nonce (RFC 6979, HMAC-SHA256)."""
    zb = z.to_bytes(32, "big")
    xb = priv.to_bytes(32, "big")
    k = b"\x00" * 32
    v = b"\x01" * 32
    k = hmac.new(k, v + b"\x00" + xb + zb, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + xb + zb, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < _N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def ecdsa_sign(msg_hash: bytes, priv: int) -> tuple[int, int, int]:
    """Returns (r, s, recovery_id) with low-s normalization (EIP-2)."""
    z = int.from_bytes(msg_hash, "big")
    while True:
        k = _rfc6979_k(z, priv)
        R = _ec_mul(k, _G)
        r = R[0] % _N
        if r == 0:
            z += 1  # re-derive (astronomically unlikely)
            continue
        s = _inv(k, _N) * (z % _N + r * priv) % _N
        if s == 0:
            z += 1
            continue
        rec = R[1] & 1
        if s > _N // 2:
            s = _N - s
            rec ^= 1
        return r, s, rec


def ecdsa_verify(msg_hash: bytes, r: int, s: int, pub: tuple[int, int]) -> bool:
    if not (1 <= r < _N and 1 <= s < _N):
        return False
    z = int.from_bytes(msg_hash, "big") % _N
    w = _inv(s, _N)
    u1, u2 = z * w % _N, r * w % _N
    pt = _ec_add(_ec_mul(u1, _G), _ec_mul(u2, pub))
    return pt is not None and pt[0] % _N == r


# ---------------------------------------------------------------------------
# ABI
# ---------------------------------------------------------------------------


def selector(fn_sig: str) -> bytes:
    return keccak256(fn_sig.encode())[:4]


def _abi_static_word(t: str, a: Any) -> bytes:
    """One 32-byte word for a static type."""
    if t == "bytes32":
        b = bytes.fromhex(a[2:]) if isinstance(a, str) else bytes(a)
        if len(b) != 32:
            raise ValueError(f"bytes32 arg of length {len(b)}")
        return b
    if t.startswith("uint") or t.startswith("int"):
        v = int(a)
        return (v % (1 << 256)).to_bytes(32, "big")
    if t == "address":
        h = a[2:] if isinstance(a, str) and a.startswith("0x") else a
        return bytes.fromhex(h).rjust(32, b"\x00")
    if t == "bool":
        return int(bool(a)).to_bytes(32, "big")
    raise ValueError(f"unsupported ABI type {t}")


def _abi_is_dynamic(t: str) -> bool:
    return t.endswith("[]") or t in ("bytes", "string")


def _abi_tail(t: str, a: Any) -> bytes:
    """Tail encoding of a dynamic value (length word + padded payload)."""
    if t.endswith("[]"):
        base = t[:-2]
        if _abi_is_dynamic(base):
            raise ValueError(f"nested dynamic ABI type {t} not supported")
        items = list(a)
        return len(items).to_bytes(32, "big") + b"".join(
            _abi_static_word(base, x) for x in items
        )
    if t in ("bytes", "string"):
        b = a.encode() if t == "string" else (
            bytes.fromhex(a[2:]) if isinstance(a, str) else bytes(a)
        )
        pad = (-len(b)) % 32
        return len(b).to_bytes(32, "big") + b + b"\x00" * pad
    raise ValueError(f"unsupported dynamic ABI type {t}")


def abi_encode_args(fn_sig: str, args: Sequence[Any]) -> bytes:
    """Solidity ABI argument encoding with standard head/tail layout:
    static types inline, dynamic types (``T[]`` of static T, ``bytes``,
    ``string``) as head offsets into a shared tail — enough for the full
    Smartnodes surface, including reward claims whose ``bytes32[]`` merkle
    proof arrays the previous static-only encoder could not express
    (reference claim machinery, contract_manager.py:911-1000)."""
    types = fn_sig[fn_sig.index("(") + 1 : fn_sig.rindex(")")]
    type_list = [t for t in types.split(",") if t]
    if len(type_list) != len(args):
        raise ValueError(f"{fn_sig}: {len(args)} args for {len(type_list)} types")
    head_len = 32 * len(type_list)
    heads: list[bytes] = []
    tails: list[bytes] = []
    tail_off = 0
    for t, a in zip(type_list, args):
        if _abi_is_dynamic(t):
            heads.append((head_len + tail_off).to_bytes(32, "big"))
            tail = _abi_tail(t, a)
            tails.append(tail)
            tail_off += len(tail)
        else:
            heads.append(_abi_static_word(t, a))
    return b"".join(heads) + b"".join(tails)


def call_data(fn_sig: str, args: Sequence[Any]) -> bytes:
    return selector(fn_sig) + abi_encode_args(fn_sig, args)


# ---------------------------------------------------------------------------
# JSON-RPC + client
# ---------------------------------------------------------------------------


class JsonRpc:
    # an RPC response larger than this is hostile or broken — a registry
    # view or tx hash is well under 1 KB, and an unbounded read() would let
    # a malicious endpoint exhaust validator memory
    MAX_RESPONSE_BYTES = 1 << 20

    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url
        self.timeout = timeout
        self._id = 0

    def call(self, method: str, params: list | None = None) -> Any:
        self._id += 1
        body = json.dumps(
            {"jsonrpc": "2.0", "method": method, "params": params or [],
             "id": self._id}
        ).encode()
        req = urllib.request.Request(
            self.url, data=body, headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                raw = r.read(self.MAX_RESPONSE_BYTES + 1)
                if len(raw) > self.MAX_RESPONSE_BYTES:
                    raise ChainError(
                        f"rpc {method}: response exceeds "
                        f"{self.MAX_RESPONSE_BYTES} bytes"
                    )
                resp = json.loads(raw)
        except ChainError:
            raise
        except (OSError, ValueError, http.client.HTTPException) as e:
            # HTTPException covers hostile non-HTTP banners (BadStatusLine)
            # and truncated chunked bodies (IncompleteRead) — neither is an
            # OSError, and callers catch ChainError to degrade
            raise ChainError(f"rpc {method} failed: {e}") from e
        if not isinstance(resp, dict):
            # a JSON array/string/number here is not a JSON-RPC envelope
            raise ChainError(f"rpc {method}: malformed response envelope")
        if "error" in resp:
            raise ChainError(f"rpc {method}: {resp['error']}")
        return resp.get("result")


class ChainClient:
    """Build, sign (EIP-155 legacy tx), and submit contract calls."""

    def __init__(
        self,
        rpc_url: str,
        contract: str,
        private_key_hex: str,
        *,
        chain_id: int | None = None,
        gas_limit: int = 500_000,
    ):
        self.rpc = JsonRpc(rpc_url)
        self.contract = contract
        self.priv = int(private_key_hex.removeprefix("0x"), 16)
        self.address = priv_to_address(self.priv)
        self._chain_id = chain_id
        self.gas_limit = gas_limit
        # submissions serialize: concurrent transact() calls would fetch
        # the same pending nonce and one tx would be silently replaced
        self._tx_lock = threading.Lock()

    @property
    def chain_id(self) -> int:
        if self._chain_id is None:
            self._chain_id = int(self.rpc.call("eth_chainId"), 16)
        return self._chain_id

    def _sign_tx(
        self, nonce: int, gas_price: int, data: bytes, to: str, value: int = 0
    ) -> bytes:
        to_b = bytes.fromhex(to.removeprefix("0x"))
        base = [nonce, gas_price, self.gas_limit, to_b, value, data]
        signing = rlp_encode(base + [self.chain_id, 0, 0])
        r, s, rec = ecdsa_sign(keccak256(signing), self.priv)
        v = self.chain_id * 2 + 35 + rec
        return rlp_encode(base + [v, r, s])

    def transact(self, fn_sig: str, args: Sequence[Any]) -> str:
        """Submit a state-changing call; returns the tx hash."""
        with self._tx_lock:
            nonce = int(
                self.rpc.call("eth_getTransactionCount", [self.address, "pending"]),
                16,
            )
            gas_price = int(self.rpc.call("eth_gasPrice"), 16)
            raw = self._sign_tx(
                nonce, gas_price, call_data(fn_sig, args), self.contract
            )
            return self.rpc.call("eth_sendRawTransaction", ["0x" + raw.hex()])

    def call_view(self, fn_sig: str, args: Sequence[Any]) -> bytes:
        result = self.rpc.call(
            "eth_call",
            [{"to": self.contract, "data": "0x" + call_data(fn_sig, args).hex()},
             "latest"],
        )
        # normalize EVERY malformed-result shape to ChainError: callers
        # (e.g. the handshake credential gate) catch ChainError to fail
        # CLOSED — an odd-length hex string or a non-string result from a
        # hostile RPC must not escape as ValueError/TypeError and crash
        # the caller instead
        try:
            if result is None:
                return b""
            if not isinstance(result, str) or not result.startswith("0x"):
                raise ValueError(f"non-hex eth_call result: {result!r:.80}")
            return bytes.fromhex(result[2:])
        except (ValueError, TypeError) as e:
            raise ChainError(f"rpc eth_call: malformed result: {e}") from e


class ChainSubmitter:
    """Guarded Smartnodes submission surface used by the contract manager
    (reference contract_manager.py:534 createProposal, :208 voteForProposal,
    :683 executeProposal). Every method degrades to a warning on RPC
    failure — a flaky chain endpoint must not take the validator down."""

    def __init__(self, client: ChainClient):
        self.client = client

    def _submit(self, fn_sig: str, args: Sequence[Any]) -> str | None:
        try:
            txh = self.client.transact(fn_sig, args)
            log.info("chain: %s -> %s", fn_sig.split("(")[0], txh)
            return txh
        except ChainError as e:
            log.warning("chain: %s submission failed: %s", fn_sig, e)
            return None

    def _guarded(self, fn_sig: str, args: Sequence[Any]) -> str | None:
        """Submit without ever blocking an event loop: called from async
        context (the validator's frame handlers / proposal round), the
        blocking HTTP round-trip is offloaded to a worker thread
        fire-and-forget; called synchronously, it submits inline."""
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return self._submit(fn_sig, args)
        loop.run_in_executor(None, self._submit, fn_sig, args)
        return None

    def submit_proposal(self, prop_hash: str, round_: int) -> str | None:
        return self._guarded(
            "createProposal(bytes32,uint256)", ["0x" + prop_hash, round_]
        )

    def submit_vote(self, prop_hash: str, approve: bool) -> str | None:
        return self._guarded(
            "voteForProposal(bytes32,bool)", ["0x" + prop_hash, approve]
        )

    def execute_proposal(self, round_: int) -> str | None:
        return self._guarded("executeProposal(uint256)", [round_])

    def submit_claim(self, round_: int, claim: dict) -> str | None:
        """Submit a worker's reward claim (reference claim flow,
        contract_manager.py:911-1000: distribution id + capacity + merkle
        proof; the contract recomputes the leaf from ``msg.sender`` and
        folds the proof to the executed round's stored root). ``claim`` is
        ``ContractManager.claim_data``'s dict: the proof's sibling hashes
        ride as ``bytes32[]``, and the leaf index lets the contract derive
        each fold's side (sib = index ^ 1 per level)."""
        proof = ["0x" + h for _side, h in claim["proof"]]
        return self._guarded(
            "claimRewards(uint256,uint256,uint256,bytes32[])",
            [round_, claim["capacity"], claim["index"], proof],
        )


def make_credential_check(client: ChainClient):
    """Handshake Sybil gate backed by the chain registry (reference
    smart_node.py:708-739: ``getValidatorInfo(addr)`` must say active and
    match the peer's key hash). Node ids here ARE sha256(pubkey) hex — a
    natural ``bytes32`` — so the registry views key on the id directly:
    ``isActiveValidator(bytes32)`` / ``isActiveWorker(bytes32)`` return a
    nonzero word for registered nodes. Users are not registry-gated (the
    reference accepts "U" roles without a chain check). A failed RPC
    REJECTS (fail closed, like the reference's contract-query-error path)."""

    def check(node_id: str, role: str) -> bool:
        view = {
            "validator": "isActiveValidator(bytes32)",
            "worker": "isActiveWorker(bytes32)",
        }.get(role)
        if view is None:
            return True
        try:
            out = client.call_view(view, ["0x" + node_id])
        except Exception as e:  # noqa: BLE001 — ANY failure fails closed:
            # a hostile RPC must not find an exception type that slips a
            # peer past the gate (or crashes the handshake loop)
            log.warning("credential check for %s failed: %s", node_id[:12], e)
            return False
        return any(out)

    return check


def from_env(env, *, default_chain_id: int | None = None) -> ChainSubmitter | None:
    """Build the submitter from ``.tensorlink_tpu.env`` — CHAIN_URL,
    CONTRACT_ADDRESS, CHAIN_PRIVATE_KEY (reference keys live in
    .tensorlink.env, contract_manager.py:222). Returns None (with a log
    line) when any piece is missing so ``off_chain=False`` without
    credentials degrades instead of crashing."""
    url = env.get("CHAIN_URL")
    contract = env.get("CONTRACT_ADDRESS")
    key = env.get("CHAIN_PRIVATE_KEY")
    if not (url and contract and key):
        log.warning(
            "on-chain mode requested but CHAIN_URL/CONTRACT_ADDRESS/"
            "CHAIN_PRIVATE_KEY are not all set — continuing off-chain"
        )
        return None
    cid = env.get("CHAIN_ID")
    return ChainSubmitter(
        ChainClient(
            url, contract, key,
            chain_id=int(cid) if cid else default_chain_id,
        )
    )


__all__ = [
    "ChainClient", "ChainError", "ChainSubmitter", "JsonRpc", "abi_encode_args",
    "call_data", "ecdsa_sign", "ecdsa_verify", "from_env", "keccak256",
    "priv_to_address", "pubkey", "rlp_decode", "rlp_encode", "selector",
]
