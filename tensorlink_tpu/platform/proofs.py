"""Proof-of-learning primitives — wired into enforcement.

Reference ml/proofs.py:18 ships gradient continuity, loss-trajectory
plausibility, and gradient hashing but never calls them (SURVEY §2.1 "mostly
unused scaffolding"; JobMonitor's verification paths are commented out,
job_monitor.py:193-207). Here the same checks are a working path:

- workers record a per-optimizer-step **proof entry** — gradient norm, a
  deterministic fixed-coordinate *sketch* of the step gradient (cheap: a
  device-side gather of a few hundred elements, no full-gradient host
  transfer), and a hash chained over the log (tamper-evident ordering);
- the validator's JobMonitor periodically pulls each worker's log
  (PROOF_REQ) and runs :func:`verify_proof_log` — continuity cosine over
  consecutive sketches (reference's check, proofs.py:23), norm plausibility,
  chain integrity;
- a failed verification flags the job record and dings the worker's
  reputation (p2p/reputation.py), which the handshake gate enforces.
"""

from __future__ import annotations

import hashlib

import numpy as np


def _leaves(tree) -> list[np.ndarray]:
    import jax

    return [np.asarray(x) for x in jax.tree.leaves(tree)]


def gradient_hash(grads) -> str:
    """Deterministic digest of a gradient pytree (reference
    calculate_gradient_hash, proofs.py:6)."""
    h = hashlib.sha256()
    for leaf in _leaves(grads):
        h.update(np.ascontiguousarray(leaf, dtype=np.float32).tobytes())
    return h.hexdigest()


def gradient_continuity(g1, g2, *, min_cosine: float = -0.2) -> tuple[bool, float]:
    """Cosine similarity between consecutive gradient pytrees; wildly
    anti-correlated consecutive gradients suggest fabricated work
    (reference continuity check, proofs.py:23)."""
    a = np.concatenate([l.ravel().astype(np.float64) for l in _leaves(g1)])
    b = np.concatenate([l.ravel().astype(np.float64) for l in _leaves(g2)])
    denom = np.linalg.norm(a) * np.linalg.norm(b)
    if denom == 0:
        return False, 0.0
    cos = float(a @ b / denom)
    return cos >= min_cosine, cos


SKETCH_DIM = 256


def gradient_sketch(grads, dim: int = SKETCH_DIM, seed: int = 0) -> np.ndarray:
    """Deterministic fixed-coordinate subsample of the flattened gradient
    pytree. The same ``seed`` picks the same coordinates every step, so the
    cosine between consecutive sketches estimates the true gradient
    continuity without shipping gradients. Device cost: one small gather
    per leaf; host transfer: ``dim`` floats total."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(grads)
    sizes = [int(np.prod(l.shape)) for l in leaves]
    total = max(sum(sizes), 1)
    rng = np.random.default_rng(seed)
    gathers = []  # device-side slices; ONE host transfer at the end
    for leaf, n in zip(leaves, sizes):
        if n == 0:
            continue
        k = min(max(1, round(dim * n / total)), n)
        idx = np.sort(rng.choice(n, size=k, replace=False))
        gathers.append(jnp.ravel(leaf)[jnp.asarray(idx)])
    if not gathers:
        return np.zeros(0)
    out = jax.device_get(gathers)
    return np.concatenate([np.asarray(v, np.float64).ravel() for v in out])


def proof_entry(
    step: int, grad_norm: float, sketch: np.ndarray, prev_hash: str = ""
) -> dict:
    """JSON-safe log entry; ``hash`` chains over (prev, step, sketch) so a
    log can't be silently reordered or rewritten after the fact."""
    sk = [round(float(v), 6) for v in np.asarray(sketch).ravel()]
    h = hashlib.sha256()
    h.update(prev_hash.encode())
    h.update(str(step).encode())
    h.update(repr(round(float(grad_norm), 9)).encode())
    h.update(np.asarray(sk, np.float64).tobytes())
    return {
        "step": int(step),
        "grad_norm": float(grad_norm),
        "sketch": sk,
        "hash": h.hexdigest(),
    }


def verify_proof_log(
    log: list[dict],
    *,
    min_cosine: float = -0.2,
    max_norm_ratio: float = 100.0,
) -> tuple[bool, dict]:
    """Monitor-side verification of a worker's proof log: hash-chain
    integrity, strictly increasing steps, finite sane norms, and gradient
    continuity over consecutive sketches (reference continuity semantics:
    flag wildly anti-correlated steps, proofs.py:23)."""
    if not log:
        return True, {"reason": "empty"}
    try:
        return _verify_entries(log, min_cosine, max_norm_ratio)
    except (KeyError, TypeError, ValueError, AttributeError, IndexError):
        # the log is adversarial input — a malformed entry is a failed
        # verdict, never an exception escaping into the monitor
        return False, {"reason": "malformed"}


def _verify_entries(
    log: list[dict], min_cosine: float, max_norm_ratio: float
) -> tuple[bool, dict]:
    prev_hash = str(log[0].get("_chain_root", ""))
    cosines = []
    # entries contributing no continuity evidence (empty or all-zero
    # sketch — the worker's documented fallback emits np.zeros(0))
    sketchless = sum(
        1 for e in log
        if not np.any(np.asarray(e.get("sketch", []), np.float64))
    )
    for i, e in enumerate(log):
        expect = proof_entry(
            e.get("step", -1), e.get("grad_norm", 0.0),
            np.asarray(e.get("sketch", []), np.float64), prev_hash,
        )["hash"]
        if e.get("hash") != expect:
            return False, {"reason": "chain-broken", "at": i}
        prev_hash = e["hash"]
        gn = float(e.get("grad_norm", np.nan))
        if not np.isfinite(gn) or gn < 0:
            return False, {"reason": "bad-norm", "at": i}
        if i:
            if int(e["step"]) <= int(log[i - 1]["step"]):
                return False, {"reason": "non-increasing-step", "at": i}
            prev_gn = max(float(log[i - 1]["grad_norm"]), 1e-12)
            if gn / prev_gn > max_norm_ratio:
                return False, {"reason": "norm-spike", "at": i,
                               "ratio": gn / prev_gn}
            a = np.asarray(log[i - 1].get("sketch", []), np.float64)
            b = np.asarray(e.get("sketch", []), np.float64)
            if a.size and b.size and a.shape != b.shape:
                return False, {"reason": "sketch-shape", "at": i}
            denom = (
                np.linalg.norm(a) * np.linalg.norm(b)
                if a.shape == b.shape else 0.0
            )
            if denom > 0:
                cosines.append(float(a @ b / denom))
    if cosines and float(np.median(cosines)) < min_cosine:
        return False, {"reason": "anti-correlated",
                       "median_cosine": float(np.median(cosines))}
    # all-empty / all-zero sketches would trivially bypass the continuity
    # check. The worker's sketch fallback (np.zeros(0) on a sketch error)
    # makes an occasional sketchless entry legitimate; more than a quarter
    # of a multi-entry log contributing no evidence is not
    if len(log) >= 3 and sketchless > max(1, len(log) // 4):
        return False, {"reason": "sketchless", "n_sketchless": sketchless}
    return True, {
        "n": len(log),
        "median_cosine": float(np.median(cosines)) if cosines else None,
    }


def loss_plausibility(
    losses: list[float], *, max_spike: float = 3.0, min_progress: float = -0.5
) -> tuple[bool, dict]:
    """Loss-trajectory sanity (reference monotonicity check, proofs.py:41,
    loosened: real training is noisy). Flags NaN/Inf, per-step spikes
    > max_spike×, and net regression beyond min_progress of the start."""
    arr = np.asarray(losses, np.float64)
    if arr.size == 0:
        return False, {"reason": "empty"}
    if not np.isfinite(arr).all():
        return False, {"reason": "non-finite"}
    spikes = arr[1:] / np.maximum(arr[:-1], 1e-12)
    if arr.size > 1 and float(spikes.max()) > max_spike:
        return False, {"reason": "spike", "max_ratio": float(spikes.max())}
    progress = (arr[0] - arr[-1]) / max(abs(arr[0]), 1e-12)
    ok = progress >= min_progress
    return ok, {"progress": float(progress)}
