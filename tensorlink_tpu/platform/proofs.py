"""Proof-of-learning primitives (reference ml/proofs.py:18 — gradient
continuity, loss-trajectory plausibility, gradient hashing; scaffolding the
reference never wired into enforcement, SURVEY §2.1). Implemented over
numpy pytree leaves so both driver and monitor can verify worker claims."""

from __future__ import annotations

import hashlib

import numpy as np


def _leaves(tree) -> list[np.ndarray]:
    import jax

    return [np.asarray(x) for x in jax.tree.leaves(tree)]


def gradient_hash(grads) -> str:
    """Deterministic digest of a gradient pytree (reference
    calculate_gradient_hash, proofs.py:6)."""
    h = hashlib.sha256()
    for leaf in _leaves(grads):
        h.update(np.ascontiguousarray(leaf, dtype=np.float32).tobytes())
    return h.hexdigest()


def gradient_continuity(g1, g2, *, min_cosine: float = -0.2) -> tuple[bool, float]:
    """Cosine similarity between consecutive gradient pytrees; wildly
    anti-correlated consecutive gradients suggest fabricated work
    (reference continuity check, proofs.py:23)."""
    a = np.concatenate([l.ravel().astype(np.float64) for l in _leaves(g1)])
    b = np.concatenate([l.ravel().astype(np.float64) for l in _leaves(g2)])
    denom = np.linalg.norm(a) * np.linalg.norm(b)
    if denom == 0:
        return False, 0.0
    cos = float(a @ b / denom)
    return cos >= min_cosine, cos


def loss_plausibility(
    losses: list[float], *, max_spike: float = 3.0, min_progress: float = -0.5
) -> tuple[bool, dict]:
    """Loss-trajectory sanity (reference monotonicity check, proofs.py:41,
    loosened: real training is noisy). Flags NaN/Inf, per-step spikes
    > max_spike×, and net regression beyond min_progress of the start."""
    arr = np.asarray(losses, np.float64)
    if arr.size == 0:
        return False, {"reason": "empty"}
    if not np.isfinite(arr).all():
        return False, {"reason": "non-finite"}
    spikes = arr[1:] / np.maximum(arr[:-1], 1e-12)
    if arr.size > 1 and float(spikes.max()) > max_spike:
        return False, {"reason": "spike", "max_ratio": float(spikes.max())}
    progress = (arr[0] - arr[-1]) / max(abs(arr[0]), 1e-12)
    ok = progress >= min_progress
    return ok, {"progress": float(progress)}
