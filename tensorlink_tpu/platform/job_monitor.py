"""JobMonitor — per-job health, expiry, and REAL worker replacement.

Reference: nodes/job_monitor.py:88 — a 30 s watchdog with an
ACTIVE→PENDING_OFFLINE→FAILED/COMPLETED state machine whose recovery is a
comment ("request another worker", module.py:510-511) and stubbed penalty
hooks (job_monitor.py:293-328). Here replacement is a working, tested path
(SURVEY §5 explicitly calls this out as the gap to close):

- a job whose worker connection drops goes PENDING_OFFLINE;
- the validator recruits a spare worker for the dead stage (same 3 s accept
  window as initial recruiting), rewrites the plan + DHT record, and pushes
  a JOB_UPDATE to the user;
- the user side (DistributedModel._repair) can also *pull* a replacement
  synchronously via JOB_REPAIR when a request fails mid-flight;
- free jobs expire after FREE_JOB_MAX_TIME and completed/failed jobs fold
  into the contract layer's capacity accounting.
"""

from __future__ import annotations

import asyncio
import time

FREE_JOB_MAX_TIME = 3600.0  # reference validator_thread.py:19
OFFLINE_GRACE = 5.0  # default; NodeConfig.offline_grace overrides
PROOF_INTERVAL = 60.0  # seconds between PoL log pulls per job
# a job that keeps losing workers is flapping — endless recruit loops burn
# the spare pool for a job that cannot hold a placement; fail it instead
MAX_REPAIRS_PER_JOB = 16


class JobMonitor:
    """Operates on a ValidatorServer from its event loop."""

    def __init__(self, server):
        self.server = server
        self.grace = float(
            getattr(getattr(server, "cfg", None), "offline_grace", OFFLINE_GRACE)
            or OFFLINE_GRACE
        )

    async def check_jobs(self) -> None:
        now = time.time()
        for job_id, job in list(self.server.jobs.items()):
            status = job.get("status", "active")
            if status in ("failed", "completed"):
                continue
            # tlint: disable=TL004(job t0 is persisted/replicated — epoch is the record's clock)
            if now - job.get("t0", now) > FREE_JOB_MAX_TIME:
                await self._finish(job_id, job, "completed")
                continue
            missing = [
                wid for wid in job.get("workers", {})
                if wid not in self.server.connections
            ]
            if not missing:
                if status != "active":
                    job["status"] = "active"
                job.pop("offline_since", None)  # full self-recovery resets grace
                # healthy job: periodically verify proof-of-learning logs
                # (reference PoL hooks exist but are commented out,
                # job_monitor.py:193-207 — here a bad log costs reputation)
                # tlint: disable=TL004(pol.ts rides the persisted job record — epoch)
                if now - job.get("pol", {}).get("ts", 0.0) > PROOF_INTERVAL:
                    # fire-and-forget: the pull awaits per-worker replies
                    # (10 s timeouts) and must never stall this tick's
                    # OFFLINE_GRACE liveness handling for other jobs; stamp
                    # ts first so a slow pull isn't re-fired every tick
                    job.setdefault("pol", {})["ts"] = now
                    # strong ref + done-callback: the loop holds tasks
                    # weakly, and an unreferenced pull could be GC'd
                    # mid-await (same pattern as P2PNode.sync_dht)
                    t = asyncio.ensure_future(
                        self.server.collect_job_proofs(job_id)
                    )
                    self.server._conn_tasks.add(t)
                    t.add_done_callback(self.server._conn_tasks.discard)
                continue
            job.setdefault("offline_since", now)
            job["status"] = "pending_offline"
            # tlint: disable=TL004(offline_since rides the persisted job record — epoch)
            if now - job["offline_since"] < self.grace:
                continue
            if job.get("repairs", 0) >= MAX_REPAIRS_PER_JOB:
                # flapping: this job has churned through too many
                # replacements — stop feeding it the worker pool
                await self._finish(job_id, job, "failed")
                continue
            ok = True
            for wid in missing:
                update = await self.server.replace_worker(job_id, wid)
                ok = ok and update is not None
                if update is not None:
                    job["repairs"] = job.get("repairs", 0) + 1
            if ok:
                job["status"] = "active"
                job.pop("offline_since", None)
            # tlint: disable=TL004(offline_since rides the persisted job record — epoch)
            elif now - job["offline_since"] > 6 * self.grace:
                await self._finish(job_id, job, "failed")

    async def _finish(self, job_id: str, job: dict, status: str) -> None:
        job["status"] = status
        self.server.contract.record_job(job)
        await self.server.cmd_shutdown_job({"job_id": job_id})
