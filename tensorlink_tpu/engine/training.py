"""Compiled training: loss, train-step factory, micro-batch accumulation.

The reference's training path replays torch autograd per offloaded module and
fans out optimizer RPCs (ml/module.py:414-524, ml/optim.py:81-205). Here a
training job inside one mesh is ONE compiled program: forward + backward +
optax update, parameters/grads/optimizer state all sharded by GSPMD, gradient
all-reduce riding ICI (psum over data/fsdp axes inserted by the compiler).
Micro-batching is a ``lax.scan`` gradient accumulation inside the program —
the compiled analogue of the reference's micro-batch threads
(module.py:374-399).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp
import optax

from ..models.base import ModelConfig
from ..models.transformer import forward


def causal_lm_loss(
    params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [B, T]
    loss_mask: jax.Array | None = None,  # [B, T] — True where next-token counts
    remat: bool = True,
):
    """Next-token cross-entropy in fp32. Returns (loss, aux)."""
    logits, _ = forward(params, tokens, cfg, remat=remat)
    logits = logits[:, :-1].astype(jnp.float32)
    targets = tokens[:, 1:]
    mask = (
        loss_mask[:, 1:]
        if loss_mask is not None
        else jnp.ones_like(targets, dtype=bool)
    )
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * mask
    n = jnp.maximum(mask.sum(), 1)
    loss = nll.sum() / n
    return loss, {"loss": loss, "n_tokens": n}


def make_optimizer(
    name: str = "adamw",
    lr: float | optax.Schedule = 1e-4,
    *,
    weight_decay: float = 0.0,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float | None = 1.0,
    **kw,
) -> optax.GradientTransformation:
    """optax chain mirroring the reference's optimizer spec ser/de surface
    (ml/utils.py:870-887 maps a name + kwargs)."""
    if name in ("adamw", "adam"):
        opt = optax.adamw(lr, b1=b1, b2=b2, weight_decay=weight_decay, **kw)
    elif name == "sgd":
        opt = optax.sgd(lr, **kw)
    elif name == "adafactor":
        opt = optax.adafactor(lr, **kw)
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    if grad_clip:
        opt = optax.chain(optax.clip_by_global_norm(grad_clip), opt)
    return opt


@dataclass
class TrainStep:
    """Bundle of compiled step + optimizer for a model on a mesh."""

    step_fn: Callable  # (params, opt_state, batch) -> (params, opt_state, metrics)
    optimizer: optax.GradientTransformation

    def init_state(self, params):
        return self.optimizer.init(params)


def make_train_step(
    cfg: ModelConfig,
    optimizer: optax.GradientTransformation,
    *,
    n_micro: int = 1,
    remat: bool = True,
    loss_fn: Callable | None = None,
    donate: bool = True,
) -> TrainStep:
    """Build the compiled train step.

    ``n_micro > 1`` splits the batch inside the program and accumulates
    gradients with ``lax.scan`` (sequential — bounds activation memory the
    same way the reference's micro-batch pipeline does, without threads).
    """
    loss_fn = loss_fn or causal_lm_loss

    def compute_grads(params, tokens, loss_mask):
        grad_fn = jax.value_and_grad(
            lambda p: loss_fn(p, cfg, tokens, loss_mask, remat=remat),
            has_aux=True,
        )
        (loss, aux), grads = grad_fn(params)
        return loss, aux, grads

    def step(params, opt_state, batch):
        tokens = batch["tokens"]
        loss_mask = batch.get("loss_mask")
        if n_micro > 1:
            B = tokens.shape[0]
            if B % n_micro != 0:
                raise ValueError(
                    f"batch {B} not divisible by n_micro={n_micro}"
                )
            mb = B // n_micro
            toks = tokens[: mb * n_micro].reshape(n_micro, mb, -1)
            lm = (
                loss_mask[: mb * n_micro].reshape(n_micro, mb, -1)
                if loss_mask is not None
                else None
            )

            # Accumulate token-weighted: each micro loss is a per-token mean,
            # so scale its grads back to sums and divide once by the total
            # token count — the result matches the n_micro=1 step even when
            # loss masks make micro-batches unevenly populated.
            # accumulate in fp32 regardless of param dtype: bf16 params
            # would otherwise carry a bf16 accumulator that `g * n_tok`
            # (fp32 scalar) promotes to fp32 — a lax.scan carry dtype
            # mismatch — and fp32 is the numerically right accumulator
            def scan_fn(acc, xs):
                t = xs[0]
                m = xs[1] if lm is not None else None
                loss, aux, grads = compute_grads(params, t, m)
                n_tok = aux["n_tokens"].astype(jnp.float32)
                acc_grads, acc_nll, acc_tok = acc
                acc_grads = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32) * n_tok,
                    acc_grads, grads,
                )
                return (acc_grads, acc_nll + loss * n_tok, acc_tok + n_tok), None

            zero = jax.tree.map(
                lambda p: jnp.zeros_like(p, dtype=jnp.float32), params
            )
            xs = (toks, lm) if lm is not None else (toks,)
            (grads, nll_sum, tok_sum), _ = jax.lax.scan(
                scan_fn, (zero, jnp.float32(0.0), jnp.float32(0.0)), xs
            )
            tok_sum = jnp.maximum(tok_sum, 1.0)
            # hand the optimizer grads in param dtype, matching n_micro=1
            # (keeps opt_state dtypes stable across both paths)
            grads = jax.tree.map(
                lambda g, p: (g / tok_sum).astype(p.dtype), grads, params
            )
            loss = nll_sum / tok_sum
        else:
            loss, _aux, grads = compute_grads(params, tokens, loss_mask)

        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    donate_args = (0, 1) if donate else ()
    return TrainStep(
        step_fn=jax.jit(step, donate_argnums=donate_args), optimizer=optimizer
    )


def optimizer_state_specs(
    optimizer: optax.GradientTransformation, params, param_specs
):
    """PartitionSpec pytree for the optax state: any sub-tree that mirrors
    the param tree (adam moments, momentum buffers) shards like the params;
    scalars (step counts) replicate. The reference keeps optimizer state on
    each worker next to its modules (ml/optim.py init fan-out) — same
    locality, but declared to the compiler instead of managed by RPC."""
    from jax.sharding import PartitionSpec as P

    state_shapes = jax.eval_shape(optimizer.init, params)
    pdef = jax.tree.structure(params)

    def is_param_tree(node):
        try:
            return jax.tree.structure(node) == pdef
        except Exception:
            return False

    return jax.tree.map(
        lambda node: param_specs if is_param_tree(node) else P(),
        state_shapes,
        is_leaf=is_param_tree,
    )
