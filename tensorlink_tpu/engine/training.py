"""Compiled training: loss, train-step factory, micro-batch accumulation.

The reference's training path replays torch autograd per offloaded module and
fans out optimizer RPCs (ml/module.py:414-524, ml/optim.py:81-205). Here a
training job inside one mesh is ONE compiled program: forward + backward +
optax update, parameters/grads/optimizer state all sharded by GSPMD, gradient
all-reduce riding ICI (psum over data/fsdp axes inserted by the compiler).
Micro-batching is a ``lax.scan`` gradient accumulation inside the program —
the compiled analogue of the reference's micro-batch threads
(module.py:374-399).

``make_train_step(zero1=True)`` is the ZeRO-1 data-parallel variant
(docs/TRAINING.md): gradients reduce cross-replica in a FIXED gather
order (the same trick that makes ``quantized_psum`` bitwise,
parallel/ring.py), the optax update runs on optimizer state that LIVES
1/dp per replica — declared to GSPMD through ``PartitionSpec`` rather
than hand-rolled RPC — and the updated params re-replicate through the
compiler's all-gather. With ``n_micro == dp`` the sharded step is
bit-identical to the unsharded microbatched step (test-pinned in
tests/test_zero1.py) while per-replica optimizer-state bytes drop to
~1/dp.

``make_train_step(zero1=True, tp_axis="tp")`` composes that with the
serving path's explicit tensor parallelism (docs/SHARDING.md) on a 2-D
``(dp, tp)`` mesh: params enter and leave the step as the SAME
head/column shards the paged engine serves (transformer.py
``tp_partition_specs``), each device gathers them whole for
forward/backward (grads land replicated over tp), the gradient
reduction runs over the dp axis only, and the optimizer update is
sliced over the FLATTENED ``dp·tp`` device grid — so resident
optimizer+weight bytes drop to ~1/(dp·tp) while the step stays
bit-identical to the unsharded reference (tests/test_tp.py). This is
what lets ``ServeTrainLoop`` train and hot-swap the very tensors a
tensor-parallel engine is serving without a relayout on either side.
"""

from __future__ import annotations

import typing
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from ..models.base import ModelConfig
from ..models.transformer import forward


def causal_lm_loss(
    params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [B, T]
    loss_mask: jax.Array | None = None,  # [B, T] — True where next-token counts
    remat: bool = True,
):
    """Next-token cross-entropy in fp32. Returns (loss, aux)."""
    logits, _ = forward(params, tokens, cfg, remat=remat)
    logits = logits[:, :-1].astype(jnp.float32)
    targets = tokens[:, 1:]
    mask = (
        loss_mask[:, 1:]
        if loss_mask is not None
        else jnp.ones_like(targets, dtype=bool)
    )
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * mask
    n = jnp.maximum(mask.sum(), 1)
    loss = nll.sum() / n
    return loss, {"loss": loss, "n_tokens": n}


class ChainedOptimizer(typing.NamedTuple):
    """A ``make_optimizer`` result: duck-types optax.GradientTransformation
    (``init``/``update`` are the full chain's) while keeping the chain
    STRUCTURE visible — ``grad_clip`` + ``inner`` (the post-clip stages).
    The zero1 step needs the split: the global-norm clip must see the FULL
    gradient (a shard's norm is not the global norm), while the inner
    elementwise stages run on each replica's 1/dp shard."""

    init: Callable
    update: Callable
    grad_clip: float | None
    inner: "optax.GradientTransformation"
    name: str


def make_optimizer(
    name: str = "adamw",
    lr: float | optax.Schedule = 1e-4,
    *,
    weight_decay: float = 0.0,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float | None = 1.0,
    **kw,
) -> ChainedOptimizer:
    """optax chain mirroring the reference's optimizer spec ser/de surface
    (ml/utils.py:870-887 maps a name + kwargs)."""
    if name in ("adamw", "adam"):
        opt = optax.adamw(lr, b1=b1, b2=b2, weight_decay=weight_decay, **kw)
    elif name == "sgd":
        opt = optax.sgd(lr, **kw)
    elif name == "adafactor":
        opt = optax.adafactor(lr, **kw)
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    inner = opt
    if grad_clip:
        opt = optax.chain(optax.clip_by_global_norm(grad_clip), opt)
    return ChainedOptimizer(
        init=opt.init, update=opt.update,
        grad_clip=float(grad_clip) if grad_clip else None,
        inner=inner, name=str(name),
    )


@dataclass
class TrainStep:
    """Bundle of compiled step + optimizer for a model on a mesh."""

    step_fn: Callable  # (params, opt_state, batch) -> (params, opt_state, metrics)
    optimizer: optax.GradientTransformation
    mode: str = "unsharded"  # "unsharded" | "zero1"
    mesh: Any = None  # zero1 only: the mesh carrying the dp axis
    dp_axis: str = "data"
    tp_axis: str | None = None  # zero1 × TP: the mesh's tensor axis

    def init_state(self, params):
        state = self.optimizer.init(params)
        if self.mode != "zero1":
            return state
        # ZeRO-1: the PERSISTENT optimizer state lives 1/dp per replica —
        # device_put with the dp-extended specs here, and every step's
        # output constraint keeps it there (the donated buffers round-trip
        # sharded, so full state never materializes after this point).
        # Composed with TP the slice axis is the FLATTENED (dp, tp) grid:
        # 1/(dp·tp) resident state per device.
        from jax.sharding import NamedSharding, PartitionSpec as P

        dp = int(self.mesh.shape[self.dp_axis])
        if self.tp_axis:
            axes: Any = (self.dp_axis, self.tp_axis)
            size = dp * int(self.mesh.shape[self.tp_axis])
        else:
            axes, size = self.dp_axis, dp
        sspecs = optimizer_state_specs(
            self.optimizer, params,
            jax.tree.map(lambda _: P(), params),
            dp_axis=axes, dp_size=size,
        )
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            state, sspecs,
        )

    def n_programs(self) -> int:
        """Compiled-program count of the step — the zero1 compile guard's
        probe: at most TWO programs per train config (the cold-entry
        layout whose params/state arrive freshly placed, and the
        steady-state layout whose inputs are the previous step's
        donated outputs), and further steps add ZERO (test-pinned)."""
        cache_size = getattr(self.step_fn, "_cache_size", None)
        return int(cache_size()) if cache_size is not None else -1


def _accum_micro_grads(sum_grads, params, toks, lm):
    """Token-weighted gradient accumulation over the leading micro axis
    of ``toks``/``lm`` — ONE implementation shared by the unsharded scan
    and each zero1 replica's local scan, which is what makes the zero1
    fixed-order cross-replica reduction bitwise against the unsharded
    carry (the two paths cannot drift). ``sum_grads`` returns SUM-form
    gradients (the backward is seeded with the micro's token count, see
    make_train_step), so the carry is a plain add per micro and the
    caller divides once by the total token count — matching the
    n_micro=1 step even when loss masks populate micro-batches unevenly.

    The carry accumulates in fp32 regardless of param dtype: bf16 params
    would otherwise mix a bf16 gradient into an accumulator whose dtype
    must not degrade — a ``lax.scan`` carry dtype mismatch (the r02
    train_error) — and fp32 is the numerically right accumulator
    (test-pinned: tests/test_engine.py::test_bf16_scan_carry_stays_fp32).

    Bitwise invariance (what the zero1 == unsharded pin is built on):
    the ``optimization_barrier`` fences pin the accumulation arithmetic
    to exactly "materialized grads, one add" per micro — without them
    XLA fuses the accumulate into the backward's epilogue differently
    per scan length, and a replica's length-1 scan would not be bitwise
    a prefix of the unsharded length-N scan (measured; so is the
    sum-FORM requirement itself — a mean-form backward followed by a
    ``* n_tok`` rescale cancels against the loss's ``/ n`` differently
    per program). Returns ``(grad_sums_fp32, nll_sum, tok_sum)``."""
    from jax import lax

    def scan_fn(acc, xs):
        t = xs[0]
        m = xs[1] if len(xs) > 1 else None
        nll_sum, n_tok, grads = sum_grads(params, t, m)
        grads = lax.optimization_barrier(grads)
        acc_grads, acc_nll, acc_tok = acc
        acc_grads = jax.tree.map(
            lambda a, g: a + g.astype(jnp.float32),
            acc_grads, grads,
        )
        return lax.optimization_barrier(
            (acc_grads, acc_nll + nll_sum, acc_tok + n_tok)
        ), None

    zero = jax.tree.map(
        lambda p: jnp.zeros_like(p, dtype=jnp.float32), params
    )
    xs = (toks, lm) if lm is not None else (toks,)
    (grads, nll_sum, tok_sum), _ = jax.lax.scan(
        scan_fn, (zero, jnp.float32(0.0), jnp.float32(0.0)), xs
    )
    return grads, nll_sum, tok_sum


def make_train_step(
    cfg: ModelConfig,
    optimizer: optax.GradientTransformation,
    *,
    n_micro: int = 1,
    remat: bool = True,
    loss_fn: Callable | None = None,
    donate: bool = True,
    zero1: bool = False,
    mesh: Any = None,
    dp_axis: str = "data",
    tp_axis: str | None = None,
) -> TrainStep:
    """Build the compiled train step.

    ``n_micro > 1`` splits the batch inside the program and accumulates
    gradients with ``lax.scan`` (sequential — bounds activation memory the
    same way the reference's micro-batch pipeline does, without threads).

    ``zero1=True`` (docs/TRAINING.md) shards the WEIGHT UPDATE across the
    ``dp_axis`` of ``mesh``: each replica scans its contiguous block of
    the global micro-batches, partial gradient sums reduce cross-replica
    in a fixed gather order (bitwise-deterministic, the quantized_psum
    trick), and the optax update runs over optimizer state stored 1/dp
    per replica — declared through ``PartitionSpec``/sharding constraints
    so GSPMD shards the elementwise update math and re-replicates the
    params with one all-gather. Forward/backward and ``lax.scan``
    microbatching are byte-for-byte the unsharded path's (shared helper);
    with ``n_micro == dp`` the whole step is bit-identical to
    ``zero1=False`` (test-pinned). Requires ``n_micro % dp == 0`` so each
    replica scans whole micro-batches; buffer donation is preserved.

    ``tp_axis`` (with ``zero1=True``) composes the update sharding with
    the serving path's tensor parallelism: params flow through the step
    AS the serving shards (``tp_partition_specs``), gathered whole
    per-device for forward/backward, and the optimizer slice axis
    becomes the flattened ``dp·tp`` grid — see the module docstring and
    docs/SHARDING.md. The batch still shards over ``dp_axis`` only.
    """
    loss_fn = loss_fn or causal_lm_loss

    def compute_grads(params, tokens, loss_mask):
        grad_fn = jax.value_and_grad(
            lambda p: loss_fn(p, cfg, tokens, loss_mask, remat=remat),
            has_aux=True,
        )
        (loss, aux), grads = grad_fn(params)
        return loss, aux, grads

    def sum_grads(params, tokens, loss_mask):
        # token-SUM objective for the micro accumulation: seeding the
        # backward with the micro's token count yields sum-form grads
        # directly, so the scan carry is a plain add — a mean-form
        # backward rescaled by n_tok after the fact is NOT bitwise
        # stable across scan lengths (see _accum_micro_grads)
        def objective(p):
            loss, aux = loss_fn(p, cfg, tokens, loss_mask, remat=remat)
            return loss * aux["n_tokens"].astype(jnp.float32), aux

        (nll_sum, aux), grads = jax.value_and_grad(
            objective, has_aux=True
        )(params)
        return nll_sum, aux["n_tokens"].astype(jnp.float32), grads

    if zero1:
        tp_pspecs = None
        if tp_axis is not None:
            from ..models.transformer import tp_partition_specs, tp_shardable

            if mesh is None:
                raise ValueError("tp_axis requires a mesh")
            if tp_axis not in dict(mesh.shape):
                raise ValueError(
                    f"mesh has no {tp_axis!r} axis: {dict(mesh.shape)}"
                )
            reason = tp_shardable(cfg, int(mesh.shape[tp_axis]))
            if reason is not None:
                raise ValueError(f"tp_axis={tp_axis!r}: {reason}")
            tp_pspecs = tp_partition_specs(cfg, axis=tp_axis)
        return _make_zero1_step(
            optimizer, sum_grads, mesh=mesh, dp_axis=dp_axis,
            n_micro=n_micro, donate=donate,
            tp_axis=tp_axis, tp_pspecs=tp_pspecs,
        )
    if tp_axis is not None:
        raise ValueError("tp_axis requires zero1=True (the sharded step)")

    def step(params, opt_state, batch):
        tokens = batch["tokens"]
        loss_mask = batch.get("loss_mask")
        if n_micro > 1:
            B = tokens.shape[0]
            if B % n_micro != 0:
                raise ValueError(
                    f"batch {B} not divisible by n_micro={n_micro}"
                )
            mb = B // n_micro
            toks = tokens[: mb * n_micro].reshape(n_micro, mb, -1)
            lm = (
                loss_mask[: mb * n_micro].reshape(n_micro, mb, -1)
                if loss_mask is not None
                else None
            )
            grads, nll_sum, tok_sum = _accum_micro_grads(
                sum_grads, params, toks, lm
            )
            tok_sum = jnp.maximum(tok_sum, 1.0)
            # hand the optimizer grads in param dtype, matching n_micro=1
            # (keeps opt_state dtypes stable across both paths)
            grads = jax.tree.map(
                lambda g, p: (g / tok_sum).astype(p.dtype), grads, params
            )
            loss = nll_sum / tok_sum
        else:
            loss, _aux, grads = compute_grads(params, tokens, loss_mask)

        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    donate_args = (0, 1) if donate else ()
    return TrainStep(
        step_fn=jax.jit(step, donate_argnums=donate_args), optimizer=optimizer
    )


def _dp_shardable(shape, dp: int) -> bool:
    """THE zero1 sharding predicate — shared by the spec derivation and
    the step's in-region slicing so a state leaf can never shard
    differently from the param/grad slice it updates."""
    return bool(shape) and shape[0] >= dp and shape[0] % dp == 0


def _make_zero1_step(
    optimizer, sum_grads, *, mesh, dp_axis, n_micro, donate,
    tp_axis=None, tp_pspecs=None,
) -> TrainStep:
    """The ZeRO-1 step body (see make_train_step). Split out so the
    unsharded path above stays byte-identical to its pre-zero1 shape.

    Layout (docs/TRAINING.md): params and gradients stay REPLICATED over
    the dp axis (forward/backward need whole params); only the optimizer
    state shards. The whole step is one shard_map region —

    1. local ``lax.scan`` micro accumulation on each replica's batch
       block (the shared helper, fp32 sum-form carry),
    2. fixed-gather-order cross-replica reduction (bitwise — the
       quantized_psum trick; a psum's ring order varies by position),
    3. the global-norm clip stage on the FULL replicated gradient
       (bitwise the unsharded chain's own first stage),
    4. the inner elementwise update on each replica's 1/dp slice of
       (grads, params) against its resident 1/dp optimizer-state shard —
       elementwise math is slice-invariant, proven bitwise in tests,
    5. one tiled all_gather re-replicates the updated params.

    The inner update must be SHARD-LOCAL (elementwise): adam/adamw/sgd
    qualify; adafactor's factored second moments do not and are refused.
    A plain optax transformation (not from ``make_optimizer``) is trusted
    to be shard-local — wrap global-norm stages via ``make_optimizer`` so
    the clip split applies.

    ``tp_axis`` composes the step with explicit tensor parallelism
    (docs/SHARDING.md): params enter/leave the region as their LOCAL
    serving shards (``tp_pspecs``), step 0.5 all-gathers each sharded
    leaf whole along its own sharded dim (tiled — exact reassembly, so
    the forward/backward sees bitwise the unsharded weights), the
    reduction in step 2 runs over ``dp_axis`` only (grads land
    replicated over tp for free: every tp peer saw the same batch
    block and the same full params), and steps 4-5 slice by the
    flattened ``data_idx · tp + tp_idx`` device index and re-gather
    over BOTH axes in that order — optimizer state persists 1/(dp·tp)
    per device. With ``tp_axis=None`` every helper degenerates to the
    plain zero1 shape above."""
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import get_shard_map

    if mesh is None:
        raise ValueError("zero1=True requires a mesh with a dp axis")
    if dp_axis not in dict(mesh.shape):
        raise ValueError(f"mesh has no {dp_axis!r} axis: {dict(mesh.shape)}")
    dp = int(mesh.shape[dp_axis])
    if dp < 2:
        raise ValueError(
            f"zero1 needs {dp_axis} > 1 (got {dp}) — the planner picks the "
            "unsharded step for single-replica meshes"
        )
    if n_micro % dp != 0:
        raise ValueError(
            f"zero1 needs n_micro ({n_micro}) divisible by {dp_axis}={dp} "
            "so each replica scans whole micro-batches"
        )
    grad_clip = getattr(optimizer, "grad_clip", None)
    inner = getattr(optimizer, "inner", optimizer)
    if getattr(optimizer, "name", "") == "adafactor":
        raise ValueError(
            "zero1 requires a shard-local (elementwise) optimizer update; "
            "adafactor's factored second moments are not — use adamw/sgd"
        )
    tp = int(mesh.shape[tp_axis]) if tp_axis else 1
    world = dp * tp  # the flattened update-slice grid
    local_micro = n_micro // dp
    shard_map = get_shard_map()
    replicated = NamedSharding(mesh, P())

    def _tp_dim(spec):
        """Index of the tp-sharded dim in a weight's partition spec, or
        None for replicated leaves (norms, embeddings)."""
        for i, part in enumerate(tuple(spec)):
            if part == tp_axis:
                return i
        return None

    def gather_full(params):
        """Reassemble whole weights from this device's serving shards —
        tiled all_gather along each leaf's own sharded dim is EXACT
        (concatenation of the original column blocks in axis order), so
        downstream forward/backward math is bitwise the unsharded
        step's."""
        if tp_axis is None:
            return params
        return jax.tree.map(
            lambda x, sp: x if _tp_dim(sp) is None else lax.all_gather(
                x, tp_axis, axis=_tp_dim(sp), tiled=True
            ),
            params, tp_pspecs,
        )

    def slice_leaf(x, idx):
        shape = tuple(x.shape)
        if not _dp_shardable(shape, world):
            return x
        blk = shape[0] // world
        return lax.dynamic_slice_in_dim(x, idx * blk, blk, axis=0)

    def region(params, opt_state, tokens, loss_mask):
        # runs per replica inside shard_map: this replica's batch shard is
        # its contiguous block of the global micro sequence, scanned with
        # the SAME fp32 sum-form carry as the unsharded path
        params = gather_full(params)
        mb = tokens.shape[0] // local_micro
        toks = tokens.reshape(local_micro, mb, -1)
        lm = (
            loss_mask.reshape(local_micro, mb, -1)
            if loss_mask is not None else None
        )
        partial, nll, ntok = _accum_micro_grads(
            sum_grads, params, toks, lm
        )

        # Fixed-order cross-replica reduction: all_gather the partial
        # sums, then add them left-to-right in replica order — the
        # accumulation tree extends the scan carry exactly, so with one
        # micro per replica the reduced gradient is BITWISE the unsharded
        # scan's (unlike psum, whose ring accumulation order varies with
        # device position — the same reasoning as ring.quantized_psum).
        # Every replica computes the identical full value, which is what
        # lets out_specs declare the results replicated. (Sole caveat: an
        # exact-zero partial may normalize -0.0 → +0.0 — invisible to
        # every downstream op.)
        def ordered(x):
            g = lax.all_gather(x, dp_axis, axis=0)
            acc = g[0]
            for i in range(1, dp):
                acc = acc + g[i]
            return acc

        grads = jax.tree.map(ordered, partial)
        nll_sum, tok_sum = ordered(nll), ordered(ntok)
        tok_sum = jnp.maximum(tok_sum, 1.0)
        grads = jax.tree.map(
            lambda g, p: (g / tok_sum).astype(p.dtype), grads, params
        )
        loss = nll_sum / tok_sum
        gnorm = optax.global_norm(grads)

        # the global-norm clip needs the FULL gradient (a shard's norm is
        # not the global norm): run the chain's own clip stage on the
        # replicated grads — the exact transformation the unsharded chain
        # applies, on bitwise-identical inputs
        if grad_clip is not None:
            clip_t = optax.clip_by_global_norm(grad_clip)
            grads_in, _ = clip_t.update(grads, clip_t.init(params), params)
            clip_state, inner_state = opt_state[0], opt_state[1]
        else:
            grads_in = grads
            clip_state, inner_state = None, opt_state

        # the sharded weight update: this device's 1/world slice of grads
        # + params against its RESIDENT 1/world optimizer-state shard
        # (the in_specs delivered it as local blocks — state never
        # re-replicates); elementwise updates are slice-invariant, so the
        # gathered result is bitwise the full update's. Under TP the
        # slice index is the FLATTENED (dp, tp) grid position — the
        # device order serving_mesh documents.
        idx = lax.axis_index(dp_axis)
        if tp_axis is not None:
            idx = idx * tp + lax.axis_index(tp_axis)
        g_r = jax.tree.map(lambda x: slice_leaf(x, idx), grads_in)
        p_r = jax.tree.map(lambda x: slice_leaf(x, idx), params)
        u_r, new_inner = inner.update(g_r, inner_state, p_r)
        newp_r = optax.apply_updates(p_r, u_r)

        def unslice(full, piece):
            if _dp_shardable(tuple(full.shape), world):
                axes = (dp_axis, tp_axis) if tp_axis is not None else dp_axis
                return lax.all_gather(piece, axes, axis=0, tiled=True)
            return piece

        new_params = jax.tree.map(unslice, params, newp_r)
        if tp_axis is not None:
            # hand the updated weights back as this device's SERVING
            # shard (the out_specs layout): exact column re-slice of the
            # full update — the serve-train hot-swap publishes these
            # without any relayout
            def reslice(x, sp):
                d = _tp_dim(sp)
                if d is None:
                    return x
                sz = x.shape[d] // tp
                return lax.dynamic_slice_in_dim(
                    x, lax.axis_index(tp_axis) * sz, sz, axis=d
                )

            new_params = jax.tree.map(reslice, new_params, tp_pspecs)
        new_state = (
            (clip_state, new_inner) if grad_clip is not None else new_inner
        )
        return new_params, new_state, loss, gnorm

    def step(params, opt_state, batch):
        tokens = batch["tokens"]
        loss_mask = batch.get("loss_mask")
        B = tokens.shape[0]
        if B % n_micro != 0:
            raise ValueError(f"batch {B} not divisible by n_micro={n_micro}")
        mb = B // n_micro
        toks = tokens[: mb * n_micro]
        lm = loss_mask[: mb * n_micro] if loss_mask is not None else None
        pspecs = (
            tp_pspecs if tp_axis is not None
            else jax.tree.map(lambda _: P(), params)
        )
        state_axes = (dp_axis, tp_axis) if tp_axis is not None else dp_axis
        sspecs = optimizer_state_specs(
            optimizer, params, jax.tree.map(lambda _: P(), params),
            dp_axis=state_axes, dp_size=world,
        )
        out_sspecs = (
            (sspecs[0], sspecs[1]) if grad_clip is not None else sspecs
        )
        if lm is None:
            fn = shard_map(
                lambda p, s, t: region(p, s, t, None),
                mesh=mesh,
                in_specs=(pspecs, sspecs, P(dp_axis)),
                out_specs=(pspecs, out_sspecs, P(), P()),
            )
            new_params, new_state, loss, gnorm = fn(params, opt_state, toks)
        else:
            fn = shard_map(
                region, mesh=mesh,
                in_specs=(pspecs, sspecs, P(dp_axis), P(dp_axis)),
                out_specs=(pspecs, out_sspecs, P(), P()),
            )
            new_params, new_state, loss, gnorm = fn(
                params, opt_state, toks, lm
            )
        return new_params, new_state, {"loss": loss, "grad_norm": gnorm}

    jit_step = jax.jit(step, donate_argnums=(0, 1) if donate else ())

    def step_fn(params, opt_state, batch):
        # bounded-compile discipline: entry params may arrive committed
        # anywhere (init_params: one device; a checkpoint restore: host)
        # — normalize them to ONE layout before the jit (replicated, or
        # the serving shards under TP), so the cache holds at most the
        # cold-entry program plus the steady-state program whose inputs
        # are the previous step's outputs (tests pin n_programs() <= 2,
        # churn-free)
        if tp_axis is not None:
            params = jax.tree.map(
                lambda x, sp: x
                if getattr(x, "sharding", None) == NamedSharding(mesh, sp)
                else jax.device_put(x, NamedSharding(mesh, sp)),
                params, tp_pspecs,
            )
        else:
            params = jax.tree.map(
                lambda x: x if getattr(x, "sharding", None) == replicated
                else jax.device_put(x, replicated),
                params,
            )
        return jit_step(params, opt_state, batch)

    step_fn._cache_size = jit_step._cache_size  # the compile-guard probe
    return TrainStep(
        step_fn=step_fn,
        optimizer=optimizer, mode="zero1", mesh=mesh, dp_axis=dp_axis,
        tp_axis=tp_axis,
    )


def optimizer_state_specs(
    optimizer: optax.GradientTransformation, params, param_specs,
    *, dp_axis: "str | tuple | None" = None, dp_size: int = 0,
):
    """PartitionSpec pytree for the optax state: any sub-tree that mirrors
    the param tree (adam moments, momentum buffers) shards like the params;
    scalars (step counts) replicate. The reference keeps optimizer state on
    each worker next to its modules (ml/optim.py init fan-out) — same
    locality, but declared to the compiler instead of managed by RPC.

    ``dp_axis``/``dp_size`` is the ZeRO-1 extension (docs/TRAINING.md):
    every state leaf whose leading dim divides ``dp_size`` additionally
    shards over ``dp_axis`` (only where the param spec leaves dim 0
    unsharded — composing with an existing dim-0 axis is refused rather
    than guessed), dropping persistent per-replica bytes to ~1/dp. Under
    GSPMD the dp sharding is pure LAYOUT: elementwise update math is
    partition-invariant, so this never changes a step's values.
    ``dp_axis`` may be a TUPLE of mesh axes — the zero1 × TP step passes
    ``(dp_axis, tp_axis)`` so state shards over the flattened device
    grid (~1/(dp·tp) resident bytes).

    Hardened for optax states whose sub-trees DON'T mirror the param tree
    (``optax.masked`` moment trees carry ``MaskedNode`` placeholders,
    factored states carry row/col vectors, chains nest ``EmptyState``):
    a non-mirroring array leaf inherits the spec of the unique same-shape
    param when one exists, else shards over ``dp_axis`` when divisible —
    a moment buffer is never silently replicated; leaves we genuinely
    can't place replicate with a WARNING (unit-tested in
    tests/test_zero1.py)."""
    from jax.sharding import PartitionSpec as P

    from ..core.logging import get_logger

    state_shapes = jax.eval_shape(optimizer.init, params)
    pdef = jax.tree.structure(params)

    def is_param_tree(node):
        try:
            return jax.tree.structure(node) == pdef
        except Exception:
            return False

    def with_dp(spec, shape):
        """Extend ``spec`` with the dp axis on an unsharded, divisible
        leading dim; anything else passes through unchanged."""
        if not dp_axis or dp_size <= 1:
            return spec
        if not shape or shape[0] < dp_size or shape[0] % dp_size:
            return spec
        parts = list(tuple(spec))
        parts += [None] * (len(shape) - len(parts))
        if parts[0] is not None:
            return spec  # dim 0 already sharded — never compose, refuse
        parts[0] = dp_axis
        return P(*parts)

    def _shape(leaf) -> tuple:
        return tuple(getattr(leaf, "shape", ()) or ())

    # shape → candidate specs, the fallback for state leaves OUTSIDE a
    # mirroring sub-tree (masked/chained/factored optax states)
    shape_specs: dict[tuple, list] = {}
    spec_leaves = jax.tree.leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P)
    )
    for pl, sp in zip(jax.tree.leaves(params), spec_leaves):
        cands = shape_specs.setdefault(_shape(pl), [])
        if sp not in cands:
            cands.append(sp)

    log = get_logger("engine.training")

    def spec_for_stray(leaf):
        shape = _shape(leaf)
        cands = shape_specs.get(shape, [])
        if len(cands) == 1:
            return with_dp(cands[0], shape)
        if dp_axis and dp_size > 1 and shape \
                and shape[0] >= dp_size and shape[0] % dp_size == 0:
            # moment-like buffer with no (unambiguous) param twin: dp
            # sharding is safe layout — never silently replicate it
            return with_dp(P(), shape)
        if shape and any(d > 1 for d in shape):
            log.warning(
                "optimizer state leaf of shape %s matches no unique param "
                "layout — replicating it (candidates: %s)", shape, cands,
            )
        return P()

    def map_node(node):
        if is_param_tree(node):
            return jax.tree.map(
                lambda sp, leaf: with_dp(sp, _shape(leaf)),
                param_specs, node,
                is_leaf=lambda x: isinstance(x, P),
            )
        return spec_for_stray(node)

    return jax.tree.map(map_node, state_shapes, is_leaf=is_param_tree)
