"""Jittable token sampling.

The reference delegates sampling to HF ``generate()`` kwargs
(temperature/top-p/top-k normalized in ml/formatter.py:7-117); here sampling
is a pure function compiled into the decode program so the token loop never
leaves the device.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass
class SamplingParams:
    """Dynamic sampling knobs — pytree leaves so one compiled program serves
    every request (no recompile per temperature change)."""

    temperature: jax.Array  # f32 scalar; <=0 → greedy
    top_k: jax.Array  # int32 scalar; 0 → disabled
    top_p: jax.Array  # f32 scalar; >=1 → disabled

    @classmethod
    def make(cls, temperature=0.0, top_k=0, top_p=1.0) -> "SamplingParams":
        return cls(
            temperature=jnp.float32(temperature),
            top_k=jnp.int32(top_k),
            top_p=jnp.float32(top_p),
        )


@jax.jit
def sample(
    logits: jax.Array,  # [B, V] float
    key: jax.Array,
    p: SamplingParams,
) -> jax.Array:
    """Temperature / top-k / top-p sampling, greedy when temperature<=0.

    Fully vectorized: filters are masks over the sorted distribution, so the
    same program handles any (k, p) at runtime.

    jit at the definition is load-bearing: the ``lax.cond`` below builds
    fresh branch closures per call, so an EAGER call can never hit jax's
    trace cache and pays a full XLA compile of the sampled branch (argsort
    over the vocab) every time — ~0.5 s on CPU, seconds on TPU. That
    exact miss sat on every ``generate_compiled`` call (the prefill-token
    sample) and every host-driven decode step, and was the dominant term in
    the round-2 decode benchmark (25 tok/s vs 101 roofline). Inside an
    enclosing jit the wrapper inlines and changes nothing.
    """
    logits = logits.astype(jnp.float32)
    B, V = logits.shape

    def sampled(_):
        scaled = logits / jnp.maximum(p.temperature, 1e-6)
        sort_idx = jnp.argsort(-scaled, axis=-1)
        sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        ranks = jnp.arange(V)[None, :]
        # top-k: keep ranks < k (k==0 → keep all)
        k = jnp.where(p.top_k > 0, p.top_k, V)
        keep = ranks < k
        # top-p: keep the smallest prefix with cumulative prob >= p
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep &= (cum - probs) < p.top_p
        masked = jnp.where(keep, sorted_logits, -jnp.inf)
        choice = jax.random.categorical(key, masked, axis=-1)  # [B]
        return jnp.take_along_axis(sort_idx, choice[:, None], axis=-1)[:, 0]

    def greedy(_):
        return logits.argmax(-1)

    return jax.lax.cond(p.temperature > 0.0, sampled, greedy, None).astype(
        jnp.int32
    )
