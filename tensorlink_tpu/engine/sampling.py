"""Jittable token sampling.

The reference delegates sampling to HF ``generate()`` kwargs
(temperature/top-p/top-k normalized in ml/formatter.py:7-117); here sampling
is a pure function compiled into the decode program so the token loop never
leaves the device.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass
class SamplingParams:
    """Dynamic sampling knobs — pytree leaves so one compiled program serves
    every request (no recompile per temperature change).

    Leaves are scalars for a single request, or ``[B, 1]`` for a batched
    mix of requests with different knobs (the serving batcher,
    ml/batching.py) — :func:`sample` broadcasts either shape."""

    temperature: jax.Array  # f32; <=0 → greedy
    top_k: jax.Array  # int32; 0 → disabled
    top_p: jax.Array  # f32; >=1 → disabled
    # OpenAI-style repetition control (0 → disabled): logits of tokens seen
    # in the context so far are shifted by
    #   -presence·1[count>0] - frequency·count
    # (applied in :func:`sample` when the caller supplies token counts —
    # the reference declares these fields, api/models.py:73-74, but never
    # applies them)
    presence_penalty: jax.Array = None  # type: ignore[assignment]
    frequency_penalty: jax.Array = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.presence_penalty is None:
            object.__setattr__(self, "presence_penalty", jnp.float32(0.0))
        if self.frequency_penalty is None:
            object.__setattr__(self, "frequency_penalty", jnp.float32(0.0))

    @classmethod
    def make(
        cls, temperature=0.0, top_k=0, top_p=1.0,
        presence_penalty=0.0, frequency_penalty=0.0,
    ) -> "SamplingParams":
        return cls(
            temperature=jnp.float32(temperature),
            top_k=jnp.int32(top_k),
            top_p=jnp.float32(top_p),
            presence_penalty=jnp.float32(presence_penalty),
            frequency_penalty=jnp.float32(frequency_penalty),
        )

    def pad_rows(self, batch: int) -> "SamplingParams":
        """Pad per-row leaves to the engine's bucketed batch size (extra
        rows decode greedily); scalar leaves pass through untouched."""
        if jnp.asarray(self.temperature).ndim == 0:
            return self
        n = jnp.asarray(self.temperature).reshape(-1).shape[0]
        if n == batch:
            return self

        def pad(leaf, fill, dtype):
            flat = jnp.asarray(leaf, dtype).reshape(-1)
            return jnp.concatenate(
                [flat, jnp.full((batch - n,), fill, dtype)]
            )[:, None]

        return SamplingParams(
            temperature=pad(self.temperature, 0.0, jnp.float32),
            top_k=pad(self.top_k, 0, jnp.int32),
            top_p=pad(self.top_p, 1.0, jnp.float32),
            presence_penalty=pad(self.presence_penalty, 0.0, jnp.float32),
            frequency_penalty=pad(self.frequency_penalty, 0.0, jnp.float32),
        )

    @classmethod
    def stack(cls, params: "list[SamplingParams]", pad_to: int) -> "SamplingParams":
        """Per-row knobs for a batched generate; rows past ``len(params)``
        (bucket padding) decode greedily."""
        def col(attr, fill, dtype):
            vals = [float(jnp.asarray(getattr(p, attr))) for p in params]
            vals += [fill] * (pad_to - len(vals))
            return jnp.asarray(vals, dtype)[:, None]  # [B, 1]

        return cls(
            temperature=col("temperature", 0.0, jnp.float32),
            top_k=col("top_k", 0, jnp.int32),
            top_p=col("top_p", 1.0, jnp.float32),
            presence_penalty=col("presence_penalty", 0.0, jnp.float32),
            frequency_penalty=col("frequency_penalty", 0.0, jnp.float32),
        )


@jax.jit
def sample(
    logits: jax.Array,  # [B, V] float
    key: jax.Array,
    p: SamplingParams,
    counts: jax.Array | None = None,  # int32 [B, V] context token counts
) -> jax.Array:
    """Temperature / top-k / top-p sampling, greedy when temperature<=0.

    Fully vectorized: filters are masks over the sorted distribution, so the
    same program handles any (k, p) at runtime.

    jit at the definition is load-bearing: the ``lax.cond`` below builds
    fresh branch closures per call, so an EAGER call can never hit jax's
    trace cache and pays a full XLA compile of the sampled branch (argsort
    over the vocab) every time — ~0.5 s on CPU, seconds on TPU. That
    exact miss sat on every ``generate_compiled`` call (the prefill-token
    sample) and every host-driven decode step, and was the dominant term in
    the round-2 decode benchmark (25 tok/s vs 101 roofline). Inside an
    enclosing jit the wrapper inlines and changes nothing.

    Scalar knobs apply to every row (with an all-greedy fast path that
    skips the vocab argsort); ``[B, 1]`` knobs mix per-row settings in one
    batch and select greedy/sampled per row.
    """
    logits = logits.astype(jnp.float32)
    B, V = logits.shape
    if counts is not None:
        # OpenAI-style repetition control over the context so far
        pres = jnp.broadcast_to(
            jnp.atleast_1d(p.presence_penalty).reshape(-1, 1), (B, 1)
        )
        freq = jnp.broadcast_to(
            jnp.atleast_1d(p.frequency_penalty).reshape(-1, 1), (B, 1)
        )
        cf = counts.astype(jnp.float32)
        logits = logits - pres * (cf > 0) - freq * cf
    temp = jnp.broadcast_to(jnp.atleast_1d(p.temperature).reshape(-1, 1), (B, 1))
    top_k = jnp.broadcast_to(jnp.atleast_1d(p.top_k).reshape(-1, 1), (B, 1))
    top_p = jnp.broadcast_to(jnp.atleast_1d(p.top_p).reshape(-1, 1), (B, 1))

    def sampled(_):
        scaled = logits / jnp.maximum(temp, 1e-6)
        sort_idx = jnp.argsort(-scaled, axis=-1)
        sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        ranks = jnp.arange(V)[None, :]
        # top-k: keep ranks < k (k==0 → keep all)
        k = jnp.where(top_k > 0, top_k, V)
        keep = ranks < k
        # top-p: keep the smallest prefix with cumulative prob >= p
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep &= (cum - probs) < top_p
        masked = jnp.where(keep, sorted_logits, -jnp.inf)
        choice = jax.random.categorical(key, masked, axis=-1)  # [B]
        picks = jnp.take_along_axis(sort_idx, choice[:, None], axis=-1)[:, 0]
        # per-row greedy/sampled selection for mixed batches
        return jnp.where(temp[:, 0] > 0.0, picks, logits.argmax(-1))

    def greedy(_):
        return logits.argmax(-1)

    return jax.lax.cond(temp.max() > 0.0, sampled, greedy, None).astype(
        jnp.int32
    )
