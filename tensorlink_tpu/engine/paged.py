"""Block-paged KV cache + slot-batched decode step (continuous batching).

The dense :class:`~tensorlink_tpu.models.base.KVCache` is ``[L, B, S_max,
n_kv, hd]`` — one contiguous span per batch row, so a batched decode is
welded to one (B, S_max) shape and a finished row's span stays allocated
until the whole batch drains. Here KV lives in fixed-size **pages**
``[L, P, n_kv, page, hd]`` (kv-head-major, so the Pallas kernel's
per-(page, head) blocks carry TPU-native ``(page, hd)`` trailing tiles)
with a per-slot **block table**: sequences of
ragged lengths share ONE compiled decode program (the block table and
lengths are data, not shape), a finished slot's pages return to the
free-list immediately, and a queued prompt is admitted by writing a new
block-table row — no recompile, no cache realloc.

Page 0 is a reserved scratch page: free slots ride the fixed slot-batch
shape with an all-zero block-table row and length 0, so their (masked,
invisible) per-step KV writes land on scratch instead of a page another
slot owns — that invariant is what makes eviction safe with zero
cross-slot contamination.

Attention routes through ops/attention.py: the Pallas
:func:`~tensorlink_tpu.ops.attention.ragged_paged_attention` kernel on
TPU (whole mixed prefill+decode block, KV gathered page-by-page via a
scalar-prefetched block table) with
:func:`~tensorlink_tpu.ops.attention.ragged_paged_attention_ref` on CPU
and in parity tests; the decode continuation inside the step runs the
:func:`~tensorlink_tpu.ops.attention.paged_attention` kernel per token.

**Quantized pages** (``MLConfig.kv_quant="int8"`` / ``"int4"``): the page
pool stores KV int8 — or PACKED int4, two values per byte over a
split-half nibble layout (models/quant.py::quantize_kv4) — with
per-(page, position, head) symmetric f32 scales carried page-granular
alongside the payload. Quantization happens at THE one page-write path
(``_ragged_write_indices`` feeds every program), one position at a time —
a position's (quantized bytes, scale) pair depends only on its own KV
row, so the bitwise cache contract survives by construction: a quantized
page + its scale rows IS the cache value, and COW ``copy_page``, trie
promotion, LRU eviction, crash-recovery re-prefill and preemption resume
all move it byte-exactly. The kernels dequantize at the page fetch
(nibble unpack + scale multiply fused into the HBM read), so KV bytes
halve (int8) or quarter (int4) while the MXU math stays in the model
dtype — ~2×/~4× serving slots and prefix-cache residency at fixed HBM.

**Multi-tenant pool** (:class:`SharedPagePool`): co-hosted models with
matching page geometry share ONE physical pool under per-tenant quotas —
the reclaimed HBM spent on scenario diversity instead of headroom.
"""

from __future__ import annotations

import hashlib
import heapq
from dataclasses import dataclass, replace
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from ..models.base import ModelConfig
from ..models.transformer import (
    _embed_tokens,
    _logits,
    _mlp,
    _norm,
    _rms_head_norm,
    _tp_gather,
    apply_rope,
    _rope_dim,
    rope_tables,
    tp_partition_specs,
    tp_shardable,
)
from ..models.quant import matmul as _mm
from ..models.quant import quantize_kv as _quant_kv
from ..models.quant import quantize_kv4 as _quant_kv4
from ..ops.attention import (
    paged_attention,
    paged_attention_ref,
    ragged_paged_attention,
    ragged_paged_attention_ref,
)


@jax.tree_util.register_dataclass
@dataclass
class PagedKVCache:
    """Paged decode cache: ``k``/``v`` are ``[L, P, n_kv, page, hd]``,
    ``block_tables`` maps each serving slot to its pages ``[S, n_pp]``
    (0 = the reserved scratch page), ``lengths`` counts valid positions
    per slot ``[S]``. Stacked over layers like the dense cache so the
    decode ``lax.scan`` indexes its layer slice; donated into the step so
    XLA updates pages in place.

    **int8 mode** (``quantized=True``): ``k``/``v`` hold int8 and
    ``k_scale``/``v_scale`` ``[L, P, n_kv, page]`` carry the
    per-(page, position, head) symmetric f32 scales — page-granular
    storage, so every page operation (COW, promotion, eviction, clear)
    moves payload and scales together byte-exactly."""

    k: jax.Array
    v: jax.Array
    block_tables: jax.Array  # int32 [S, pages_per_slot]
    lengths: jax.Array  # int32 [S]
    k_scale: jax.Array | None = None  # f32 [L, P, n_kv, page] — int8 mode
    v_scale: jax.Array | None = None

    @classmethod
    def init(
        cls,
        cfg: ModelConfig,
        max_slots: int,
        *,
        page_size: int = 16,
        max_len: int | None = None,
        dtype=None,
        quantized: bool = False,
        kv_quant: str | None = None,
        n_pages: int | None = None,
    ) -> "PagedKVCache":
        """``kv_quant`` ("none"/"int8"/"int4") supersedes the legacy
        ``quantized`` bool (kept as an "int8" alias). ``n_pages``
        overrides the slots×capacity pool sizing — how a shared
        multi-tenant pool decouples its page budget from any one
        tenant's slot count (:class:`SharedPagePool`)."""
        mode = kv_quant or ("int8" if quantized else "none")
        S_max = max_len or cfg.max_seq_len
        n_pp = -(-S_max // page_size)  # pages per slot (ceil)
        # page 0 = scratch, never allocated
        P = n_pages if n_pages is not None else 1 + max_slots * n_pp
        hd = cfg.head_dim
        if mode == "int4":
            if hd % 2:
                raise ValueError(
                    f"kv_quant='int4' packs two values per byte — "
                    f"head_dim {hd} must be even"
                )
            hd //= 2  # packed: two int4 values per stored byte
        shape = (cfg.n_layers, P, cfg.n_kv_heads, page_size, hd)
        if mode in ("int8", "int4"):
            return cls(
                k=jnp.zeros(shape, jnp.int8),
                v=jnp.zeros(shape, jnp.int8),
                block_tables=jnp.zeros((max_slots, n_pp), jnp.int32),
                lengths=jnp.zeros((max_slots,), jnp.int32),
                k_scale=jnp.zeros(shape[:-1], jnp.float32),
                v_scale=jnp.zeros(shape[:-1], jnp.float32),
            )
        if mode != "none":
            raise ValueError(f"unknown kv_quant mode {mode!r}")
        dt = dtype or cfg.dtype
        return cls(
            k=jnp.zeros(shape, dt),
            v=jnp.zeros(shape, dt),
            block_tables=jnp.zeros((max_slots, n_pp), jnp.int32),
            lengths=jnp.zeros((max_slots,), jnp.int32),
        )

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def page_size(self) -> int:
        return self.k.shape[3]

    @property
    def n_pages(self) -> int:
        return self.k.shape[1]

    @property
    def max_slots(self) -> int:
        return self.block_tables.shape[0]

    @property
    def pages_per_slot(self) -> int:
        return self.block_tables.shape[1]


class PageAllocator:
    """Host-side free-list over physical page ids 1..P-1 (0 is scratch).

    Pure bookkeeping — allocation order is irrelevant to correctness (the
    block table names pages explicitly), so a freed page is reused LIFO
    for locality. ``alloc`` is all-or-nothing: admission either gets every
    page a request could need or stays queued."""

    def __init__(self, n_pages: int):
        self._free = list(range(n_pages - 1, 0, -1))  # pop() yields 1 first

    @property
    def n_free(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        if n > len(self._free):
            return None
        return [self._free.pop() for _ in range(n)]

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if p > 0:
                self._free.append(p)


# ---------------------------------------------------------------------------
# Shared multi-tenant page pool (co-hosted models, docs/SERVING.md
# "Co-hosting multiple models")
# ---------------------------------------------------------------------------


class PoolTenant:
    """One co-hosted model's quota-bounded allocator façade over a
    :class:`SharedPagePool` — the ``PageAllocator`` interface a
    ``ContinuousEngine`` consumes (``n_free``/``alloc``/``free``), with
    two extra constraints: an allocation must fit BOTH the shared pool's
    free list and this tenant's page quota, and every page this tenant
    holds (slot-owned, prefix-cache-resident, or in transit) counts
    against ``used`` until it returns through :meth:`free` — which is
    what makes the per-tenant conservation term checkable."""

    def __init__(self, pool: "SharedPagePool", model_id: str, quota: int):
        self.pool = pool
        self.model_id = str(model_id)
        # 0 = uncapped (bounded by the pool alone)
        self.quota = int(quota) if quota else pool.n_pages - 1
        self.used = 0
        self.engine = None  # bound by SharedPagePool.attach

    @property
    def n_free(self) -> int:
        return min(self.pool.alloc.n_free, self.quota - self.used)

    @property
    def _free(self):
        # page_accounting compatibility: the authoritative free list is
        # the shared pool's
        return self.pool.alloc._free

    def alloc(self, n: int) -> list[int] | None:
        if self.used + n > self.quota:
            return None  # quota dry — the tenant's own eviction/preemption
            # ladder must reclaim ITS pages; other tenants are unaffected
        pages = self.pool.alloc.alloc(n)
        if pages is not None:
            self.used += len(pages)
        return pages

    def free(self, pages: list[int]) -> None:
        n = sum(1 for p in pages if p > 0)
        self.pool.alloc.free(pages)
        self.used -= n
        assert self.used >= 0, (
            f"tenant {self.model_id!r} freed more pages than it held"
        )


class SharedPagePool:
    """ONE physical KV page pool shared by several co-hosted tenant
    engines — the multi-tenant density play: the HBM a quantized page
    pool reclaims is spent on MORE MODELS resident per chip instead of
    idle headroom. Tenants must share page geometry (layers, kv heads,
    head_dim, page size, storage mode) — the many-small-fine-tunes
    shape, where N adapters of one base model serve from one worker;
    each keeps its OWN block tables, slots, scheduler, and prefix cache
    (cache keys are per-model by construction — tries never mix), while
    the physical pages and the free list are shared under per-tenant
    quotas.

    Threading contract: the pool extends the engines' single-driver
    discipline ACROSS tenants — every attached engine must be stepped
    from the same driver thread (the worker's run loop already is), so
    cross-tenant reclaim and preemption can walk another tenant's
    host-side state without racing its driver.

    Cross-tenant policy (the PR 4 scheduler's rank rules, extended):
    when a tenant's allocation fails on the SHARED free list (not its
    quota), the admission ladder may (1) evict other tenants'
    refcount-0 prefix-cache pages LRU-first (:meth:`reclaim_cache`),
    then (2) preempt another tenant's strictly-lower-ranked running
    slot (:meth:`cross_model_victim`) through that engine's normal
    preemption path — so an interactive request of model A outranks a
    best_effort slot of model B, but can never touch B's equal-or-
    better-ranked work."""

    def __init__(
        self,
        cfg: ModelConfig,
        n_pages: int,
        *,
        page_size: int = 16,
        dtype=None,
        kv_quant: str = "none",
    ):
        self.page_size = int(page_size)
        self.kv_quant = str(kv_quant or "none")
        proto = PagedKVCache.init(
            cfg, 0, page_size=self.page_size, max_len=self.page_size,
            dtype=dtype, kv_quant=self.kv_quant, n_pages=1 + int(n_pages),
        )
        # the canonical layer-stacked page arrays: tenant engines read
        # them through their cache property and write them back after
        # every donated step — one physical pool, N block-table views
        self.kv: tuple = _cache_kv(proto)
        self.alloc = PageAllocator(1 + int(n_pages))
        self.tenants: dict[str, PoolTenant] = {}
        self.geometry = (
            cfg.n_layers, cfg.n_kv_heads, cfg.head_dim, self.page_size,
            self.kv_quant, str(proto.k.dtype),
        )
        self.cross_preemptions = 0
        self.cache_reclaims = 0

    @property
    def n_pages(self) -> int:
        return self.kv[0].shape[1]

    @property
    def n_free(self) -> int:
        return self.alloc.n_free

    def attach(self, model_id: str, engine, *, quota: int = 0) -> PoolTenant:
        """Register a tenant engine. Geometry must match the pool's —
        a mismatched model cannot share physical pages and must get its
        own pool (loud, never a silent corruption)."""
        t_dtype = (
            "int8" if engine.kv_quant in ("int8", "int4")
            else str(jnp.dtype(engine.engine.cache_dtype))
        )
        geo = (
            engine.cfg.n_layers, engine.cfg.n_kv_heads,
            engine.cfg.head_dim, engine.page_size, engine.kv_quant,
            t_dtype,
        )
        if geo != self.geometry:
            raise ValueError(
                f"tenant {model_id!r} page geometry {geo} does not match "
                f"the shared pool's {self.geometry} — co-hosted models "
                "must share (layers, kv_heads, head_dim, page_size, "
                "kv_quant, dtype)"
            )
        if model_id in self.tenants:
            raise ValueError(f"tenant {model_id!r} already attached")
        t = PoolTenant(self, model_id, quota)
        t.engine = engine
        self.tenants[model_id] = t
        return t

    def detach(self, model_id: str) -> None:
        t = self.tenants.pop(model_id, None)
        assert t is None or t.used == 0, (
            f"tenant {model_id!r} detached holding {t.used} pages"
        )

    # -- cross-tenant reclaim / preemption (single driver thread) --------
    def reclaim_cache(self, n: int, exclude) -> int:
        """Evict up to ``n`` refcount-0 prefix-cache pages from OTHER
        tenants (LRU within each trie) back to the shared free list.
        Returns how many pages came back. The first rung of the
        cross-tenant ladder: cold resident prefixes are the cheapest
        HBM to take — no stream is disturbed."""
        freed = 0
        for t in self.tenants.values():
            if t.engine is exclude or t.engine.prefix is None:
                continue
            need = n - freed
            if need <= 0:
                break
            pages = t.engine.prefix.evict(need)
            if pages:
                t.engine.alloc.free(pages)
                freed += len(pages)
        self.cache_reclaims += freed
        return freed

    def cross_model_victim(self, cand_rank: int, exclude):
        """The running request another tenant should preempt for a
        candidate of effective rank ``cand_rank``, or None — the PR 4
        victim rules applied across models: only slots whose
        ADMISSION-TIME rank is strictly worse are eligible, worst rank
        first (ties broken toward the tenant holding the most pages, so
        one teardown frees the most HBM). Returns ``(engine, request)``;
        the caller preempts through that engine's normal path, so the
        victim's resume contract (promotion, requeue, bit-identical
        stream) is untouched."""
        best = None
        for t in self.tenants.values():
            eng = t.engine
            if eng is exclude:
                continue
            with eng._lock:
                v = eng.sched.victim_for_rank(eng._preemptable(), cand_rank)
            if v is None:
                continue
            key = (v.admit_rank, t.used)
            if best is None or key > best[0]:
                best = (key, eng, v)
        if best is None:
            return None
        self.cross_preemptions += 1
        return best[1], best[2]

    # -- conservation ----------------------------------------------------
    def check_page_conservation(self) -> None:
        """The multi-tenant free-list invariant: shared free + Σ per
        tenant (slot-owned + cache-resident + in-transit) == total
        usable pages, every set pairwise disjoint ACROSS tenants, each
        tenant's ``used`` counter equal to what its engine actually
        holds, scratch page 0 nowhere. Raises AssertionError on
        violation — the per-tenant terms are what keep a quota
        meaningful: a tenant can neither hide pages from its quota nor
        leak them into a neighbor's."""
        problems: list[str] = []
        free = set(self.alloc._free)
        if len(free) != len(self.alloc._free):
            problems.append("shared free-list holds a duplicate page")
        seen: dict[int, str] = {p: "free" for p in free}
        total_held = 0
        for mid, t in self.tenants.items():
            acc = t.engine.page_accounting()
            slots, cached = list(acc["slots"]), set(acc["cached"])
            transit = list(acc["in_transit"])
            if len(slots) != len(set(slots)):
                problems.append(f"[{mid}] a page is owned by two slots")
            if len(transit) != len(set(transit)):
                problems.append(f"[{mid}] a page is in transit twice")
            held = set(slots) | cached | set(transit)
            if len(held) != len(slots) + len(cached) + len(transit):
                problems.append(f"[{mid}] page in two ownership classes")
            for p in held:
                prev = seen.get(p)
                if prev is not None:
                    problems.append(
                        f"page {p} held by both {prev} and {mid}"
                    )
                seen[p] = mid
            n_held = len(slots) + len(cached) + len(transit)
            total_held += n_held
            if n_held != t.used:
                problems.append(
                    f"[{mid}] quota accounting drifted: engine holds "
                    f"{n_held} pages, tenant.used={t.used}"
                )
            if t.used > t.quota:
                problems.append(
                    f"[{mid}] over quota: used={t.used} > {t.quota}"
                )
        if 0 in seen:
            problems.append("scratch page 0 entered an ownership set")
        total = self.n_pages - 1
        if len(free) + total_held != total:
            problems.append(
                f"leak: free={len(free)} + held={total_held} != "
                f"total={total}"
            )
        if problems:
            raise AssertionError(
                "pool page conservation violated: " + "; ".join(problems)
            )

    def snapshot(self) -> dict:
        """Pool-level telemetry (each tenant's engine merges this into
        its serving_snapshot; /metrics reads the same numbers through
        per-engine callback gauges)."""
        return {
            "pool_pages_total": self.n_pages - 1,
            "pool_pages_free": self.alloc.n_free,
            "pool_tenants": len(self.tenants),
            "pool_cross_preemptions": self.cross_preemptions,
            "pool_cache_reclaims": self.cache_reclaims,
            "pool_used": {
                mid: {"used": t.used, "quota": t.quota}
                for mid, t in self.tenants.items()
            },
        }


# ---------------------------------------------------------------------------
# Automatic prefix cache (host-side index over physical pages)
# ---------------------------------------------------------------------------


def chain_hash(parent_hash: str, block) -> str:
    """16-hex-char rolling hash of a trie chain: the previous prefix's
    hash folded with one page-size token block. Structural trie equality
    stays the CACHE key (no collision can ever map a wrong page); these
    hashes exist only so a chain can be NAMED compactly off-box — the
    fleet router scores a replica's cache affinity against a digest of
    them (docs/SERVING.md "Fleet serving") without shipping the trie. A
    collision merely misguides placement by one request, never
    correctness."""
    h = hashlib.blake2b(digest_size=8)
    h.update(parent_hash.encode("ascii"))
    h.update(",".join(str(int(t)) for t in block).encode("ascii"))
    return h.hexdigest()


def prompt_chain_hashes(tokens, page_size: int, max_pages: int) -> list[str]:
    """The rolling chain hashes of ``tokens``' leading full page blocks
    (up to ``max_pages``) — what the router matches against a replica's
    :meth:`PrefixCache.digest`. Index i covers ``(i + 1) * page_size``
    tokens. Host-only, no trie required."""
    out: list[str] = []
    prev = ""
    p = int(page_size)
    limit = min((len(tokens) // p), int(max_pages))
    for i in range(limit):
        prev = chain_hash(prev, tokens[i * p : (i + 1) * p])
        out.append(prev)
    return out


class _TrieNode:
    """One cached FULL page: the KV of ``block`` (page_size token ids) at
    the absolute positions its chain depth implies."""

    __slots__ = (
        "block", "page", "parent", "children", "refs", "tick",
        "depth", "key_hash", "weights_version",
    )

    def __init__(self, block: tuple, page: int, parent: "_TrieNode | None"):
        self.block = block
        self.page = page
        self.parent = parent
        self.children: dict[tuple, _TrieNode] = {}
        self.refs = 0  # slots currently mapping this page
        self.tick = 0  # LRU recency (monotonic engine counter)
        # the model weights version this page's KV was computed under
        # (PrefixCache.insert stamps it): the match fence for live weight
        # publishes — see ContinuousEngine.publish_weights
        self.weights_version = 1
        # chain identity for the fleet digest: pages-from-root count and
        # the rolling chain hash (root carries depth 0 / hash "")
        if parent is None:
            self.depth = 0
            self.key_hash = ""
        else:
            self.depth = parent.depth + 1
            self.key_hash = chain_hash(parent.key_hash, block)


class PrefixCache:
    """Host-side automatic-prefix-cache index over ``PagedKVCache`` pages.

    A trie over page-size token blocks: a node's path from the root IS the
    cache key — the exact token chain from position 0 — so two prompts
    share a cached page only when every earlier token matches, which makes
    the key rope-offset-invariant by construction (same tokens at the same
    absolute positions ⇒ bitwise the same KV). The cache is per engine,
    hence per (model, dtype): no model id needs to ride the key.

    Only FULL pages are cached. ``refs`` counts slots whose block tables
    currently name the page; refcount-0 pages stay resident and are
    evicted leaf-first in LRU order when the allocator runs dry (evicting
    an interior node would orphan descendants whose positions assume it).
    Structural equality (no hashing) means no collision can ever map a
    wrong page — the "hash map" is Python's dict over the block tuples.
    """

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        self.root = _TrieNode((), 0, None)
        self._by_page: dict[int, _TrieNode] = {}
        self._tick = 0
        # bumped on every membership change (insert/evict) so the engine
        # can skip rebuilding the fleet digest when nothing moved
        self.version = 0
        # the CURRENT model weights version (the engine bumps it on every
        # live weight publish, docs/TRAINING.md): inserts stamp it onto
        # their nodes, and match() refuses chains stamped with any other
        # version — cached KV from older weights can never become a hit,
        # which is what keeps the bitwise cache contract true across a
        # hot-swap. Stale refcount-0 chains are evicted at publish time;
        # still-referenced ones free as their slots do.
        self.weights_version = 1
        # the demote seam (docs/SERVING.md "Tiered prefix cache"): when
        # set, evict() hands each victim node to this callable BEFORE the
        # page id returns to the free-list — the engine wires it to the
        # host-RAM tier so the bytes survive the eviction. Best-effort by
        # contract: the spill contains its own failures (a page that
        # fails to demote is simply destroyed, the pre-tier behavior),
        # so eviction itself can never be blocked by the tier below.
        self.spill = None
        self.stats = {
            "lookups": 0,
            "hits": 0,
            "hit_tokens": 0,
            "cow_copies": 0,
            "evictions": 0,
            "inserts": 0,
        }

    # -- introspection ---------------------------------------------------
    @property
    def resident_pages(self) -> set[int]:
        return set(self._by_page)

    @property
    def n_resident(self) -> int:
        return len(self._by_page)

    def digest(self, max_chains: int = 32) -> dict:
        """Compact export of the resident chains for off-box cache-
        affinity scoring (docs/SERVING.md "Fleet serving"): the
        ``max_chains`` most-recently-used nodes as ``{chain_hash:
        covered_tokens}``. Interior prefixes of a hot chain are touched
        by every hit, so recency order naturally exports them too — a
        prompt matching only part of a resident chain still scores.
        Bounded bytes by construction (~26 B/entry serialized), JSON-
        safe, and NEVER authoritative: admission re-walks the real trie,
        so a stale or colliding digest can only misplace a request, not
        corrupt a stream."""
        nodes = sorted(
            (
                n for n in self._by_page.values()
                if n.weights_version == self.weights_version
            ),
            key=lambda n: n.tick, reverse=True,
        )[: max(int(max_chains), 0)]
        return {
            "page_size": self.page_size,
            "chains": {
                n.key_hash: n.depth * self.page_size for n in nodes
            },
        }

    def _touch(self, node: _TrieNode) -> None:
        self._tick += 1
        node.tick = self._tick

    # -- lookup ----------------------------------------------------------
    def _blocks(self, tokens, limit: int):
        p = self.page_size
        for i in range(0, (limit // p) * p, p):
            yield tuple(int(t) for t in tokens[i : i + p])

    def match(self, tokens, limit: int) -> list[_TrieNode]:
        """Longest chain of cached full pages covering ``tokens[:limit]``.
        Returns the matched nodes in position order (refs NOT yet taken —
        callers acquire() before anything can evict, single-driver).
        lookup/hit telemetry is counted at successful ADMISSION, not
        here: a head-of-line request waiting for pages re-matches every
        chunk and must not inflate the operator-facing hit rate."""
        node = self.root
        out: list[_TrieNode] = []
        for block in self._blocks(tokens, limit):
            child = node.children.get(block)
            if child is None or child.weights_version != self.weights_version:
                # a version mismatch fences the WHOLE chain below: its KV
                # was computed under different weights (publish_weights)
                break
            out.append(child)
            self._touch(child)  # a hit IS a use: refresh LRU recency
            node = child
        return out

    def partial_match(
        self, nodes: list[_TrieNode], tokens, limit: int
    ) -> tuple[_TrieNode, int] | None:
        """Best divergent child for copy-on-write: among the children of
        the last matched node, the page whose block shares the LONGEST
        non-empty token prefix with what the request still needs (capped
        at ``limit`` tokens past the full-page hit). The caller copies
        that page and owns the copy — the cached original is never
        written."""
        parent = nodes[-1] if nodes else self.root
        done = len(nodes) * self.page_size
        want = [int(t) for t in tokens[done : done + min(self.page_size, limit - done)]]
        if not want:
            return None
        best: tuple[_TrieNode, int] | None = None
        for block, child in parent.children.items():
            if child.weights_version != self.weights_version:
                # stale-version KV (live weight publish) must not seed a
                # COW copy any more than it may full-page match
                continue
            n = 0
            for a, b in zip(want, block):
                if a != b:
                    break
                n += 1
            if n > 0 and (best is None or n > best[1]):
                best = (child, n)
        return best

    # -- refcounts -------------------------------------------------------
    def acquire(self, nodes: list[_TrieNode]) -> None:
        for n in nodes:
            n.refs += 1
            self._touch(n)

    def release(self, nodes: list[_TrieNode]) -> None:
        for n in nodes:
            assert n.refs > 0, "prefix-cache refcount underflow"
            n.refs -= 1
            self._touch(n)

    # -- insert / evict --------------------------------------------------
    def insert(
        self, parent: "_TrieNode | None", block: tuple, page: int,
        freed: "list[int] | None" = None,
    ) -> tuple[_TrieNode, bool]:
        """Adopt ``page`` as the cached KV of ``block`` under ``parent``
        (None = root). Returns ``(node, adopted)`` — ``adopted=False``
        means an identical chain is already resident: the caller keeps
        ownership of ``page`` (frees it) and continues the walk from the
        existing node.

        A STALE-version unreferenced leaf shadowing this block (its KV
        predates a weight publish, so it can never match again) is
        evicted in place and the fresh page adopted — its page id lands
        in ``freed`` for the caller's allocator. A stale node that still
        has refs or children stays (its readers are mid-stream); the
        fresh page is declined and the chain re-caches once they drain."""
        parent = parent or self.root
        existing = parent.children.get(block)
        if (
            existing is not None
            and existing.weights_version != self.weights_version
            and existing.refs == 0
            and not existing.children
        ):
            del parent.children[block]
            del self._by_page[existing.page]
            self.stats["evictions"] += 1
            self.version += 1
            if freed is not None:
                freed.append(existing.page)
            existing = None
        if existing is not None:
            self._touch(existing)
            return existing, False
        node = _TrieNode(block, int(page), parent)
        node.weights_version = self.weights_version
        parent.children[block] = node
        self._by_page[int(page)] = node
        self._touch(node)
        self.stats["inserts"] += 1
        self.version += 1
        return node, True

    def n_evictable(self) -> int:
        """Pages a (cascading) evict could free in the limit: nodes whose
        WHOLE subtree is unreferenced — a referenced descendant pins its
        ancestors because eviction is leaf-first. Lets the allocator skip
        a destructive cache wipe when eviction can never satisfy the
        allocation anyway."""
        def walk(node: _TrieNode) -> tuple[int, bool]:
            total, clear = 0, node.refs == 0
            for child in node.children.values():
                c_total, c_clear = walk(child)
                total += c_total
                clear = clear and c_clear
            return total + (1 if clear else 0), clear
        return sum(walk(c)[0] for c in self.root.children.values())

    def evict(self, k: int) -> list[int]:
        """Free up to ``k`` least-recently-used unreferenced LEAF pages
        in one pass (a parent whose last child evicts becomes a leaf and
        is eligible within the same call); returns the freed page ids.
        One resident scan amortized over the whole batch — the allocator
        asks for the full deficit at once instead of one page per retry."""
        heap = [
            (n.tick, n.page, n)
            for n in self._by_page.values()
            if n.refs == 0 and not n.children
        ]
        heapq.heapify(heap)
        freed: list[int] = []
        while heap and len(freed) < k:
            _, _, victim = heapq.heappop(heap)
            if self.spill is not None:
                # tiered demotion: the victim's bytes are still intact in
                # HBM (its page id hasn't been reused yet) — offer them
                # to the tier below before the trie forgets the chain
                self.spill(victim)
            del victim.parent.children[victim.block]
            del self._by_page[victim.page]
            self.stats["evictions"] += 1
            self.version += 1
            freed.append(victim.page)
            parent = victim.parent
            if (
                parent is not self.root
                and parent.refs == 0
                and not parent.children
            ):
                heapq.heappush(heap, (parent.tick, parent.page, parent))
        return freed

    def evict_one(self) -> int | None:
        """Free the least-recently-used unreferenced LEAF page; returns
        its physical page id (for the allocator's free-list) or None when
        nothing is evictable."""
        freed = self.evict(1)
        return freed[0] if freed else None

    def drop_all(self) -> list[int]:
        """Evict everything evictable (teardown): returns the freed page
        ids. Referenced pages stay — their slots still map them."""
        return self.evict(len(self._by_page))


def _paged_qkv(h, lp, cfg: ModelConfig, cos, sin):
    """Shared projection prologue of the paged blocks — q/k/v with
    biases, both qk-norm variants, and (partial-dim) rope. IDENTICAL math
    to transformer.py::_block's opening (the parity tests' anchor),
    generic over the ``[B, T, d]`` input so the decode step (S slots × 1
    token) and the prefill chunk (1 slot × C tokens) maintain ONE copy.
    A new model-family flag added to the dense block must land here once,
    not once per paged path."""
    B, T = h.shape[:2]
    ap = lp["attn"]
    q = _mm(h, ap["wq"])
    k = _mm(h, ap["wk"])
    v = _mm(h, ap["wv"])
    if "bq" in ap:
        q, k, v = q + ap["bq"], k + ap["bk"], v + ap["bv"]
    if cfg.qk_norm_full:
        q = _rms_head_norm(q, ap["q_norm"], cfg.norm_eps)
        k = _rms_head_norm(k, ap["k_norm"], cfg.norm_eps)
    # -1 head counts: under tensor parallelism the projections hold a
    # head-major-contiguous LOCAL slice, so the head axis is n/tp there
    # and the full n on the single-device path — same reshape either way
    q = q.reshape(B, T, -1, cfg.head_dim)
    k = k.reshape(B, T, -1, cfg.head_dim)
    v = v.reshape(B, T, -1, cfg.head_dim)
    if cfg.qk_norm:
        q = _rms_head_norm(q, ap["q_norm"], cfg.norm_eps)
        k = _rms_head_norm(k, ap["k_norm"], cfg.norm_eps)
    if cos is not None:
        rd = cos.shape[-1]
        if rd == cfg.head_dim:
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        else:
            q = jnp.concatenate(
                [apply_rope(q[..., :rd], cos, sin), q[..., rd:]], axis=-1
            )
            k = jnp.concatenate(
                [apply_rope(k[..., :rd], cos, sin), k[..., rd:]], axis=-1
            )
    return q, k, v


def _paged_residual(
    x, attn_raw, lp, cfg: ModelConfig,
    tp_axis: str | None = None, tp_quant: bool = False,
):
    """Shared epilogue: output projection (+bias) and the norm-position /
    parallel-residual wiring, identical to transformer.py::_block's
    closing. ``attn_raw`` is the attention output ``[B, T, Hq, hd]``.

    Under tensor parallelism ``attn_raw`` holds the LOCAL heads; the
    flattened head outputs gather to the full ``q_dim`` (head-major
    contiguous slices, so the flattened-axis concat IS the head-axis
    concat), wo produces LOCAL d_model columns (+ its local bias slice)
    and gathers back — the residual stream ``x`` is always FULL, so
    norms and residual adds are untouched by sharding."""
    B, T = attn_raw.shape[:2]
    ap = lp["attn"]
    attn_flat = _tp_gather(attn_raw.reshape(B, T, -1), tp_axis, tp_quant)
    attn_out = _mm(attn_flat, ap["wo"])
    if "bo" in ap:
        attn_out = attn_out + ap["bo"]
    attn_out = _tp_gather(attn_out, tp_axis, tp_quant)
    if cfg.norm_position == "post":
        x = x + _norm(attn_out, lp["ln1"], cfg)
        x = x + _norm(_mlp(x, lp["mlp"], cfg, tp_axis, tp_quant), lp["ln2"], cfg)
    elif cfg.parallel_residual:
        x = x + attn_out + _mlp(
            _norm(x, lp["ln2"], cfg), lp["mlp"], cfg, tp_axis, tp_quant
        )
    else:
        x = x + attn_out
        x = x + _mlp(_norm(x, lp["ln2"], cfg), lp["mlp"], cfg, tp_axis, tp_quant)
    return x


def _attn_scale(cfg: ModelConfig) -> float:
    return cfg.attn_scale if cfg.attn_scale is not None else cfg.head_dim**-0.5


def _ragged_write_indices(block_tables, starts, n_valid, page, n_pp, C):
    """Physical ``(page, offset)`` write targets for a ragged ``[S, C]``
    token block: position ``j`` of slot ``s`` lands at absolute position
    ``starts[s] + j`` when ``j < n_valid[s]``; every other (padding row,
    idle slot) write lands on the scratch page, unreachable from any
    block table. THE one page-write path: prefill-written and
    decode-written KV route through this same computation — a decode
    token is just the ``C = 1`` / ``n_valid = 1`` case (the clamp is
    belt-and-braces; the host evicts a slot before it reaches capacity).
    Also returns the uncapped absolute positions (the rope offsets) and
    the validity mask."""
    idx = jnp.arange(C)[None, :]
    pos = starts[:, None] + idx  # [S, C]
    valid = idx < n_valid[:, None]
    cpos = jnp.minimum(pos, n_pp * page - 1)
    pg = jnp.take_along_axis(block_tables, cpos // page, axis=1)
    write_pg = jnp.where(valid, pg, 0)
    write_off = jnp.where(valid, cpos % page, 0)
    return write_pg, write_off, pos, valid


def _cache_kv(cache: PagedKVCache) -> tuple:
    """One layer-stacked KV tuple for the decode scan — ``(k, v)`` plain,
    ``(k, v, k_scale, v_scale)`` in int8 mode; the blocks branch on the
    tuple arity (a trace-time constant)."""
    if cache.k_scale is None:
        return (cache.k, cache.v)
    return (cache.k, cache.v, cache.k_scale, cache.v_scale)


def _with_kv(cache: PagedKVCache, kv: tuple, **kw) -> PagedKVCache:
    """Rebuild the cache from a scan's stacked KV output (inverse of
    :func:`_cache_kv`)."""
    if len(kv) == 4:
        return replace(
            cache, k=kv[0], v=kv[1], k_scale=kv[2], v_scale=kv[3], **kw
        )
    return replace(cache, k=kv[0], v=kv[1], **kw)


# tlint: hot-path
def _scatter_kv(cache_kv: tuple, write_pg, write_off, k, v) -> tuple:
    """THE one page-write path's scatter: land this block's KV rows at
    their ``(page, offset)`` targets across every program. In quantized
    mode this is the single quantize site — each position's row quantizes
    independently (per-(position, head) scale over ``head_dim``,
    models/quant.py::quantize_kv — or ``quantize_kv4`` when the pages are
    PACKED int4, detected by the page dim being half the row's), which is
    exactly what keeps chunk framing, COW and promotion byte-exact under
    quantization. ``k``/``v`` are ``[..., Hkv, hd]`` with leading dims
    matching ``write_pg``."""
    if len(cache_kv) == 4:
        ck, cv, cks, cvs = cache_kv
        quant = _quant_kv4 if ck.shape[-1] != k.shape[-1] else _quant_kv
        k8, ks = quant(k)
        v8, vs = quant(v)
        ck = ck.at[write_pg, :, write_off].set(k8)
        cv = cv.at[write_pg, :, write_off].set(v8)
        cks = cks.at[write_pg, :, write_off].set(ks)
        cvs = cvs.at[write_pg, :, write_off].set(vs)
        return ck, cv, cks, cvs
    ck, cv = cache_kv
    ck = ck.at[write_pg, :, write_off].set(k.astype(ck.dtype))
    cv = cv.at[write_pg, :, write_off].set(v.astype(cv.dtype))
    return ck, cv


def _paged_block(x, lp, cfg: ModelConfig, cos, sin, cache_kv, write_pg,
                 write_off, att_len, block_tables, kernel: bool,
                 tp_axis: str | None = None, tp_quant: bool = False):
    """One transformer block over a slot batch of single tokens (T=1),
    reading/writing KV through pages. Mirrors transformer.py::_block's
    projection/norm/residual structure exactly (via the shared
    prologue/epilogue above) — the parity tests pin the two paths
    token-for-token — but swaps the contiguous-cache dynamic_update_slice
    for a flat page scatter and the masked einsum for paged attention."""
    h = x if cfg.norm_position == "post" else _norm(x, lp["ln1"], cfg)
    q, k, v = _paged_qkv(h, lp, cfg, cos, sin)  # [S, 1, H, hd]

    # per-slot scatter of the new token's KV through THE one write path
    # (quantizes in int8 mode); cache_kv is this layer's pages
    kv = _scatter_kv(cache_kv, write_pg, write_off, k[:, 0], v[:, 0])
    attn = paged_attention if kernel else paged_attention_ref
    if len(kv) == 4:
        attn_raw = attn(
            q[:, 0], kv[0], kv[1], block_tables, att_len,
            scale=_attn_scale(cfg), k_scale=kv[2], v_scale=kv[3],
        )[:, None]
    else:
        attn_raw = attn(
            q[:, 0], kv[0].astype(q.dtype), kv[1].astype(q.dtype),
            block_tables, att_len, scale=_attn_scale(cfg),
        )[:, None]  # [S, 1, Hq, hd]
    return _paged_residual(x, attn_raw, lp, cfg, tp_axis, tp_quant), kv


# tlint: hot-path
def _decode_step_impl(
    params,
    tok: jax.Array,
    cache: PagedKVCache,
    active: jax.Array,
    cfg: ModelConfig,
    kernel: bool,
    tp_axis: str | None = None,
    tp_quant: bool = False,
):
    """Unjitted body of :func:`paged_decode_step` — also traced inside
    the tensor-parallel shard_map (:func:`make_tp_ragged_step`), where
    ``tp_axis`` names the mesh axis the weights/KV-heads are split over
    and the blocks gather activations back to full width."""
    S = tok.shape[0]
    lengths = cache.lengths
    page = cache.page_size
    n_pp = cache.pages_per_slot
    # physical write position for each slot's new token via the shared
    # ragged write path (C=1, n_valid=active); free slots have a zeroed
    # block-table row and length 0 → scratch page 0
    write_pg, write_off, _, _ = _ragged_write_indices(
        cache.block_tables, lengths, active.astype(jnp.int32), page, n_pp, 1
    )
    write_pg = write_pg[:, 0]
    write_off = write_off[:, 0]
    att_len = jnp.where(active, lengths + 1, 0)

    x = _embed_tokens(params, tok[:, None], cfg)  # [S, 1, d]
    positions = lengths[:, None]
    if cfg.pos == "learned":
        x = x + params["embed"]["pos"][positions].astype(cfg.dtype)
    cos = sin = None
    if cfg.pos == "rope":
        cos, sin = rope_tables(positions, _rope_dim(cfg), cfg.rope_theta)

    def scan_fn(carry, xs):
        lp, ckv = xs[0], xs[1:]
        y, ckv = _paged_block(
            carry, lp, cfg, cos, sin, ckv, write_pg, write_off,
            att_len, cache.block_tables, kernel, tp_axis, tp_quant,
        )
        return y, ckv

    x, kv_new = jax.lax.scan(
        scan_fn, x, (params["layers"], *_cache_kv(cache))
    )
    x = _norm(x, params["final_norm"], cfg)
    logits = _logits(params, x, cfg, tp_axis, tp_quant)[:, 0]
    new_cache = _with_kv(
        cache, kv_new, lengths=jnp.where(active, lengths + 1, lengths)
    )
    return logits, new_cache


# tlint: hot-path  # tlint: one-program
@partial(
    jax.jit, static_argnames=("cfg", "kernel"), donate_argnames=("cache",)
)
def paged_decode_step(
    params,
    tok: jax.Array,  # int32 [S] — each slot's last token
    cache: PagedKVCache,
    active: jax.Array,  # bool [S] — slots holding a live request
    cfg: ModelConfig,
    kernel: bool = False,
):
    """ONE fixed-shape decode step over every serving slot. Returns
    ``(logits [S, V], cache)`` with each active slot's new KV written to
    its pages and its length advanced by one.

    This is the continuous-batching engine's only decode program: its
    shape depends on (max_slots, model) alone — never on the request mix —
    so the compiled set stays at exactly one entry per engine (asserted by
    tests/test_continuous.py). Free slots write their masked token to the
    scratch page and attend over nothing (length 0 → zero row)."""
    return _decode_step_impl(params, tok, cache, active, cfg, kernel)


def _decode_loop_body(params, seeds, temp, top_k, top_p, pres, freq, eos,
                      cfg: ModelConfig, kernel: bool,
                      tp_axis: str | None = None, tp_quant: bool = False):
    """The decode-continuation while_loop body of ``paged_ragged_step``
    (one fixed-shape slot decode step + in-program sampling per
    iteration). A slot that finishes mid-chunk (EOS / budget) freezes:
    its length stops advancing, it re-feeds its own token, and its
    per-slot key index stops — so the emitted stream is BIT-IDENTICAL to
    stepping one token at a time, which is what keeps the
    solo/co-batched/recovery parity contract intact. Tokens land at each
    slot's OWN column cursor (``col``): a speculating slot's verify pass
    may have emitted several tokens in the ragged block, so the
    continuation appends after them instead of at a shared step index
    (frozen slots re-write their token at a column the host never reads —
    delivery stops at the per-slot token count)."""
    from .continuous import _row_keys, _sample_rows

    S = seeds.shape[0]
    rows = jnp.arange(S)

    def body(st):
        i, tok, cache, done, steps, counts, remaining, col, tokens = st
        if tp_axis is None:
            logits, cache = paged_decode_step(
                params, tok, cache, ~done, cfg, kernel
            )
        else:  # already inside the TP shard_map — trace the body inline
            logits, cache = _decode_step_impl(
                params, tok, cache, ~done, cfg, kernel, tp_axis, tp_quant
            )
        keys = _row_keys(seeds, steps)
        nxt = _sample_rows(
            logits, keys, temp, top_k, top_p, pres, freq, counts
        )
        nxt = jnp.where(done, tok, nxt)  # frozen slots re-feed their token
        live = (~done).astype(jnp.int32)
        counts = counts.at[rows, nxt].add(live)
        steps = steps + live
        remaining = remaining - live
        done = done | (nxt[:, None] == eos).any(-1) | (remaining <= 0)
        tokens = tokens.at[
            rows, jnp.minimum(col, tokens.shape[1] - 1)
        ].set(nxt)
        return (
            i + 1, nxt, cache, done, steps, counts, remaining,
            col + live, tokens,
        )

    return body


# tlint: hot-path
def _verify_emit(blk, logits_v, base, n_spec, emit, seeds, steps, temp,
                 top_k, top_p, pres, freq, counts, remaining, eos):
    """The unified step's sampling epilogue, generalized to speculative
    verification — the in-program acceptance walk over each slot's
    gathered verification rows ``[S, W]``.

    Row ``j`` of a speculating slot holds the logits of block row
    ``base + j`` (absolute position ``start + base + j``) — the model's
    view AFTER the draft tokens up to that row were scattered — so the
    draw at row ``j`` with key ``fold_in(seed, steps + j)`` is EXACTLY
    the draw sequential decode would make at that step, provided every
    earlier draft matched its draw. The walk therefore accepts the
    longest prefix of drafts whose tokens equal their own-row draws and
    emits ONE extra token (the correction on a reject, the bonus draw
    when every draft matched), updating the penalty histogram, key index
    and budget per accepted token so the RNG/penalty state after the
    pass equals the sequential state bit-for-bit. A non-speculating slot
    (``n_spec == 0``) walks exactly one row — its last valid row — which
    reduces to the plain single-draw epilogue, token for token.

    Returns ``(tokens [S, W], last, m, ended, counts, steps, remaining)``
    where ``m`` is each slot's emitted count this pass (the verify-pass
    amortization the kill switch measures) and ``ended`` marks slots
    that hit EOS or their budget INSIDE the pass."""
    S, W, _V = logits_v.shape
    rows = jnp.arange(S)
    # the draft token draw j must match to be accepted: the NEXT packed
    # block row's token (clamped gather; masked by j < n_spec)
    j_idx = jnp.arange(W)[None, :]
    nxt_rows = jnp.clip(base[:, None] + j_idx + 1, 0, blk.shape[1] - 1)
    draft_next = jnp.take_along_axis(blk, nxt_rows, axis=1)  # [S, W]
    has_draft = j_idx < n_spec[:, None]  # [S, W]

    from .continuous import _row_keys, _sample_rows

    def vstep(carry, xs):
        counts, steps, remaining, stopped, ended, last, m = carry
        lg, dnext, hd = xs
        keys = _row_keys(seeds, steps)
        t = _sample_rows(lg, keys, temp, top_k, top_p, pres, freq, counts)
        live = emit & ~stopped
        liv32 = live.astype(jnp.int32)
        t = jnp.where(live, t, 0)
        counts = counts.at[rows, t].add(liv32)
        steps = steps + liv32
        remaining = remaining - liv32
        end_now = live & ((t[:, None] == eos).any(-1) | (remaining <= 0))
        # accept: this row's draw reproduced the next draft token, so the
        # already-scattered KV at that position is the TRUE token's KV
        # and the walk may trust the next row's logits
        accept = live & hd & (dnext == t) & ~end_now
        last = jnp.where(live, t, last)
        m = m + liv32
        ended = ended | end_now
        stopped = stopped | (live & ~accept)
        return (counts, steps, remaining, stopped, ended, last, m), t

    init = (
        counts, steps, remaining, ~emit, jnp.zeros_like(emit),
        jnp.zeros(S, jnp.int32), jnp.zeros(S, jnp.int32),
    )
    (counts, steps, remaining, _stopped, ended, last, m), toks = (
        jax.lax.scan(
            vstep, init,
            (logits_v.transpose(1, 0, 2), draft_next.T, has_draft.T),
        )
    )
    return toks.T, last, m, ended, counts, steps, remaining


def _ragged_block(x, lp, cfg: ModelConfig, cos, sin, cache_kv, write_pg,
                  write_off, block_tables, starts, n_valid, kernel: bool,
                  tp_axis: str | None = None, tp_quant: bool = False):
    """One transformer block over the ragged ``[S, C]`` token block,
    reading/writing KV through every slot's pages at once. Shares
    ``_paged_block``'s prologue/epilogue (scatter-then-attend order
    preserved) but carries the whole mixed prefill+decode block: a
    decode slot's single token and a mid-prefill slot's chunk go through
    the SAME projection, the SAME page scatter and the SAME ragged
    attention — the kernel-level erasure of the prefill/decode split."""
    h = x if cfg.norm_position == "post" else _norm(x, lp["ln1"], cfg)
    q, k, v = _paged_qkv(h, lp, cfg, cos, sin)  # [S, C, H, hd]

    # block scatter through the one write path (quantizes in int8 mode):
    # position (s, j) lands at (write_pg[s, j], write_off[s, j]); padding
    # rows and idle slots land on scratch page 0, unreachable from any
    # block table
    kv = _scatter_kv(cache_kv, write_pg, write_off, k, v)
    attn = ragged_paged_attention if kernel else ragged_paged_attention_ref
    if len(kv) == 4:
        attn_raw = attn(
            q, kv[0], kv[1], block_tables, starts, n_valid,
            scale=_attn_scale(cfg), k_scale=kv[2], v_scale=kv[3],
        )
    else:
        attn_raw = attn(
            q, kv[0].astype(q.dtype), kv[1].astype(q.dtype), block_tables,
            starts, n_valid, scale=_attn_scale(cfg),
        )  # [S, C, Hq, hd]
    return _paged_residual(x, attn_raw, lp, cfg, tp_axis, tp_quant), kv


# tlint: hot-path
def _ragged_step_impl(
    params, blk, cache, starts, n_valid, n_spec, emit, seeds, steps,
    temp, top_k, top_p, pres, freq, counts, remaining, eos,
    cfg: ModelConfig, n_steps: int, spec_width: int, kernel: bool,
    tp_axis: str | None = None, tp_quant: bool = False,
):
    """Unjitted body of :func:`paged_ragged_step` — also traced inside
    the tensor-parallel shard_map (:func:`make_tp_ragged_step`). There
    ``params`` holds head-major column slices, the per-layer KV pages
    hold the LOCAL kv heads (axis 2 of ``[L, P, n_kv, page, hd]``), and
    every control-state array (block tables, starts/n_valid, sampling
    knobs, histograms) is replicated — so the sampling epilogue sees
    gathered full-width logits and draws the SAME token on every
    shard."""
    S, C = blk.shape
    page = cache.page_size
    n_pp = cache.pages_per_slot
    bt = cache.block_tables
    write_pg, write_off, pos, _valid = _ragged_write_indices(
        bt, starts, n_valid, page, n_pp, C
    )

    x = _embed_tokens(params, blk, cfg)  # [S, C, d]
    positions = pos
    if cfg.pos == "learned":
        x = x + params["embed"]["pos"][positions].astype(cfg.dtype)
    cos = sin = None
    if cfg.pos == "rope":
        cos, sin = rope_tables(positions, _rope_dim(cfg), cfg.rope_theta)

    def scan_fn(carry, xs):
        lp, ckv = xs[0], xs[1:]
        y, ckv = _ragged_block(
            carry, lp, cfg, cos, sin, ckv, write_pg, write_off,
            bt, starts, n_valid, kernel, tp_axis, tp_quant,
        )
        return y, ckv

    x, kv_new = jax.lax.scan(
        scan_fn, x, (params["layers"], *_cache_kv(cache))
    )
    x = _norm(x, params["final_norm"], cfg)
    # verification rows: the last spec_width rows of each slot's valid
    # span — base = n_valid - 1 - n_spec, so a non-speculating slot
    # (n_spec 0: plain decode, completing prefill, idle) gathers exactly
    # its last valid row at walk index 0 and the epilogue reduces to the
    # plain single draw. The vocab head runs over [S, W] rows only —
    # never the whole [S, C] block (idle slots read row 0: garbage,
    # masked out of sampling by `emit`).
    W = int(spec_width)
    base = jnp.maximum(n_valid - 1 - n_spec, 0)
    gather = jnp.minimum(
        base[:, None] + jnp.arange(W)[None, :],
        jnp.maximum(n_valid - 1, 0)[:, None],
    )  # [S, W]
    h_v = x[jnp.arange(S)[:, None], gather]  # [S, W, d]
    logits_v = _logits(params, h_v, cfg, tp_axis, tp_quant)  # [S, W, V]

    toks0, nxt, spec_m, ended, counts, steps, remaining = _verify_emit(
        blk, logits_v, base, n_spec, emit, seeds, steps, temp, top_k,
        top_p, pres, freq, counts, remaining, eos,
    )
    done = ~emit | ended
    # KV unwind at the write seam: a speculating slot's length advances
    # only past its ACCEPTED tokens (spec_m includes the final
    # bonus/correction draw, which — like a plain decode's draw — is not
    # yet written); everything else keeps the full-block advance
    adv = jnp.where((n_spec > 0) & emit, spec_m, n_valid)
    cache = _with_kv(
        cache, kv_new,
        lengths=jnp.where(n_valid > 0, starts + adv, cache.lengths),
    )
    tokens = (
        jnp.zeros((S, n_steps + W - 1), jnp.int32).at[:, :W].set(toks0)
    )

    # decode continuation, starting past the ragged block's step, each
    # slot appending at its own column cursor (the verify pass emitted
    # spec_m tokens there)
    body = _decode_loop_body(
        params, seeds, temp, top_k, top_p, pres, freq, eos, cfg, kernel,
        tp_axis, tp_quant,
    )

    def cond(st):
        return (st[0] < n_steps) & ~st[3].all()

    init = (
        jnp.int32(1), nxt, cache, done, steps, counts, remaining,
        spec_m, tokens,
    )
    n_exec, _tok, cache, done, steps, counts, remaining, n_tok, tokens = (
        jax.lax.while_loop(cond, body, init)
    )
    return (
        tokens, n_tok, spec_m, n_exec, cache, done, steps, counts,
        remaining,
    )


# tlint: hot-path  # tlint: one-program
@partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "spec_width", "kernel"),
    donate_argnames=("cache", "counts"),
)
def paged_ragged_step(
    params,
    blk: jax.Array,  # int32 [S, C] — packed ragged token block (0-padded)
    cache: PagedKVCache,
    starts: jax.Array,  # int32 [S] — absolute position of blk[s, 0]
    n_valid: jax.Array,  # int32 [S] — valid tokens per slot (0 = idle)
    n_spec: jax.Array,  # int32 [S] — draft tokens per slot (rows 1..n_spec)
    emit: jax.Array,  # bool [S] — slot samples from its last valid row
    seeds: jax.Array,  # int32 [S] — per-slot RNG seeds
    steps: jax.Array,  # int32 [S] — per-slot next draw index
    temp: jax.Array,  # f32 [S] sampling knobs …
    top_k: jax.Array,  # int32 [S]
    top_p: jax.Array,  # f32 [S]
    pres: jax.Array,  # f32 [S]
    freq: jax.Array,  # f32 [S]
    counts: jax.Array,  # int32 [S, V] context histograms (penalties)
    remaining: jax.Array,  # int32 [S] — tokens still wanted per slot
    eos: jax.Array,  # int32 [S, E] per-slot EOS ids (pad with -1)
    cfg: ModelConfig,
    n_steps: int,
    spec_width: int = 1,
    kernel: bool = False,
):
    """THE serving hot loop's single compiled program: one ragged
    prefill+decode forward over the packed ``[S, C]`` token block, then
    up to ``n_steps - 1`` decode continuation steps in the same
    on-device while_loop — one host round trip per chunk, zero
    scheduling seams between prefilling and decoding slots.

    The packed block (assembled by the host-side
    ``engine/continuous.py::pack_prefill_budgets`` packing) carries every
    slot's role as DATA: a decode slot contributes its 1 current token at
    ``starts = length``, a mid-prefill slot its next prompt piece, an
    idle slot 0 tokens. Slots with ``emit`` set (decode slots, and
    prefills whose prompt completes in this block) sample their next
    token from their last valid row's logits with the request's own key
    chain and continue through the decode loop; mid-prefill slots
    that didn't finish stay frozen for the rest of the chunk and get
    their next grant at the next step boundary. One compiled program
    serves every (prefill/decode mix, prompt length, offset, budget
    split) — asserted in tests/test_continuous.py. With a quantized
    cache the same program stores int8 pages: the scatter quantizes,
    the kernels dequantize at the fetch.

    **Speculative slots** (``spec_width > 1``, docs/SERVING.md
    "Speculative decoding"): a decoding slot may pack up to
    ``spec_width - 1`` host-drafted tokens as EXTRA valid rows after its
    current token (``n_spec[s]`` of them, DATA like everything else —
    spec/non-spec mixes never recompile). The ragged forward then
    verifies all rows in-program (draft row ``j`` attends ``<= start +
    j`` — the kernel's existing causal ``q_pos`` masking, pinned bitwise
    against sequential decode in tests/test_ops.py), and the
    :func:`_verify_emit` walk accepts the longest draft prefix matching
    the slot's own fold_in draw chain plus one bonus/correction token —
    so speculative streams are bit-identical to plain decode. Rejected
    draft positions hold garbage KV that the length truncation below
    unwinds: ``lengths`` advances only past ACCEPTED tokens (write-then-
    truncate at the one ``_scatter_kv`` write seam — the int8
    payload+scales pairing and page conservation hold mid-rejection
    because the slot already owns every page it wrote), and the next
    pass overwrites the garbage before any mask can reach it.

    Returns ``(tokens [S, n_steps + spec_width - 1], n_tok [S], spec_m
    [S], n_exec, cache, done, steps, counts, remaining)``: per-slot
    token counts ``n_tok`` replace the old shared column convention
    (column 0..n_tok[s]-1 hold slot ``s``'s draws), and ``spec_m`` is
    the ragged pass's emitted count (the tokens-per-verify-pass signal
    the engine's kill switch consumes)."""
    return _ragged_step_impl(
        params, blk, cache, starts, n_valid, n_spec, emit, seeds, steps,
        temp, top_k, top_p, pres, freq, counts, remaining, eos,
        cfg, n_steps, spec_width, kernel,
    )


def tp_cache_specs(quantized: bool, axis: str = "tp") -> "PagedKVCache":
    """PartitionSpec pytree for a tensor-parallel :class:`PagedKVCache`:
    pages shard by kv head (axis 2 of ``[L, P, n_kv, page, hd]`` — the
    per-row int8 scales ``[L, P, n_kv, page]`` shard with them), while
    block tables and lengths REPLICATE. That replication is the
    control-state invariant (docs/SHARDING.md): the host-side scheduler,
    allocator, spec decode, and the export/stage/migrate path all read
    and write page indices and lengths exactly as on one device."""
    kv = P(None, None, axis)
    rep = P()
    return PagedKVCache(
        k=kv, v=kv, block_tables=rep, lengths=rep,
        k_scale=kv if quantized else None,
        v_scale=kv if quantized else None,
    )


# Compiled tensor-parallel ragged-step programs, keyed by every static
# that shapes the trace. Engines sharing (mesh, model, chunk geometry)
# share ONE program — churn in slots/requests/spec mixes never adds
# entries, which is what the per-shard-degree jit-cache guard in
# tests/test_tp.py pins.
# tlint: disable=TL006(append-only compiled-program registry, the TP analogue of a @jax.jit function's cache, bounded by hosted configs)
_TP_RAGGED_CACHE: dict = {}


def make_tp_ragged_step(
    mesh,
    cfg: ModelConfig,
    *,
    n_steps: int,
    spec_width: int = 1,
    kernel: bool = False,
    tp_quant: bool = False,
    axis: str = "tp",
):
    """Build (or fetch) THE tensor-parallel serving program: the ragged
    step body shard_mapped over ``mesh[axis]`` and jitted with the same
    donation discipline as :func:`paged_ragged_step`.

    Weights enter as head-major column slices (tp_partition_specs), KV
    pages as kv-head slices (:func:`tp_cache_specs`), everything else
    replicated; outputs mirror that layout, so the donated cache keeps
    its sharding across chunks. Call with the SAME positional arrays as
    ``paged_ragged_step`` minus the trailing statics (closed over
    here). ``tp_quant`` routes the per-chunk activation gathers through
    the int8 quantized collective (bounded divergence, opt-in via
    ModelConfig.collective_quant)."""
    key = (mesh, cfg, int(n_steps), int(spec_width), bool(kernel),
           bool(tp_quant), axis)
    hit = _TP_RAGGED_CACHE.get(key)
    if hit is not None:
        return hit
    from ..parallel.mesh import get_shard_map

    shard_map = get_shard_map()
    pspecs = tp_partition_specs(cfg, axis=axis)
    rep = P()

    def body(params, blk, cache, starts, n_valid, n_spec, emit, seeds,
             steps, temp, top_k, top_p, pres, freq, counts, remaining,
             eos):
        return _ragged_step_impl(
            params, blk, cache, starts, n_valid, n_spec, emit, seeds,
            steps, temp, top_k, top_p, pres, freq, counts, remaining,
            eos, cfg, n_steps, spec_width, kernel, axis, tp_quant,
        )

    def specs_for(quantized: bool):
        cspecs = tp_cache_specs(quantized, axis)
        in_specs = (pspecs, rep, cspecs) + (rep,) * 14
        out_specs = (rep, rep, rep, rep, cspecs, rep, rep, rep, rep)
        return in_specs, out_specs

    def build(quantized: bool):
        in_specs, out_specs = specs_for(quantized)
        return jax.jit(
            shard_map(
                body, mesh=mesh, in_specs=in_specs, out_specs=out_specs
            ),
            donate_argnums=(2, 14),  # cache, counts — as the 1-dev step
        )

    # int8-cache engines carry scale planes (a different cache pytree),
    # so the spec tree is chosen at first call by the cache's own arity
    plain, quant = build(False), build(True)

    def _canon(x):
        # Replicated control arrays reach the dispatcher with two
        # spellings of the same placement — P() from host-side
        # device_puts and rank-expanded P(None, ...) from jit/shard_map
        # outputs — and the jit cache keys on the spelling, not the
        # placement. Pin ONE canonical form (the rank-expanded one the
        # step's own outputs carry, so steady-state decode chunks pass
        # through untouched) to keep the hot loop at one program.
        want = NamedSharding(mesh, P(*([None] * x.ndim)))
        sh = getattr(x, "sharding", None)
        if isinstance(sh, NamedSharding) and sh == want:
            return x
        return jax.device_put(x, want)

    def step(params, blk, cache, *rest):
        fn = plain if cache.k_scale is None else quant
        bt = _canon(cache.block_tables)
        ln = _canon(cache.lengths)
        if bt is not cache.block_tables or ln is not cache.lengths:
            cache = replace(cache, block_tables=bt, lengths=ln)
        rest = list(rest)
        rest[11] = _canon(rest[11])  # counts (donated, like the cache)
        return fn(params, blk, cache, *rest)

    step._cache_size = lambda: (  # compile-count guard hook, summed
        plain._cache_size() + quant._cache_size()
    )
    _TP_RAGGED_CACHE[key] = step
    return step


# tlint: hot-path  # tlint: one-program
@partial(jax.jit, donate_argnames=("cache",))
def copy_page(
    cache: PagedKVCache, src: jax.Array, dst: jax.Array
) -> PagedKVCache:
    """Copy-on-write: duplicate a cached page's KV (every layer) into a
    page the admitting slot owns, so the slot can overwrite its tail
    without touching the shared original. In int8 mode the scale rows
    move with the payload — the copy is byte-exact, so a COW'd quantized
    page dequantizes to exactly what the original does."""
    out = replace(
        cache,
        k=cache.k.at[:, dst].set(cache.k[:, src]),
        v=cache.v.at[:, dst].set(cache.v[:, src]),
    )
    if cache.k_scale is not None:
        out = replace(
            out,
            k_scale=cache.k_scale.at[:, dst].set(cache.k_scale[:, src]),
            v_scale=cache.v_scale.at[:, dst].set(cache.v_scale[:, src]),
        )
    return out


# tlint: hot-path  # tlint: one-program
@jax.jit
def gather_page(cache: PagedKVCache, page: jax.Array) -> tuple:
    """Read one physical page's KV across every layer — the migration
    EXPORT device path. Returns ``(k, v)`` (``[L, n_kv, page, hd]``) or
    ``(k, v, k_scale, v_scale)`` in int8 mode. The bytes are the cache
    value itself (no dequantize, no cast), which is what makes a shipped
    page byte-exact on the destination: an adopted quantized page
    dequantizes to exactly what the source's kernels read."""
    if cache.k_scale is None:
        return cache.k[:, page], cache.v[:, page]
    return (
        cache.k[:, page], cache.v[:, page],
        cache.k_scale[:, page], cache.v_scale[:, page],
    )


# tlint: hot-path  # tlint: one-program
@partial(jax.jit, donate_argnames=("cache",))
def scatter_page(
    cache: PagedKVCache,
    page: jax.Array,
    k: jax.Array,
    v: jax.Array,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> PagedKVCache:
    """Write one shipped page's KV into a destination-owned physical page —
    the migration IMPORT device path (inverse of :func:`gather_page`,
    byte-exact; page shape is fixed, so any migration compiles this ONCE
    per engine mode regardless of how many pages move)."""
    out = replace(
        cache,
        k=cache.k.at[:, page].set(k),
        v=cache.v.at[:, page].set(v),
    )
    if k_scale is not None:
        out = replace(
            out,
            k_scale=cache.k_scale.at[:, page].set(k_scale),
            v_scale=cache.v_scale.at[:, page].set(v_scale),
        )
    return out


# tlint: hot-path  # tlint: one-program
@partial(jax.jit, donate_argnames=("cache",))
def bind_slot(
    cache: PagedKVCache, slot: jax.Array, bt_row: jax.Array, length: jax.Array
) -> PagedKVCache:
    """Point a slot at its allocated pages (admission)."""
    return replace(
        cache,
        block_tables=cache.block_tables.at[slot].set(bt_row),
        lengths=cache.lengths.at[slot].set(length),
    )


# tlint: hot-path  # tlint: one-program
@partial(jax.jit, donate_argnames=("cache",))
def clear_slot(cache: PagedKVCache, slot: jax.Array) -> PagedKVCache:
    """Detach an evicted slot: zero its table row (→ scratch page) and its
    length, so the fixed-shape step treats it as free. The pages
    themselves go back to the host free-list — their stale contents are
    unreachable once no table row names them."""
    return replace(
        cache,
        block_tables=cache.block_tables.at[slot].set(
            jnp.zeros((cache.pages_per_slot,), jnp.int32)
        ),
        lengths=cache.lengths.at[slot].set(0),
    )


def pages_needed(total_len: int, page_size: int) -> int:
    """Pages a request of ``total_len`` positions (prompt + budget, capped
    at the engine's max_seq_len) occupies."""
    return -(-int(total_len) // int(page_size))


__all__ = [
    "PagedKVCache",
    "PageAllocator",
    "PoolTenant",
    "PrefixCache",
    "SharedPagePool",
    "paged_decode_step",
    "paged_ragged_step",
    "make_tp_ragged_step",
    "tp_cache_specs",
    "copy_page",
    "gather_page",
    "scatter_page",
    "bind_slot",
    "clear_slot",
    "pages_needed",
]
