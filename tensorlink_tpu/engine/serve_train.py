"""Serve-and-train on one mesh (docs/TRAINING.md "Serve-and-train").

The north-star loop closer: a hosted model fine-tunes WHILE it serves.
:class:`ServeTrainLoop` owns a compiled train step (engine/training.py —
the zero1 step when the mesh has a dp axis; the zero1 × TP step when the
serving engine is tensor-parallel, in which case params flow through
training AS the serving shards and the publish below needs no relayout —
docs/SHARDING.md), its params/optimizer state,
and a data source; it attaches to a local :class:`ContinuousBatcher` as
the driver's background hook, so every train step runs ON the serving
driver thread BETWEEN engine chunks:

- **best_effort class**: each tick yields while the engine holds any
  live or queued request ranked above best_effort (the PR 4 scheduler's
  rank order — ``ContinuousEngine.foreground_work``), so an interactive
  arrival waits at most ONE train step, the same chunk-granularity bound
  preemption already gives. Co-resident best_effort serving interleaves
  with train steps chunk-by-chunk — exactly what its class promises.
- **live weight publish**: every ``publish_every`` steps the trained
  params hot-swap into the serving engine at the chunk boundary
  (``ContinuousEngine.publish_weights``) — double-buffered (the engine
  gets its OWN copy; the trainer's tree keeps being donated through
  later steps), versioned, zero dropped streams, zero new compiled
  programs on the serving hot path. ``on_publish`` lets the fleet layer
  propagate the version to sibling replicas
  (``FleetAutopilot.request_publish`` — replica-by-replica).
- **telemetry**: ``train_steps``/``weights_published`` counters and
  ``train_step_ms``/``train_mfu`` gauges ride the engine's registry and
  serving snapshot → /stats → /metrics; /healthz ``serving_modes``
  carries ``weights_version``.

Single-driver discipline is inherited, not negotiated: the tick runs on
the dispatcher thread, so ``publish_weights`` and the engine reads need
no locks or control-queue hops.
"""

from __future__ import annotations

import time
from typing import Any, Callable

from ..core.logging import get_logger


class ServeTrainLoop:
    """Background fine-tuning against a serving ContinuousBatcher.

    ``data_fn(step) -> batch | None`` supplies each step's batch (dict
    with "tokens" [B, T] and optional "loss_mask"); ``None`` ends the
    run. ``peak_flops`` (device peak, FLOP/s) makes the ``train_mfu``
    gauge meaningful; 0 reports 0.0. ``publish_every=0`` trains without
    publishing (an explicit ``publish_now()`` still works — e.g. one
    publish at end-of-run).
    """

    def __init__(
        self,
        batcher: Any,
        train_step: Any,  # engine.training.TrainStep
        params: Any,
        *,
        data_fn: Callable[[int], dict | None],
        opt_state: Any = None,
        publish_every: int = 0,
        max_steps: int = 0,
        peak_flops: float = 0.0,
        cfg: Any = None,  # ModelConfig, for the 6·N·B·T MFU estimate
        yield_above: str = "best_effort",
        on_publish: Callable[[int, Any], None] | None = None,
    ):
        if getattr(batcher, "_cont", None) is None:
            raise ValueError(
                "serve-and-train needs a local-engine ContinuousBatcher"
            )
        self.batcher = batcher
        self.train_step = train_step
        self.params = params
        self.opt_state = (
            opt_state if opt_state is not None
            else train_step.init_state(params)
        )
        self.data_fn = data_fn
        self.publish_every = int(publish_every)
        self.max_steps = int(max_steps)
        self.peak_flops = float(peak_flops)
        self.cfg = cfg
        self.yield_above = str(yield_above)
        self.on_publish = on_publish
        self.step = 0
        self.publishes = 0
        self.done = False
        self.last_loss = float("nan")
        self.last_step_ms = 0.0
        self.log = get_logger("engine.serve_train")

    # -- lifecycle -------------------------------------------------------
    def attach(self) -> "ServeTrainLoop":
        """Install the tick as the batcher's background hook."""
        self.batcher.set_background(self.tick)
        return self

    def detach(self) -> None:
        self.batcher.set_background(None)

    # -- the background tick (runs ON the serving driver thread) ---------
    def tick(self) -> bool:
        """Run at most one train step; True when a step ran (the driver
        keeps the loop hot). Yields — runs nothing — while the engine
        holds work ranked above ``yield_above``, or once done."""
        if self.done:
            return False
        cont = getattr(self.batcher, "_cont", None)
        if cont is None:
            self.done = True
            return False
        if cont.foreground_work(self.yield_above):
            return False
        batch = self.data_fn(self.step)
        if batch is None:
            self.done = True
            self.detach()
            return False
        import jax

        t0 = time.perf_counter()
        self.params, self.opt_state, metrics = self.train_step.step_fn(
            self.params, self.opt_state, batch
        )
        jax.block_until_ready(metrics["loss"])
        dt = time.perf_counter() - t0
        self.step += 1
        self.last_loss = float(metrics["loss"])
        self.last_step_ms = dt * 1e3
        mfu = 0.0
        if self.peak_flops > 0 and self.cfg is not None:
            toks = batch["tokens"]
            flops = 6.0 * self.cfg.param_count() * toks.shape[0] * toks.shape[1]
            mfu = flops / max(dt, 1e-9) / self.peak_flops
        cont.note_train_step(dt * 1e3, mfu)
        if self.max_steps and self.step >= self.max_steps:
            self.done = True
            self.detach()
        if self.publish_every and self.step % self.publish_every == 0:
            self.publish_now()
        return True

    def publish_now(self) -> int:
        """Hot-swap the CURRENT trained params into the serving engine.
        Driver-thread only (the tick calls it; external callers go
        through ``batcher.publish_weights``). The engine receives its
        own copy — the trainer's tree keeps being donated through later
        steps without invalidating what serves."""
        import jax
        import jax.numpy as jnp

        cont = getattr(self.batcher, "_cont", None)
        if cont is None:
            raise RuntimeError("serving engine is gone")
        staged = jax.tree.map(jnp.copy, self.params)
        version = cont.publish_weights(staged)
        self.publishes += 1
        self.log.info(
            "published weights v%d after train step %d (loss %.4f)",
            version, self.step, self.last_loss,
        )
        if self.on_publish is not None:
            try:
                self.on_publish(version, staged)
            except Exception:
                # fleet propagation is best-effort: the local replica is
                # already serving the new version; siblings retry via
                # the autopilot's own queue/history
                self.log.exception("on_publish propagation failed")
        return version


__all__ = ["ServeTrainLoop"]
