"""SLO-aware request scheduling for the continuous serving engine.

The mechanism layers below this one (PR 2's paged slot engine, PR 3's
prefix cache + chunked prefill) made admission, eviction and re-prefill
cheap; this module is the POLICY layer that decides *who* runs, *who*
waits, and *who* gets preempted. It owns the queued side of the request
lifecycle between the API/batcher front-ends and
:class:`~tensorlink_tpu.engine.continuous.ContinuousEngine`:

**Priority classes.** Every request carries one of three classes —
``interactive`` (chat turns, latency-sensitive), ``batch`` (bulk
summarization/eval traffic), ``best_effort`` (background fill). Classes
order admission: the queued request with the best *effective* rank wins
the next free slot, FIFO within a rank.

**Starvation-free aging.** A queued request's effective rank improves by
one class for every ``aging_ticks`` scheduler ticks it waits (one tick =
one admission round = one engine chunk), so sustained high-class load
can delay low-class work but never park it forever: an aged-to-rank-0
``best_effort`` request outranks every *newer* interactive arrival (FIFO
within rank) and — because preemption compares against the rank a
request held AT admission — cannot be preempted by them once running.
Admission consumes the credit: a preempted request re-queues with its
arrival order intact but its aging clock restarted (ticks spent running
are not ticks spent waiting).

**Cache-backed preemption.** When a request would otherwise miss
admission (no free slot, or the page allocator is dry even after prefix-
cache eviction), the scheduler may evict a running victim: the slot
whose admission-time rank is strictly worse than the candidate's,
worst-rank first, most-recently-admitted first within a rank. The engine
tears the victim's slot down through the normal eviction path — its
prefill-written pages are PROMOTED into the prefix cache — and the
request re-queues with its original arrival order (so it re-admits ahead
of its class peers). Resumption rides the exact crash-recovery contract
the engine already pins: re-prefill of prompt + emitted tokens (walking
the prefix cache, so the re-prefill is near-free while the pages stay
resident) and per-token keys ``fold_in(seed, n)`` stateless in n — a
preempted-then-resumed stream is bit-identical to an uninterrupted run.

**Bounded queues + backpressure.** Each class queue has a cap;
``admission_check`` reports (to the API layer, which turns it into a
``429`` + ``Retry-After``) when a class is at its cap or when the
estimated queue wait exceeds ``max_wait_s``. The estimate is queue depth
at-or-above the class's rank over observed per-request service time —
coarse, but honest enough for a Retry-After hint.

Telemetry (queue depth, queue-wait p50/p95, admissions, rejections,
preemptions, TTFT per class) flows ``ContinuousEngine.serving_snapshot()
→ ContinuousBatcher.stats() → validator /stats``, riding the same paths
the prefix-cache counters already use (including the ``GENERATE_RESP``
snapshot for remote-mode workers).
"""

from __future__ import annotations

import time
from collections import deque

from ..core.metrics import MetricsRegistry

PRIORITY_CLASSES = ("interactive", "batch", "best_effort")
# tlint: disable=TL006(constant derived from PRIORITY_CLASSES — read-only)
PRIORITY_RANK = {c: r for r, c in enumerate(PRIORITY_CLASSES)}
DEFAULT_PRIORITY = "interactive"


def normalize_priority(priority) -> str:
    """Clamp any caller-supplied value to a known class (unknown/empty →
    the default). The API layer validates loudly; internal paths must
    never crash on a stale field riding an old wire frame."""
    p = str(priority or "").strip().lower()
    return p if p in PRIORITY_RANK else DEFAULT_PRIORITY


class SchedulerOverloaded(RuntimeError):
    """A class queue is at its cap (the engine-side backstop behind the
    API layer's 429 gate). Carries what the 429 body needs."""

    def __init__(self, priority: str, depth: int, cap: int, retry_after: float):
        super().__init__(
            f"scheduler queue full for class {priority!r} "
            f"({depth}/{cap} queued; retry after ~{retry_after:.0f}s)"
        )
        self.priority = priority
        self.queue_depth = depth
        self.cap = cap
        self.retry_after = retry_after


def _percentile(samples, q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(int(round(q * (len(s) - 1))), len(s) - 1)
    return float(s[idx])


class _ClassStats:
    """Per-class typed counters (registry-backed — they ARE the /metrics
    series) + bounded sample windows for the exact-percentile snapshot
    keys the /stats contract pins (a fixed-bucket histogram would change
    the reported p50/p95 values, so the deques stay as the percentile
    source while the histograms feed /metrics)."""

    def __init__(self, cls: str, metrics: MetricsRegistry):
        self.admitted = metrics.counter(
            "tlink_sched_admitted_total", "requests admitted", cls=cls
        )
        self.rejected = metrics.counter(
            "tlink_sched_rejected_total",
            "requests rejected (queue cap / wait bound / drain fence)",
            cls=cls,
        )
        self.preempted = metrics.counter(
            "tlink_sched_preempted_total", "slots preempted and requeued",
            cls=cls,
        )
        self.queue_wait_hist = metrics.histogram(
            "tlink_sched_queue_wait_seconds",
            "submit-to-admission wait", cls=cls,
        )
        self.ttft_hist = metrics.histogram(
            "tlink_sched_ttft_seconds",
            "submit-to-first-token latency", cls=cls,
        )
        self.queue_waits: deque = deque(maxlen=512)
        self.ttfts: deque = deque(maxlen=512)

    def snapshot(self, depth: int) -> dict:
        return {
            "queue_depth": depth,
            "admitted": int(self.admitted.value),
            "rejected": int(self.rejected.value),
            "preempted": int(self.preempted.value),
            "queue_wait_ms_p50": round(
                _percentile(self.queue_waits, 0.50) * 1e3, 2
            ),
            "queue_wait_ms_p95": round(
                _percentile(self.queue_waits, 0.95) * 1e3, 2
            ),
            "ttft_ms_p50": round(_percentile(self.ttfts, 0.50) * 1e3, 2),
            "ttft_ms_p95": round(_percentile(self.ttfts, 0.95) * 1e3, 2),
        }


class RequestScheduler:
    """Priority/aging/preemption policy over the engine's queued requests.

    Thread-safety contract mirrors the engine's: mutation happens under
    the ENGINE's lock (``push`` from ``submit``, the rest from the
    single-driver admission loop) — this object adds no lock of its own.

    Queued entries are any objects carrying the fields the engine's
    :class:`~tensorlink_tpu.engine.continuous.ContinuousRequest` has:
    ``priority`` (class name), ``sched_seq`` (arrival order, assigned
    here), ``enqueue_tick`` / ``enqueue_t`` (assigned here),
    ``admit_rank`` (effective rank at admission, assigned here).
    """

    def __init__(
        self,
        *,
        max_slots: int,
        queue_cap: int = 64,
        aging_ticks: int = 32,
        preemption: bool = True,
        policy: str = "slo",
        max_wait_s: float = 60.0,
        metrics: MetricsRegistry | None = None,
    ):
        if policy not in ("slo", "fcfs"):
            raise ValueError(f"unknown scheduling policy {policy!r}")
        self.max_slots = max(int(max_slots), 1)
        self.queue_cap = max(int(queue_cap), 1)
        self.aging_ticks = max(int(aging_ticks), 1)
        self.preemption = bool(preemption) and policy == "slo"
        self.policy = policy
        self.max_wait_s = float(max_wait_s)
        # the queue + its stats are raced by client threads (submit /
        # admission_check / serving_snapshot) against the driver; every
        # touch happens with the ENGINE's lock held by the caller, so
        # touching methods carry `# tlint: holds-lock(the engine lock)`
        self._queued: list = []  #: guarded by the engine lock
        # drain fence (live slot migration, docs/FAILURE_MODEL.md): a
        # draining engine takes no new work — push fails fast and
        # admission_check rejects, so the drain loop never races fresh
        # arrivals while it sheds the live slots
        self.draining = False  #: guarded by the engine lock
        self._seq = 0
        self._admit_seq = 0  # admission order — victim-recency tiebreak
        self._tick = 0
        # EWMA of per-request service time (admit→finish wall seconds):
        # the unit the wait estimator scales queue depth by
        self._service_ewma = 0.0  #: guarded by the engine lock
        # typed counters/histograms (core/metrics.py): the engine shares
        # its registry so one /metrics render covers both layers; a
        # standalone scheduler (unit tests) gets its own
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.by_class = {  #: guarded by the engine lock
            c: _ClassStats(c, self.metrics) for c in PRIORITY_CLASSES
        }
        self.metrics.gauge(
            "tlink_sched_queue_depth", "queued (not yet admitted) requests",
            fn=lambda: len(self._queued),
        )
        self.metrics.gauge(
            "tlink_sched_service_ewma_seconds",
            "EWMA of per-request service time",
            fn=lambda: self._service_ewma,
        )

    # -- introspection ---------------------------------------------------
    # tlint: holds-lock(the engine lock)
    def __len__(self) -> int:
        return len(self._queued)

    # tlint: holds-lock(the engine lock)
    def pending(self) -> list:
        return list(self._queued)

    # tlint: holds-lock(the engine lock)
    def depth(self, priority: str | None = None) -> int:
        if priority is None:
            return len(self._queued)
        return sum(1 for r in self._queued if r.priority == priority)

    def effective_rank(self, req, tick: int | None = None) -> int:
        """Static class rank minus one per ``aging_ticks`` ticks waited,
        floored at 0 — the starvation-free ordering key."""
        if self.policy == "fcfs":
            return 0
        t = self._tick if tick is None else tick
        waited = max(t - req.enqueue_tick, 0)
        return max(PRIORITY_RANK[req.priority] - waited // self.aging_ticks, 0)

    # -- queue side ------------------------------------------------------
    # tlint: holds-lock(the engine lock)
    def push(self, req) -> None:
        """Enqueue; raises :class:`SchedulerOverloaded` past the class
        cap (the backstop — the API layer's admission_check normally
        rejects before the request gets this far)."""
        req.priority = normalize_priority(getattr(req, "priority", None))
        depth = self.depth(req.priority)
        if self.draining:
            # the admission fence: a draining engine is shedding its live
            # slots — new work must land on the destination instead
            self.by_class[req.priority].rejected.inc()
            raise SchedulerOverloaded(req.priority, depth, self.queue_cap, 1.0)
        if depth >= self.queue_cap:
            self.by_class[req.priority].rejected.inc()
            raise SchedulerOverloaded(
                req.priority, depth, self.queue_cap,
                self.estimate_wait(req.priority),
            )
        self._seq += 1
        req.sched_seq = self._seq
        req.enqueue_tick = self._tick
        req.enqueue_t = time.monotonic()
        self._queued.append(req)

    # tlint: holds-lock(the engine lock)
    def requeue(self, req) -> None:
        """Re-queue a PREEMPTED request: keeps its original arrival seq
        (so it re-admits ahead of class peers that arrived later) but
        RESTARTS its aging clock — admission consumed the queued-wait
        credit, and ticks spent RUNNING must not count as waiting, or a
        long-running victim would instantly outrank the very candidate
        it was preempted for and win the freed slot back (a futile
        teardown instead of a preemption). Never counts against the cap
        — the request was already admitted once."""
        req.enqueue_tick = self._tick
        req.enqueue_t = time.monotonic()
        self._queued.append(req)
        self.by_class[req.priority].preempted.inc()

    # tlint: holds-lock(the engine lock)
    def set_draining(self, draining: bool) -> None:
        """Raise/lower the drain admission fence (live slot migration —
        the engine's ``begin_drain`` flips this before shedding slots)."""
        self.draining = bool(draining)

    def tick(self) -> int:
        """One admission round has begun (the engine calls this once per
        chunk) — the aging clock."""
        self._tick += 1
        return self._tick

    # tlint: holds-lock(the engine lock)
    def select(self):
        """The queued request the next free slot should go to: best
        (effective rank, arrival seq). Returns None when idle. The caller
        admits it and then calls :meth:`remove` — selection does not pop,
        matching the engine's head-of-line page-wait retry shape."""
        if not self._queued:
            return None
        return min(
            self._queued,
            key=lambda r: (self.effective_rank(r), r.sched_seq),
        )

    # tlint: holds-lock(the engine lock)
    def remove(self, req) -> None:
        try:
            self._queued.remove(req)
        # tlint: disable=TL005(remove() is idempotent by contract — the head-of-line retry path re-removes)
        except ValueError:
            pass

    # tlint: holds-lock(the engine lock)
    def note_admitted(self, req) -> None:
        """Record admission: queue-wait sample, admission-time effective
        rank (the preemption shield — see :meth:`victim`), admission
        order (the victim-recency key — a re-admission gets a fresh seq,
        so "recently admitted" really means "least sunk work since its
        latest (re)admission")."""
        req.admit_rank = self.effective_rank(req)
        self._admit_seq += 1
        req.admit_seq = self._admit_seq
        st = self.by_class[req.priority]
        st.admitted.inc()
        wait = max(time.monotonic() - req.enqueue_t, 0.0)
        st.queue_waits.append(wait)
        st.queue_wait_hist.observe(wait)

    # tlint: holds-lock(the engine lock)
    def note_first_token(self, req, ttft_s: float) -> None:
        st = self.by_class[req.priority]
        ttft = max(float(ttft_s), 0.0)
        st.ttfts.append(ttft)
        st.ttft_hist.observe(ttft)

    # tlint: holds-lock(the engine lock)
    def note_finished(self, req, service_s: float) -> None:
        a = 0.2  # EWMA weight: a few requests settle the estimate
        s = max(float(service_s), 1e-3)
        self._service_ewma = (
            s if self._service_ewma == 0.0
            else (1 - a) * self._service_ewma + a * s
        )

    # -- preemption ------------------------------------------------------
    def victim(self, running: list, candidate) -> object | None:
        """Pick the running request ``candidate`` may preempt, or None.

        Eligible victims hold an ADMISSION-TIME rank strictly worse than
        the candidate's current effective rank — comparing against
        ``admit_rank`` (not the static class) means a request that aged
        its way into a slot keeps it, which is what makes aging a real
        no-starvation guarantee rather than a re-preemption treadmill.
        Among eligible victims: worst rank first, most-recently-ADMITTED
        first within a rank (the request whose latest (re)admission is
        newest has the least sunk decode work to re-prefill — arrival
        order says nothing about that, an early arrival may have just
        re-admitted).
        """
        if candidate is None:
            return None
        return self.victim_for_rank(running, self.effective_rank(candidate))

    # tlint: holds-lock(the engine lock)
    def victim_for_rank(self, running: list, cand_rank: int) -> object | None:
        """:meth:`victim` against an externally-computed candidate rank —
        how a co-hosted pool (engine/paged.py::SharedPagePool) applies
        THIS scheduler's admission-time-rank preemption shield to a
        candidate queued on ANOTHER tenant's scheduler: the rank value is
        the cross-model currency, the victim rules are unchanged."""
        if not self.preemption:
            return None
        eligible = [
            r for r in running
            if r is not None
            and getattr(r, "admit_rank", PRIORITY_RANK[r.priority]) > cand_rank
        ]
        if not eligible:
            return None
        return max(
            eligible,
            key=lambda r: (
                getattr(r, "admit_rank", PRIORITY_RANK[r.priority]),
                getattr(r, "admit_seq", r.sched_seq),
                r.sched_seq,
            ),
        )

    # -- backpressure ----------------------------------------------------
    # tlint: holds-lock(the engine lock)
    def estimate_wait(self, priority: str) -> float:
        """Rough seconds until a NEW request of this class would reach a
        slot: requests queued at-or-above its rank, over the slot count,
        times observed per-request service time. Zero when a slot is
        plausibly free now (the engine admits within one chunk)."""
        rank = PRIORITY_RANK[normalize_priority(priority)]
        ahead = sum(
            1 for r in self._queued if self.effective_rank(r) <= rank
        )
        if ahead == 0:
            return 0.0
        svc = self._service_ewma or 1.0
        return ahead / self.max_slots * svc

    # tlint: holds-lock(the engine lock)
    def admission_check(self, priority, n: int = 1) -> dict | None:
        """The API layer's backpressure gate: None = admit, else a
        rejection record ``{priority, queue_depth, cap, retry_after}``
        the server turns into ``429`` + ``Retry-After``. Rejects when the
        class queue cannot take ``n`` more, or when the estimated wait
        exceeds ``max_wait_s`` (0 disables the wait check)."""
        cls = normalize_priority(priority)
        depth = self.depth(cls)
        if self.draining:
            self.by_class[cls].rejected.inc(n)
            return {
                "priority": cls,
                "queue_depth": depth,
                "cap": self.queue_cap,
                "retry_after": 1.0,
                "draining": True,
            }
        est = self.estimate_wait(cls)
        if depth + n > self.queue_cap or (
            self.max_wait_s > 0 and est > self.max_wait_s
        ):
            self.by_class[cls].rejected.inc(n)
            return {
                "priority": cls,
                "queue_depth": depth,
                "cap": self.queue_cap,
                "retry_after": max(1.0, min(est, 600.0)),
            }
        return None

    # -- telemetry -------------------------------------------------------
    # tlint: holds-lock(the engine lock)
    def snapshot(self) -> dict:
        """Flat-ish JSON-safe counters for ``serving_snapshot()``."""
        classes = {
            c: st.snapshot(self.depth(c)) for c, st in self.by_class.items()
        }
        return {
            "sched_policy": self.policy,
            "sched_queue_depth": len(self._queued),
            "sched_preemptions": sum(
                int(st.preempted.value) for st in self.by_class.values()
            ),
            "sched_rejected": sum(
                int(st.rejected.value) for st in self.by_class.values()
            ),
            "sched_service_ewma_s": round(self._service_ewma, 4),
            "sched_classes": classes,
        }


__all__ = [
    "DEFAULT_PRIORITY",
    "PRIORITY_CLASSES",
    "PRIORITY_RANK",
    "RequestScheduler",
    "SchedulerOverloaded",
    "normalize_priority",
]
