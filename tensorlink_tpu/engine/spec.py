"""Shared speculative-decoding policy: prompt-lookup drafting + the
acceptance-rate kill switch.

ONE implementation feeds both decode paths so they cannot drift:

- the legacy B=1 greedy path (``engine/generate.py::generate_lookahead``,
  the ``{"lookahead": true}`` API hint) drafts with :func:`lookup_draft`
  and gates itself through a :class:`SpecController`;
- the continuous engine (``engine/continuous.py``) packs the same drafts
  as extra valid query rows of a decoding slot inside the unified ragged
  step (``engine/paged.py::paged_ragged_step``) and verifies them
  in-program — the ``MLConfig.spec_decode`` / per-request ``speculative``
  path.

The policy is the README's "never a slowdown" evidence (VERDICT r4/r5):
drafting is host-side and model-free (prompt-lookup n-grams — zero model
cost), so the ONLY way speculation loses is a padded verify pass whose
drafts keep missing or keep being rejected. Three guards close that:

- **prompt prescan**: prompt-lookup can only ever draft from a RECURRING
  n-gram, so a history with zero repeated adjacent token pairs starts
  with speculation off (re-armed on the first recurring pair when the
  generated text turns repetitive);
- **miss-run disarm**: :data:`MISS_OFF` consecutive draft misses mean
  the text is not repetitive — stop looking;
- **acceptance-rate kill switch**: after :data:`ACC_PROBE` verify passes
  a measured EMA acceptance below :data:`MIN_TOKENS_PER_PASS` tokens per
  pass cannot beat plain decode even if the padded pass were free — the
  request falls back to 1-token decode PERMANENTLY (the kill never
  re-probes; re-arming after a measured loss would reinstate the
  slowdown it stopped).
"""

from __future__ import annotations

from typing import Callable, Sequence

# draft search knobs (prompt-lookup n-gram matching)
NGRAM = 8
MIN_NGRAM = 2
HISTORY_SCAN_LIMIT = 4096  # bound the backward scan on long histories

# acceptance-rate kill switch (shared constants — the legacy path and
# the ragged path must fire at the same measured acceptance)
ACC_PROBE = 4  # verify passes before the acceptance EMA may kill
MIN_TOKENS_PER_PASS = 1.5  # below this, drafting cannot pay for itself
ACC_EMA = 0.5  # EMA weight on the newest pass

# a run of this many consecutive draft MISSES disarms speculation (the
# text isn't repetitive; a miss never produces a verify sample for the
# acceptance rule, so waiting for the kill switch would wait forever)
MISS_OFF = 8


def lookup_draft(
    history: Sequence[int], n_draft: int,
    ngram: int = NGRAM, min_ngram: int = MIN_NGRAM,
) -> list[int]:
    """Prompt-lookup drafting: if the trailing n-gram occurred earlier in
    the token history, propose the tokens that followed it. Free — no
    draft model; strong on repetitive/extractive text.

    Longest suffix first: an 8-gram match predicts the continuation far
    better than a 1-gram, and on a fixed-shape verify pass a longer draft
    costs nothing extra — so precision is the only lever. ``min_ngram=2``
    refuses single-token matches outright: "the occurred before" is
    noise, and every wrong draft still consumes a (padded) verify pass
    where a plain decode step would have done."""
    history = list(history)
    lo = max(0, len(history) - HISTORY_SCAN_LIMIT)
    for n in range(min(ngram, len(history) - 1), min_ngram - 1, -1):
        tail = history[-n:]
        # most recent earlier occurrence
        for start in range(len(history) - n - 1, lo - 1, -1):
            if history[start : start + n] == tail:
                nxt = history[start + n : start + n + n_draft]
                if nxt:
                    return nxt
                break
    return []


def spec_worthwhile(tokens_per_pass: float, t_verify: float,
                    t_decode: float) -> bool:
    """Speculation continues only while its measured throughput beats
    vanilla: tokens_per_pass/t_verify vs 1/t_decode. Pure so the
    break-even rule is unit-testable without wall-clock flakiness."""
    if t_verify <= 0 or t_decode <= 0:
        return True  # no signal yet
    return tokens_per_pass / t_verify >= 1.0 / t_decode


class SpecController:
    """Per-request drafting state machine (prescan / miss-run / re-arm /
    acceptance kill) shared by the legacy lookahead loop and the
    continuous engine's per-slot drafting.

    Lifecycle: :meth:`prescan` once over the initial history, then
    :meth:`draft` before every verify opportunity (it tracks misses and
    disarms itself), :meth:`note_pair` per emitted token (re-arms on
    recurring text when ``rearm``), :meth:`note_verify` after every
    verify pass (acceptance EMA + the permanent kill). ``draft_fn`` is
    injectable so the legacy engine's ``_lookup_draft`` staticmethod
    stays the override point its tests patch."""

    def __init__(
        self,
        n_draft: int = NGRAM,
        *,
        rearm: bool = True,
        draft_fn: Callable[..., list[int]] | None = None,
    ):
        self.n_draft = max(int(n_draft), 1)
        self._draft_fn = draft_fn or lookup_draft
        self._rearm = bool(rearm)
        self.on = True  # currently drafting (prescan/miss/kill can clear)
        self.dead = False  # kill switch fired: PERMANENT for the request
        self.miss_run = 0
        self.ema_acc: float | None = None
        self.verify_passes = 0
        # lifetime telemetry (the engine's spec_* counters read these)
        self.drafted = 0
        self.accepted = 0
        self._pairs: set[tuple[int, int]] = set()

    @property
    def active(self) -> bool:
        return self.on and not self.dead

    def prescan(self, history: Sequence[int]) -> bool:
        """Seed the adjacent-pair set from the initial history; a history
        with zero recurring pairs starts with speculation OFF (prompt-
        lookup could never draft from it). Returns the armed state."""
        rep = False
        hist = list(history)
        for a, b in zip(hist, hist[1:]):
            if (a, b) in self._pairs:
                rep = True
            else:
                self._pairs.add((a, b))
        if not rep:
            self.on = False
        return self.on

    def note_pair(self, a: int, b: int) -> None:
        """Observe one emitted-token transition. A RECURRING pair on a
        re-armable request switches drafting back on (the generated text
        became repetitive) — unless the kill switch already fired."""
        pr = (int(a), int(b))
        if pr in self._pairs:
            if not self.on and not self.dead and self._rearm:
                self.on = True
                self.miss_run = 0
        else:
            self._pairs.add(pr)

    def draft(self, history: Sequence[int], cap: int | None = None) -> list[int]:
        """Propose up to ``min(n_draft, cap)`` draft tokens, or [] on a
        miss (tracked: :data:`MISS_OFF` consecutive misses disarm). The
        ``drafted`` telemetry is NOT counted here — a caller packing
        under a draft budget may truncate or deny the proposal, so it
        credits ``drafted`` with what was actually GRANTED (the engine's
        ``_pack_drafts``; the legacy loop grants everything)."""
        if not self.active:
            return []
        k = self.n_draft if cap is None else min(int(cap), self.n_draft)
        if k <= 0:
            return []
        d = self._draft_fn(history, k)
        if not d:
            self.miss_run += 1
            if self.miss_run >= MISS_OFF:
                self.on = False
            return []
        self.miss_run = 0
        return d[:k]

    def note_verify(self, per_pass: int) -> bool:
        """Record one verify pass that emitted ``per_pass`` tokens
        (accepted drafts + the bonus/correction token). Returns True when
        this pass fired the PERMANENT acceptance-rate kill switch."""
        self.accepted += max(int(per_pass) - 1, 0)
        self.verify_passes += 1
        self.ema_acc = (
            float(per_pass) if self.ema_acc is None
            else ACC_EMA * float(per_pass) + (1 - ACC_EMA) * self.ema_acc
        )
        if (
            not self.dead
            and self.verify_passes >= ACC_PROBE
            and self.ema_acc < MIN_TOKENS_PER_PASS
        ):
            self.kill()
            return True
        return False

    def kill(self) -> None:
        """Disable speculation PERMANENTLY for this request (measured
        acceptance or a caller-side timing rule said it's a loss)."""
        self.on = False
        self.dead = True

    @property
    def tokens_per_pass(self) -> float | None:
        """Lifetime mean tokens emitted per verify pass (None before the
        first pass) — the amortization number the bench/metrics report."""
        if not self.verify_passes:
            return None
        return (self.accepted + self.verify_passes) / self.verify_passes


__all__ = [
    "ACC_PROBE",
    "MIN_TOKENS_PER_PASS",
    "MISS_OFF",
    "SpecController",
    "lookup_draft",
    "spec_worthwhile",
]
